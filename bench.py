"""Benchmark: whole-step-compiled GPT training throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

North-star-shaped (BASELINE.md: GPT-3 1.3B pretraining tokens/sec/chip):
trains the largest GPT config from the ladder below that fits one chip,
in AMP O2 (bf16 params + fp32 master weights, the reference's O2
semantics) with per-block recompute and the whole step (fwd+bwd+AdamW)
compiled to one XLA program.

Honest accounting:
- value     = tokens/sec on the real chip
- mfu       = value * model_flops_per_token / chip peak bf16 FLOPs
              (flops/token = 6N + 12*L*s*d: dense params fwd+bwd plus
              attention scores/values matmuls)
- vs_baseline = mfu / 0.40 — the anchor is a FLOPs-derived target (40%
  MFU, a strong single-chip GPT utilization), NOT a previous round's own
  measurement. vs_baseline >= 1.0 means the chip is doing >= 40% of its
  peak math on model FLOPs.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

# (name, d_model, n_layers, n_heads, seq, batch, opt_kwargs)
# 1.3B memory/MFU recipe (ablations in bench_profile.json):
# - Adam fp32 moments alone are 10.4GB; with bf16 params + fp32 master
#   that overflows 16GB HBM -> bf16 moments (fp32 compute in the rule)
#   + master-free stochastic-rounding updates cut state to 7.8GB
# - which lets the step run with NO activation recompute (full remat
#   costs an extra forward, ~25% of the step)
# - bf16 cross-entropy (fp32 accumulation inside the reductions) avoids
#   materializing the [b*s, 51200] fp32 logits copy
_FAST = {"moment_dtype": "bfloat16", "stochastic_rounding": True,
         "no_master": True, "remat": "none", "ce_bf16": True}
LADDER = [
    ("gpt3-1.3b", 2048, 24, 16, 1024, 4, dict(_FAST)),
    ("gpt-760m", 1536, 24, 16, 1024, 8, dict(_FAST)),
    ("gpt-350m", 1024, 24, 16, 1024, 8, dict(_FAST)),
]
VOCAB = 51200
PEAK_BF16 = {
    # chip device_kind substring -> peak bf16 FLOP/s
    "v5 lite": 197e12, "v5e": 197e12,
    "v5p": 459e12, "v4": 275e12, "v6": 918e12,
}
TARGET_MFU = 0.40


def _chip_peak(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for k, v in PEAK_BF16.items():
        if k in kind:
            return v
    return 197e12  # default: v5e


def build_model(d_model, n_layers, n_heads, seq, recompute=True,
                remat="full"):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    if remat == "dots":
        import jax

        remat_policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    else:
        remat_policy = None
    if remat == "none":
        recompute = False

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.ln1 = nn.LayerNorm(d_model)
            self.qkv = nn.Linear(d_model, 3 * d_model)
            self.proj = nn.Linear(d_model, d_model)
            self.ln2 = nn.LayerNorm(d_model)
            self.fc1 = nn.Linear(d_model, 4 * d_model)
            self.fc2 = nn.Linear(4 * d_model, d_model)

        def forward(self, x):
            b, s, _ = x.shape
            h = self.ln1(x)
            qkv = self.qkv(h).reshape(
                [b, s, 3, n_heads, d_model // n_heads])
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            att = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            x = x + self.proj(att.reshape([b, s, d_model]))
            return x + self.fc2(F.gelu(self.fc1(self.ln2(x))))

    class GPT(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(VOCAB, d_model)
            self.pos = nn.Embedding(seq, d_model)
            self.blocks = nn.LayerList([Block() for _ in range(n_layers)])
            self.norm = nn.LayerNorm(d_model)
            self.head = nn.Linear(d_model, VOCAB, bias_attr=False)

        def forward(self, ids, pos_ids):
            from paddle_tpu.distributed.fleet.recompute import recompute \
                as rc

            h = self.embed(ids) + self.pos(pos_ids)
            for blk in self.blocks:
                h = rc(blk, h, policy=remat_policy) if recompute else blk(h)
            return self.head(self.norm(h))

    return GPT()


def run_config(name, d_model, n_layers, n_heads, seq, batch, steps,
               opt_kwargs=None):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    opt_kwargs = dict(opt_kwargs or {})
    master = not opt_kwargs.pop("no_master", False)
    remat = opt_kwargs.pop("remat", "full")
    ce_bf16 = opt_kwargs.pop("ce_bf16", False)
    paddle.seed(0)
    model = build_model(d_model, n_layers, n_heads, seq, remat=remat)
    opt = paddle.optimizer.AdamW(
        1e-4, parameters=model.parameters(), weight_decay=0.01,
        **opt_kwargs)
    # AMP O2: bf16 params (norms stay fp32) + fp32 master weights
    model, opt = paddle.amp.decorate(model, opt, level="O2",
                                     dtype="bfloat16",
                                     master_weight=master)

    def loss_fn(logits, labels):
        # fp32 CE materializes a [b*s, 51200] fp32 logits copy (~1.7GB
        # at b8) — the bf16 path keeps logits in bf16 (log-softmax max-
        # subtraction is exact in bf16; the reduction accumulates fp32)
        flat = logits.reshape([-1, VOCAB])
        if not ce_bf16:
            flat = flat.astype("float32")
        return F.cross_entropy(flat, labels.reshape([-1]))

    step = paddle.jit.TrainStep(model, loss_fn, opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, VOCAB, (batch, seq)))
    pos = paddle.to_tensor(np.tile(np.arange(seq), (batch, 1)))
    labels = paddle.to_tensor(rng.randint(0, VOCAB, (batch, seq)))

    loss = step([ids, pos], [labels])  # compile
    _ = float(loss.numpy())

    # Timing: steps chain through the donated parameter buffers, and the
    # final scalar FETCH is what forces execution — on some transports
    # (e.g. tunneled PJRT) block_until_ready returns before the work is
    # done, which would time dispatch only.
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step([ids, pos], [labels])
    final = float(loss.numpy())
    dt = time.perf_counter() - t0
    if not np.isfinite(final):
        raise RuntimeError(f"{name}: non-finite loss")

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    tokens_per_sec = steps * batch * seq / dt
    flops_per_token = 6 * n_params + 12 * n_layers * seq * d_model
    return tokens_per_sec, n_params, flops_per_token


def run_decode_bench(batch=16, prompt=128, new_tokens=129,
                     d_model=2048, n_layers=24, n_heads=16,
                     decode_chunk=64):
    # Flagship-comparable serving rung (VERDICT r2 weak #3): the decode
    # model now matches the gpt3-1.3b training rung (d2048 L24,
    # head_dim 128 — the Pallas paged-attention lane-dim constraint),
    # so decode_tokens_per_sec is directly comparable to the training
    # headline. chunk=64 measured best through the tunneled chip: each
    # chunk is one device program + one host sync, amortizing the RPC
    # latency. new_tokens = 1 (prefill) + N*decode_chunk so the timed
    # run uses exactly the chunk programs the warmup compiled. batch 16
    # measured best (419 tok/s fp32-b8 -> 491 bf16-b8 -> 620 bf16-b16;
    # b32 regresses to 602 as KV reads saturate bandwidth).
    """Serving decode throughput: paged-KV greedy decode (Pallas paged
    attention on TPU, scan-chunked steps) through
    inference.GenerationEngine. Returns generated tokens/sec across the
    batch (decode phase only)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference import FusedCausalLM, GenerationEngine

    paddle.seed(0)
    model = FusedCausalLM(
        vocab_size=VOCAB, embed_dim=d_model, num_heads=n_heads,
        dim_feedforward=4 * d_model, num_layers=n_layers,
        max_position=prompt + new_tokens + 1)
    # serving-standard bf16 matmul weights (decode is weight-bandwidth
    # bound: the 1.3B fp32 stack alone is 5.7GB/step of HBM traffic);
    # LN params and the tied embedding (the scan-carry dtype anchor)
    # stay fp32
    st = model.stack
    for n in ("qkv_weight", "qkv_bias", "out_weight", "out_bias",
              "ffn1_weight", "ffn1_bias", "ffn2_weight", "ffn2_bias"):
        p = getattr(st, n)
        p._rebind(p._data.astype(jnp.bfloat16))
    engine = GenerationEngine(model, page_size=16,
                              max_length=prompt + new_tokens,
                              decode_chunk=decode_chunk)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, (batch, prompt))
    # warmup with the SAME token count: compiles prefill + every chunk-k
    engine.generate(ids, max_new_tokens=new_tokens)
    t0 = time.perf_counter()
    out = engine.generate(ids, max_new_tokens=new_tokens)
    dt = time.perf_counter() - t0
    assert out.shape == (batch, prompt + new_tokens)
    return batch * new_tokens / dt


def _run_one(name):
    """Run a single ladder rung (used in a fresh subprocess so a failed
    bigger config leaves no stale HBM buffers behind)."""
    import jax

    peak = _chip_peak(jax.devices()[0])
    cfg = [c for c in LADDER if c[0] == name][0]
    _, d, L, h, s, b, ok = cfg
    tps, n_params, fpt = run_config(name, d, L, h, s, b, steps=10,
                                    opt_kwargs=ok)
    from paddle_tpu.nn.functional.attention import last_attention_backend

    try:
        decode_tps = round(run_decode_bench(), 1)
    except Exception as e:  # secondary metric must not kill the headline
        decode_tps = f"failed: {e}"
    mfu = tps * fpt / peak
    print(json.dumps({
        "metric": "gpt_train_tokens_per_sec_tpu",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / TARGET_MFU, 3),
        "model": name,
        "n_params": n_params,
        "mfu": round(mfu, 4),
        "target_mfu": TARGET_MFU,
        "attention_backend": last_attention_backend(),
        "amp": "O2-bf16",
        "optimizer_state": ("bf16-moments+stochastic-rounding"
                            if cfg[6].get("stochastic_rounding")
                            else ("bf16-moments+fp32-master"
                                  if cfg[6].get("moment_dtype")
                                  else "fp32")),
        "cross_entropy": "bf16-logits-fp32-acc" if cfg[6].get("ce_bf16")
        else "fp32",
        "remat": cfg[6].get("remat", "full"),
        "decode_tokens_per_sec": decode_tps,
    }))


def main():
    if "--config" in sys.argv:
        _run_one(sys.argv[sys.argv.index("--config") + 1])
        return

    import jax

    if jax.default_backend() != "tpu":
        # CPU smoke config (CI): tiny model, correctness of the path only
        tps, n_params, fpt = run_config("gpt-smoke", 128, 2, 4, 256, 2, 2)
        print(json.dumps({
            "metric": "gpt_train_tokens_per_sec_cpu", "value": round(tps, 1),
            "unit": "tokens/s", "vs_baseline": 1.0, "model": "gpt-smoke",
        }))
        return

    import os
    import subprocess

    for (name, *_rest) in LADDER:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--config", name],
            capture_output=True, text=True, timeout=3000)
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("{")]
        if proc.returncode == 0 and lines:
            print(lines[-1])
            return
        print(f"bench: {name} failed (rc={proc.returncode}): "
              f"{proc.stderr[-300:]}", file=sys.stderr)
    raise SystemExit("bench: all ladder configs failed")


if __name__ == "__main__":
    main()
