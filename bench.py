"""Benchmark: whole-step-compiled GPT training throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

North-star-shaped (BASELINE.md: GPT-3 1.3B pretraining tokens/sec/chip):
trains the largest GPT config from the ladder below that fits one chip,
in AMP O2 (bf16 params + fp32 master weights, the reference's O2
semantics) with per-block recompute and the whole step (fwd+bwd+AdamW)
compiled to one XLA program.

Honest accounting:
- value     = tokens/sec on the real chip
- mfu       = value * model_flops_per_token / chip peak bf16 FLOPs
              (flops/token = 6N + 12*L*s*d: dense params fwd+bwd plus
              attention scores/values matmuls)
- vs_baseline = mfu / 0.40 — the anchor is a FLOPs-derived target (40%
  MFU, a strong single-chip GPT utilization), NOT a previous round's own
  measurement. vs_baseline >= 1.0 means the chip is doing >= 40% of its
  peak math on model FLOPs.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

# (name, d_model, n_layers, n_heads, seq, batch, opt_kwargs)
# 1.3B memory/MFU recipe (ablations in bench_profile.json):
# - Adam fp32 moments alone are 10.4GB; with bf16 params + fp32 master
#   that overflows 16GB HBM -> bf16 moments (fp32 compute in the rule)
#   + master-free stochastic-rounding updates cut state to 7.8GB
# - which lets the step run with NO activation recompute (full remat
#   costs an extra forward, ~25% of the step)
# - bf16 cross-entropy (fp32 accumulation inside the reductions) avoids
#   materializing the [b*s, 51200] fp32 logits copy
_FAST = {"moment_dtype": "bfloat16", "stochastic_rounding": True,
         "no_master": True, "remat": "none", "ce_bf16": True}
LADDER = [
    ("gpt3-1.3b", 2048, 24, 16, 1024, 4, dict(_FAST)),
    ("gpt-760m", 1536, 24, 16, 1024, 8, dict(_FAST)),
    ("gpt-350m", 1024, 24, 16, 1024, 8, dict(_FAST)),
]
# canonical GPT-3 1.3B context (BASELINE configs[3]): same tokens/step
# as the s1024 rung (b*s = 4096); reported as the s2048_* keys
S2048 = ("gpt3-1.3b-s2048", 2048, 24, 16, 2048, 2, dict(_FAST))
VOCAB = 51200
PEAK_BF16 = {
    # chip device_kind substring -> peak bf16 FLOP/s
    "v5 lite": 197e12, "v5e": 197e12,
    "v5p": 459e12, "v4": 275e12, "v6": 918e12,
}
TARGET_MFU = 0.40


def _chip_peak(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for k, v in PEAK_BF16.items():
        if k in kind:
            return v
    return 197e12  # default: v5e


def _telemetry():
    """Runtime-telemetry block embedded into BENCH_*.json: the
    profiler.stats registry snapshot for THIS process (per-op dispatch
    counts, VJP-cache/jit-cache outcomes, compile-time histograms, pool
    gauges) plus the per-program cost-model roofline table. Each rung
    runs in its own subprocess, so the block describes exactly that
    rung's work."""
    from paddle_tpu.profiler import roofline, stats

    snap = stats.snapshot()
    ops = {k: v for k, v in snap["counters"].items()
           if k.startswith("op.")}
    out = {
        "op_calls_top": dict(sorted(ops.items(),
                                    key=lambda kv: -kv[1])[:20]),
        "counters": {k: v for k, v in snap["counters"].items()
                     if not k.startswith("op.")},
        "gauges": snap["gauges"],
        "histograms": snap["histograms"],
    }
    hr = stats.vjp_cache_hit_rate()
    if hr is not None:
        out["vjp_cache_hit_rate"] = round(hr, 4)
    rl = roofline.report()
    if rl:
        out["roofline"] = rl
    return out


def build_model(d_model, n_layers, n_heads, seq, recompute=True,
                remat="full"):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    if remat == "dots":
        import jax

        remat_policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    else:
        remat_policy = None
    if remat == "none":
        recompute = False

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.ln1 = nn.LayerNorm(d_model)
            self.qkv = nn.Linear(d_model, 3 * d_model)
            self.proj = nn.Linear(d_model, d_model)
            self.ln2 = nn.LayerNorm(d_model)
            self.fc1 = nn.Linear(d_model, 4 * d_model)
            self.fc2 = nn.Linear(4 * d_model, d_model)

        def forward(self, x):
            b, s, _ = x.shape
            h = self.ln1(x)
            qkv = self.qkv(h).reshape(
                [b, s, 3, n_heads, d_model // n_heads])
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            att = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            x = x + self.proj(att.reshape([b, s, d_model]))
            return x + self.fc2(F.gelu(self.fc1(self.ln2(x))))

    class GPT(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(VOCAB, d_model)
            self.pos = nn.Embedding(seq, d_model)
            self.blocks = nn.LayerList([Block() for _ in range(n_layers)])
            self.norm = nn.LayerNorm(d_model)
            self.head = nn.Linear(d_model, VOCAB, bias_attr=False)

        def forward(self, ids, pos_ids):
            from paddle_tpu.distributed.fleet.recompute import recompute \
                as rc

            h = self.embed(ids) + self.pos(pos_ids)
            for blk in self.blocks:
                h = rc(blk, h, policy=remat_policy) if recompute else blk(h)
            return self.head(self.norm(h))

    return GPT()


def run_config(name, d_model, n_layers, n_heads, seq, batch, steps,
               opt_kwargs=None):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    opt_kwargs = dict(opt_kwargs or {})
    master = not opt_kwargs.pop("no_master", False)
    remat = opt_kwargs.pop("remat", "full")
    ce_bf16 = opt_kwargs.pop("ce_bf16", False)
    paddle.seed(0)
    model = build_model(d_model, n_layers, n_heads, seq, remat=remat)
    opt = paddle.optimizer.AdamW(
        1e-4, parameters=model.parameters(), weight_decay=0.01,
        **opt_kwargs)
    # AMP O2: bf16 params (norms stay fp32) + fp32 master weights
    model, opt = paddle.amp.decorate(model, opt, level="O2",
                                     dtype="bfloat16",
                                     master_weight=master)

    def loss_fn(logits, labels):
        # fp32 CE materializes a [b*s, 51200] fp32 logits copy (~1.7GB
        # at b8) — the bf16 path keeps logits in bf16 (log-softmax max-
        # subtraction is exact in bf16; the reduction accumulates fp32)
        flat = logits.reshape([-1, VOCAB])
        if not ce_bf16:
            flat = flat.astype("float32")
        return F.cross_entropy(flat, labels.reshape([-1]))

    step = paddle.jit.TrainStep(model, loss_fn, opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, VOCAB, (batch, seq)))
    pos = paddle.to_tensor(np.tile(np.arange(seq), (batch, 1)))
    labels = paddle.to_tensor(rng.randint(0, VOCAB, (batch, seq)))

    loss = step([ids, pos], [labels])  # compile
    _ = float(loss.numpy())

    # Timing: steps chain through the donated parameter buffers, and the
    # final scalar FETCH is what forces execution — on some transports
    # (e.g. tunneled PJRT) block_until_ready returns before the work is
    # done, which would time dispatch only. Two windows, best-of: the
    # first window can absorb host-settling noise right after heavy CPU
    # work (measured a ~20% dip that vanished on re-run).
    dt = float("inf")
    for _window in range(2):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step([ids, pos], [labels])
        final = float(loss.numpy())
        dt = min(dt, time.perf_counter() - t0)
    if not np.isfinite(final):
        raise RuntimeError(f"{name}: non-finite loss")

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    tokens_per_sec = steps * batch * seq / dt
    flops_per_token = 6 * n_params + 12 * n_layers * seq * d_model
    # cost-model roofline for the compiled step (XLA's own flops/bytes
    # accounting, not the 6N+12Lsd estimate), from the honestly timed
    # best window — printed per program instead of a hand-waved %
    rl = step.roofline(dt / steps)
    roofline = rl.as_dict() if rl is not None else None
    if rl is not None:
        print(rl.format(), file=sys.stderr)
    return tokens_per_sec, n_params, flops_per_token, roofline


HBM_BW = {
    # chip device_kind substring -> HBM bytes/s (decode roofline
    # denominator, detected like _chip_peak)
    "v5 lite": 819e9, "v5e": 819e9,
    "v5p": 2765e9, "v4": 1228e9, "v6": 1640e9,
}


def _chip_hbm_bw(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for k, v in HBM_BW.items():
        if k in kind:
            return v
    return 819e9  # default: v5e


def run_decode_bench(batch=32, prompt=128, new_tokens=129,
                     d_model=2048, n_layers=24, n_heads=16,
                     decode_chunk=None, quant=None, kv_dtype=None,
                     mp_degree=None):
    # Flagship-comparable serving rung: the decode model matches the
    # gpt3-1.3b training rung (d2048 L24). Round-4 redesign (each step
    # diagnosed in tools/decode_profile.py + HLO inspection):
    # - layer-FOLDED paged pool updated IN PLACE via fori_loop carry
    #   (the r3 scan xs->ys shuttle copied the whole pool every token:
    #   10.8ms/step of pure copy)
    # - XLA gather attention (the stock Pallas kernel imposes a cache
    #   layout the page scatter hates -> 2 full-pool layout copies per
    #   layer per token; measured 220 tok/s vs 1662)
    # - bf16 compute end-to-end + pre-transposed bf16 lm head with fp32
    #   accumulation; KV pool bf16
    # - batch 32 measured best (b16: 1662, b32: 2504, b64 regresses as
    #   KV gather reads outgrow the weight-stream amortization)
    # - decode_chunk: engine auto-picks 128 (one scan program for the
    #   whole generation: chunk-boundary pool relayout + host sync
    #   amortize; 64 -> 128 measured +7%)
    # - quant="int8" additionally halves weight reads via per-channel
    #   weight-only int8 (scales applied on matmul outputs)
    # - quant="a8w8" also quantizes ACTIVATIONS per token into
    #   int8 x int8 MXU matmuls with one accumulator dequant — removes
    #   the bf16-activation dequant round from the streamed weights
    """Serving decode throughput through inference.GenerationEngine
    (greedy, scan-chunked). Returns (tokens/sec, % of the HBM
    weight-bandwidth roofline).

    TPU targets for the next real-chip run (VERDICT r5 round-4 bar):
    int8/a8w8 decode >= 1.6x bf16 decode tokens/sec, and bf16 b32
    >= 50% of the weight-bandwidth roofline — the a8w8 rung exists
    precisely to close the int8 gap (weight-only int8 measured just
    1.18x bf16 because the skinny matmuls still computed bf16)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference import FusedCausalLM, GenerationEngine

    paddle.seed(0)
    model = FusedCausalLM(
        vocab_size=VOCAB, embed_dim=d_model, num_heads=n_heads,
        dim_feedforward=4 * d_model, num_layers=n_layers,
        max_position=prompt + new_tokens + 1)
    st = model.stack
    for n in ("qkv_weight", "qkv_bias", "out_weight", "out_bias",
              "ffn1_weight", "ffn1_bias", "ffn2_weight", "ffn2_bias"):
        p = getattr(st, n)
        p._rebind(p._data.astype(jnp.bfloat16))
    engine = GenerationEngine(model, page_size=16,
                              max_length=prompt + new_tokens,
                              decode_chunk=decode_chunk,
                              kv_dtype=kv_dtype, quant=quant,
                              mp_degree=mp_degree)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, (batch, prompt))
    # warmup with the SAME token count: compiles prefill + every chunk-k
    engine.generate(ids, max_new_tokens=new_tokens)
    t0 = time.perf_counter()
    out = engine.generate(ids, max_new_tokens=new_tokens)
    dt = time.perf_counter() - t0
    assert out.shape == (batch, prompt + new_tokens)
    tps = batch * new_tokens / dt
    # honest roofline: every decode step must read the full weight
    # stream (stack + lm head) once from HBM; tokens/step = batch.
    # Under TP each chip streams only its 1/mp stack slice (the lm
    # head stays replicated), so the per-chip weight floor shrinks
    # accordingly — mp1-throughput preservation is gated on the
    # EXISTING rungs, this roofline is the per-chip TP bar.
    mp = mp_degree or 1
    weight_bytes = sum(
        int(np.prod(a.shape)) * a.dtype.itemsize
        for a in st._stack().values()) / mp + \
        int(np.prod(engine._head_t.shape)) * engine._head_t.dtype.itemsize
    import jax

    roofline_tps = batch * _chip_hbm_bw(jax.devices()[0]) / weight_bytes
    # cost-model roofline: the decode/prefill programs recorded XLA's
    # flops/bytes at compile time and the engine analyzed each synced
    # decode chunk, so this block carries MEASURED achieved bytes/s and
    # bandwidth utilization per program (vs the analytic weight-stream
    # % above, which only counts weight reads)
    from paddle_tpu.profiler import roofline as _rl

    cost_roofline = {k: v for k, v in _rl.report().items()
                     if k.startswith(("decode", "prefill"))}
    return tps, round(100 * tps / roofline_tps, 1), cost_roofline


def run_decode_spec_bench(batch=8, prompt=128, new_tokens=128,
                          d_model=2048, n_layers=24, n_heads=16,
                          spec_k=4):
    """Speculative-decoding amortization rung (ISSUE 12): the SAME
    greedy workload through ContinuousBatchingEngine twice — plain
    token-by-token decode, then speculative with a ScheduledDrafter
    replaying the recorded greedy streams (accept rate 1.0 by
    construction: the acceptance CEILING, isolating pure verify
    amortization — one streamed pass per k+1 tokens instead of per
    token). Returns (tps_spec, tps_plain, accept_rate, rounds).
    Greedy parity between the two runs is asserted, not assumed."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference import (ContinuousBatchingEngine,
                                      FusedCausalLM, ScheduledDrafter)
    from paddle_tpu.profiler import stats

    def build_model():
        paddle.seed(0)
        model = FusedCausalLM(
            vocab_size=VOCAB, embed_dim=d_model, num_heads=n_heads,
            dim_feedforward=4 * d_model, num_layers=n_layers,
            max_position=prompt + new_tokens + 1)
        st = model.stack
        for n in ("qkv_weight", "qkv_bias", "out_weight", "out_bias",
                  "ffn1_weight", "ffn1_bias", "ffn2_weight",
                  "ffn2_bias"):
            p = getattr(st, n)
            p._rebind(p._data.astype(jnp.bfloat16))
        return model

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, VOCAB, (prompt,)) for _ in range(batch)]

    def drive(engine):
        rids = [engine.submit(p, max_new_tokens=new_tokens)
                for p in prompts]
        t0 = time.perf_counter()
        engine.run()
        dt = time.perf_counter() - t0
        by = {r.id: list(r.generated) for r in engine.finished}
        return dt, [by[r] for r in rids]

    kw = dict(max_batch=batch, page_size=16,
              max_length=prompt + new_tokens)
    plain = ContinuousBatchingEngine(build_model(), **kw)
    drive(plain)                      # warmup: compiles live here
    dt_plain, streams = drive(plain)

    expected = {np.asarray(p, np.int32).tobytes(): s
                for p, s in zip(prompts, streams)}
    drafter = ScheduledDrafter(
        lambda req: expected[np.asarray(req.prompt).tobytes()])
    spec = ContinuousBatchingEngine(
        build_model(), speculative=drafter, spec_k=spec_k, **kw)
    drive(spec)                       # warmup
    stats.reset()
    dt_spec, spec_streams = drive(spec)
    if spec_streams != streams:
        raise RuntimeError(
            "decode-spec rung: speculative tokens diverged from the "
            "plain greedy streams (parity violation)")
    drafted = stats.counter("serving.spec_drafted_tokens").value
    accepted = stats.counter("serving.spec_accepted_tokens").value
    rounds = stats.counter("serving.spec_rounds").value
    total = sum(len(s) for s in streams)
    return (total / dt_spec, total / dt_plain,
            (accepted / drafted) if drafted else None, int(rounds))


def build_moe_model(d_model, n_layers, n_heads, seq, num_experts,
                    top_k=2):
    """GPT with the dense FFN replaced by a NO-DROP MoELayer
    (capacity_factor=None → the ragged grouped-GEMM path, ISSUE 15)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.incubate.moe import MoELayer

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.ln1 = nn.LayerNorm(d_model)
            self.qkv = nn.Linear(d_model, 3 * d_model)
            self.proj = nn.Linear(d_model, d_model)
            self.ln2 = nn.LayerNorm(d_model)
            self.moe = MoELayer(d_model, num_experts=num_experts,
                                gate="gshard", top_k=top_k,
                                d_hidden=4 * d_model,
                                capacity_factor=None)

        def forward(self, x):
            b, s, _ = x.shape
            h = self.ln1(x)
            qkv = self.qkv(h).reshape(
                [b, s, 3, n_heads, d_model // n_heads])
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            att = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            x = x + self.proj(att.reshape([b, s, d_model]))
            return x + self.moe(self.ln2(x))

    class GPTMoE(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(VOCAB, d_model)
            self.pos = nn.Embedding(seq, d_model)
            self.blocks = nn.LayerList([Block() for _ in range(n_layers)])
            self.norm = nn.LayerNorm(d_model)
            self.head = nn.Linear(d_model, VOCAB, bias_attr=False)

        def forward(self, ids, pos_ids):
            h = self.embed(ids) + self.pos(pos_ids)
            for blk in self.blocks:
                h = blk(h)
            return self.head(self.norm(h))

    return GPTMoE()


def run_moe_train_bench(d_model, n_layers, n_heads, seq, batch,
                        num_experts, top_k=2, steps=8):
    """No-drop MoE training rung: whole-step-compiled GPT-MoE, AMP O2.
    Returns (tokens/s, mfu, activated params, total params). MFU
    charges the ACTIVATED FLOPs (dense params + top_k/E of the expert
    FFN bank) — the honest MoE utilization accounting."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.profiler import stats as _stats

    paddle.seed(0)
    model = build_moe_model(d_model, n_layers, n_heads, seq,
                            num_experts, top_k)
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                                 weight_decay=0.01,
                                 moment_dtype="bfloat16")
    model, opt = paddle.amp.decorate(model, opt, level="O2",
                                     dtype="bfloat16")

    def loss_fn(logits, labels):
        return F.cross_entropy(logits.reshape([-1, VOCAB]),
                               labels.reshape([-1]))

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, VOCAB, (batch, seq)))
    pos = paddle.to_tensor(np.tile(np.arange(seq), (batch, 1)))
    labels = paddle.to_tensor(rng.randint(0, VOCAB, (batch, seq)))

    # one EAGER forward first: stamps the data-dependent moe.* routing
    # telemetry (tokens_per_expert / imbalance / dropped_tokens) that
    # the traced step cannot — then assert the no-drop pin held
    drop0 = _stats.counter("moe.dropped_tokens").value
    model(ids, pos)
    if _stats.counter("moe.dropped_tokens").value != drop0:
        raise RuntimeError("moe-train rung: no-drop mode dropped "
                           "tokens (moe.dropped_tokens moved)")

    loss = step([ids, pos], [labels])  # compile
    _ = float(loss.numpy())
    t0 = time.perf_counter()
    for _i in range(steps):
        loss = step([ids, pos], [labels])
    final = float(loss.numpy())
    dt = time.perf_counter() - t0
    if not np.isfinite(final):
        raise RuntimeError("moe-train rung: non-finite loss")
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # expert FFN bank: E * (w1 + b1 + w2 + b2) per block; only top_k/E
    # of it is activated per token
    dff = 4 * d_model
    bank = n_layers * num_experts * (2 * d_model * dff + dff + d_model)
    n_active = n_params - bank + bank * top_k // num_experts
    tps = steps * batch * seq / dt
    flops_per_token = 6 * n_active + 12 * n_layers * seq * d_model
    mfu = tps * flops_per_token / _chip_peak(jax.devices()[0])
    return tps, round(mfu, 4), n_active, n_params


def run_moe_decode_bench(batch=32, prompt=128, new_tokens=65,
                         d_model=1024, n_layers=12, n_heads=16,
                         num_experts=8, top_k=2, ep_degree=None):
    """MoE serving decode rung: FusedCausalLM with the expert-bank FFN
    through GenerationEngine (the no-drop ragged MoE FFN per layer).
    Returns (tokens/s, total stack params)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference import FusedCausalLM, GenerationEngine

    paddle.seed(0)
    model = FusedCausalLM(
        vocab_size=VOCAB, embed_dim=d_model, num_heads=n_heads,
        dim_feedforward=4 * d_model, num_layers=n_layers,
        max_position=prompt + new_tokens + 1,
        moe_num_experts=num_experts, moe_top_k=top_k)
    st = model.stack
    for n, p in st.named_parameters():
        if "weight" in n or n.startswith(("moe_w", "gate")):
            p._rebind(p._data.astype(jnp.bfloat16))
    engine = GenerationEngine(model, page_size=16,
                              max_length=prompt + new_tokens,
                              ep_degree=ep_degree)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, (batch, prompt))
    engine.generate(ids, max_new_tokens=new_tokens)   # warmup/compile
    t0 = time.perf_counter()
    out = engine.generate(ids, max_new_tokens=new_tokens)
    dt = time.perf_counter() - t0
    assert out.shape == (batch, prompt + new_tokens)
    n_params = sum(int(np.prod(p.shape)) for _n, p in
                   st.named_parameters())
    return batch * new_tokens / dt, n_params


def run_bert_bench(batch=32, seq=512, steps=8):
    """BERT-base pretraining rung (BASELINE configs[2]): MLM+NSP whole-
    step compiled, AMP O2 bf16, single chip. Returns (tokens/s, mfu).
    batch 32 re-validated after the r5 RNG/CE fixes: b64 only paid when
    threefry dropout + gather-CE dominated the step (they amortize with
    batch); with hardware-RBG dropout masks and the fused closed-form
    CE, b32 measures 90.7k tok/s vs b64's 79.9k (tools/bert_profile)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.text.models import (BertForPretraining,
                                        BertPretrainingCriterion,
                                        bert_base)

    paddle.seed(0)
    # attention-probs dropout off → flash attention path (the modern
    # BERT recipe; dropout inside attention forces a materialized
    # [b,h,s,s] softmax that cost 6x: MFU 0.09 -> see BENCH_r04)
    model = BertForPretraining(
        bert_base(max_position_embeddings=seq,
                  attention_probs_dropout_prob=0.0))
    crit = BertPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                                 weight_decay=0.01,
                                 moment_dtype="bfloat16")
    model, opt = paddle.amp.decorate(model, opt, level="O2",
                                     dtype="bfloat16")
    step = paddle.jit.TrainStep(model, crit, opt)

    rng = np.random.RandomState(0)
    vocab = 30522
    ids = paddle.to_tensor(rng.randint(0, vocab, (batch, seq)))
    types = paddle.to_tensor(rng.randint(0, 2, (batch, seq)))
    mlm = paddle.to_tensor(np.where(
        rng.rand(batch, seq) < 0.15,
        rng.randint(0, vocab, (batch, seq)), -100))
    nsp = paddle.to_tensor(rng.randint(0, 2, (batch,)))
    # full-length sequences → no attention_mask → flash path (an
    # all-ones mask is a bias operand that blocks the flash kernel)
    args, labels = [ids, types], [mlm, nsp]

    loss = step(args, labels)  # compile
    _ = float(loss.numpy())
    t0 = time.perf_counter()
    for _i in range(steps):
        loss = step(args, labels)
    final = float(loss.numpy())
    dt = time.perf_counter() - t0
    if not np.isfinite(final):
        raise RuntimeError("bert bench: non-finite loss")
    n_params = sum(int(np.prod(p.shape))
                   for _n, p in model.named_parameters())
    tps = steps * batch * seq / dt
    d_model, n_layers = 768, 12
    flops_per_token = 6 * n_params + 12 * n_layers * seq * d_model
    mfu = tps * flops_per_token / _chip_peak(jax.devices()[0])
    rl = step.roofline(dt / steps)
    if rl is not None:
        print(rl.format(), file=sys.stderr)
    return tps, round(mfu, 4), (rl.as_dict() if rl else None)


def run_attn_varlen_bench():
    """Varlen flash-attention rung (ISSUE 13): a long packed batch
    through the segment-aware block-skipping kernel
    (nn/functional/flash_varlen.py). Returns (tokens/s,
    peak_bytes, total_tokens, backend). ``peak_bytes`` is the compiled
    program's argument+temp+output footprint from XLA's memory
    analysis — the number that was O(T²) on the dense path (a 32k-token
    pack would need a 64 GiB [h, T, T] fp32 intermediate; the varlen
    path stays O(T·d)). Gated by bench_gate: tokens/s regresses DOWN,
    peak bytes UP."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.flash_varlen import (
        flash_varlen_packed)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        h, d, dtype = 16, 128, jnp.bfloat16
        lens, iters = [4096] * 8, 20          # T = 32768 packed
    else:
        # CPU smoke: correctness of the rung plumbing only
        h, d, dtype = 2, 64, jnp.float32
        lens, iters = [512] * 4, 3
    T = int(sum(lens))
    cu = jnp.asarray(np.concatenate([[0], np.cumsum(lens)])
                     .astype(np.int32))
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(T, h, d), dtype)

    fn = jax.jit(lambda q, k, v, cu: flash_varlen_packed(
        q, k, v, cu, cu, causal=True))
    fn(q, q, q, cu).block_until_ready()       # compile outside timing
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn(q, q, q, cu)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    if not np.isfinite(np.asarray(out[:8], np.float32)).all():
        raise RuntimeError("attn-varlen bench: non-finite output")
    peak = None
    try:
        mem = fn.lower(q, q, q, cu).compile().memory_analysis()
        peak = int(mem.temp_size_in_bytes + mem.argument_size_in_bytes
                   + mem.output_size_in_bytes)
    except Exception:
        pass
    backend = "pallas" if on_tpu else "xla"
    return iters * T / dt, peak, T, backend


def _run_one(name):
    """Run a single ladder rung (used in a fresh subprocess so a failed
    bigger config leaves no stale HBM buffers behind)."""
    import jax

    peak = _chip_peak(jax.devices()[0])
    cfg = [c for c in LADDER if c[0] == name][0]
    _, d, L, h, s, b, ok = cfg
    tps, n_params, fpt, roofline = run_config(name, d, L, h, s, b,
                                              steps=10, opt_kwargs=ok)
    from paddle_tpu.nn.functional.attention import last_attention_backend

    mfu = tps * fpt / peak
    print(json.dumps({
        "metric": "gpt_train_tokens_per_sec_tpu",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / TARGET_MFU, 3),
        "model": name,
        "n_params": n_params,
        "mfu": round(mfu, 4),
        "roofline": roofline,
        "target_mfu": TARGET_MFU,
        "attention_backend": last_attention_backend(),
        "amp": "O2-bf16",
        "optimizer_state": ("bf16-moments+stochastic-rounding"
                            if cfg[6].get("stochastic_rounding")
                            else ("bf16-moments+fp32-master"
                                  if cfg[6].get("moment_dtype")
                                  else "fp32")),
        "cross_entropy": "bf16-logits-fp32-acc" if cfg[6].get("ce_bf16")
        else "fp32",
        "remat": cfg[6].get("remat", "full"),
        "telemetry": _telemetry(),
    }))


def _run_secondary(kind):
    """One serving/model rung in THIS process (spawned fresh by main so
    the training rung's HBM is fully released first)."""
    if kind == "--decode":
        tps, pct, cost_rl = run_decode_bench()
        print(json.dumps({"decode_tokens_per_sec": round(tps, 1),
                          "decode_batch": 32,
                          "decode_pct_of_hbm_roofline": pct,
                          "decode_roofline": cost_rl,
                          "decode_telemetry": _telemetry()}))
    elif kind == "--decode-int8":
        tps, pct, cost_rl = run_decode_bench(quant="int8")
        print(json.dumps({"decode_int8_tokens_per_sec": round(tps, 1),
                          "decode_int8_pct_of_hbm_roofline": pct,
                          "decode_int8_roofline": cost_rl}))
    elif kind == "--decode-a8w8":
        # full A8W8: dynamic per-token int8 activations into the
        # int8 x int8 streamed matmuls (the rung that must land the
        # >=1.6x-bf16 target the weight-only rung missed)
        tps, pct, cost_rl = run_decode_bench(quant="a8w8")
        print(json.dumps({"decode_a8w8_tokens_per_sec": round(tps, 1),
                          "decode_a8w8_pct_of_hbm_roofline": pct,
                          "decode_a8w8_roofline": cost_rl,
                          "decode_a8w8_telemetry": _telemetry()}))
    elif kind == "--decode-bf16-grouped":
        # GROUPED bf16 weight-stream decode (FLAGS_decode_grouped on +
        # cross-layer prefetch): the fused O+LN2+FFN tail kernel plus
        # in-tail next-layer QKV — ONE streamed call per layer.
        # TPU targets for the next chip run (ISSUE r6 / VERDICT r5 #1):
        #   - >= 50% of the weight-bandwidth roofline (vs 35% r5)
        #   - >= ~5,000 tok/s at b32 bf16 (vs 3,490 r5)
        #   - weights_only_grouped ablation <= 5 ms/step (vs 10.9 ms
        #     against the 2.9 ms weight-read floor)
        # gated by tools/bench_gate.py (direction "down").
        import paddle_tpu as _p

        _p.set_flags({"decode_grouped": "on", "decode_prefetch": True})
        tps, pct, cost_rl = run_decode_bench()
        print(json.dumps(
            {"decode_bf16_grouped_tokens_per_sec": round(tps, 1),
             "decode_bf16_grouped_pct_of_hbm_roofline": pct,
             "decode_bf16_grouped_roofline": cost_rl,
             "decode_bf16_grouped_telemetry": _telemetry()}))
    elif kind == "--decode-tp":
        # TENSOR-PARALLEL decode rung (ISSUE 10): the mp-sharded
        # FusedMultiTransformer over every available chip — per-chip
        # weight streams shrink to 1/mp, two psums per layer ride the
        # ICI. The roofline denominator is the PER-CHIP weight slice,
        # so the target stays the same >=50%-of-weight-roofline bar as
        # the single-chip grouped rung; mp1 throughput preservation is
        # gated by bench_gate on the existing decode_* rungs, which
        # this change leaves untouched.
        import jax

        n = len(jax.devices())
        if n < 2:
            print(json.dumps({"decode_tp_skipped":
                              f"needs >= 2 devices, have {n}"}))
            return
        mp = 1 << (n.bit_length() - 1)  # largest power of two <= n
        tps, pct, cost_rl = run_decode_bench(mp_degree=mp)
        print(json.dumps(
            {f"decode_tp{mp}_tokens_per_sec": round(tps, 1),
             f"decode_tp{mp}_pct_of_hbm_roofline": pct,
             "decode_tp_mp_degree": mp,
             "decode_tp_roofline": cost_rl,
             "decode_tp_telemetry": _telemetry()}))
    elif kind == "--decode-tp-overlap":
        # ring-overlap TP decode rung (ISSUE 19): the SAME mp2 decode
        # workload with FLAGS_tp_overlap=ring — each layer's two
        # reduce seams run as chunked ppermute rings interleaved with
        # the chunk GEMMs instead of one blocking psum, so the ICI
        # hop hides behind the weight-stream math. Keys are pinned to
        # tp2 (the ring's win shrinks as P outgrows the interconnect
        # depth; tp2 is the shape the S-OVERLAP census pins). Gated
        # by bench_gate: tokens/s DOWN. CPU runs a tiny geometry —
        # rung plumbing + parity only; the XLA fallback mirrors the
        # ring op-for-op so the numbers are chip-only signal.
        import jax

        n = len(jax.devices())
        if n < 2:
            print(json.dumps({"decode_tp2_overlap_skipped":
                              f"needs >= 2 devices, have {n}"}))
            return
        import paddle_tpu as _p

        _p.set_flags({"tp_overlap": "ring"})
        if jax.default_backend() == "tpu":
            tps, pct, cost_rl = run_decode_bench(mp_degree=2)
        else:
            tps, pct, cost_rl = run_decode_bench(
                batch=2, prompt=16, new_tokens=9, d_model=64,
                n_layers=2, n_heads=4, mp_degree=2)
        print(json.dumps(
            {"decode_tp2_overlap_tokens_per_sec": round(tps, 1),
             "decode_tp2_overlap_pct_of_hbm_roofline": pct,
             "decode_tp2_overlap_roofline": cost_rl,
             "decode_tp2_overlap_telemetry": _telemetry()}))
    elif kind == "--moe-decode-ep-overlap":
        # double-buffered EP decode rung (ISSUE 19): the MoE decode
        # workload at ep2 with FLAGS_ep_overlap on — the all_to_all
        # exchange splits into two half-capacity buffers so dispatch1
        # rides the ICI while expert FFN0 runs. Gated by bench_gate:
        # tokens/s DOWN.
        import jax

        n = len(jax.devices())
        if n < 2:
            print(json.dumps({"moe_decode_ep2_overlap_skipped":
                              f"needs >= 2 devices, have {n}"}))
            return
        import paddle_tpu as _p

        _p.set_flags({"ep_overlap": True})
        if jax.default_backend() == "tpu":
            tps, n_params = run_moe_decode_bench(ep_degree=2)
        else:
            tps, n_params = run_moe_decode_bench(
                batch=2, prompt=16, new_tokens=9, d_model=64,
                n_layers=2, n_heads=4, num_experts=4, ep_degree=2)
        print(json.dumps(
            {"moe_decode_ep2_overlap_tokens_per_sec": round(tps, 1),
             "moe_decode_ep2_overlap_params": n_params,
             "moe_decode_ep2_overlap_telemetry": _telemetry()}))
    elif kind == "--fleet":
        # fleet serving rung with the decode-concurrent drain (ISSUE
        # 19): serve_bench --fleet 2 --drain-async — replica 0 drains
        # mid-load under FLAGS_migrate_async, pages stream while both
        # endpoints keep decoding (fleet_* + fleet_async_migration_*
        # keys; gate: decode tokens DOWN, stall-ms UP).
        import os
        import subprocess

        import jax

        tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "serve_bench.py")
        argv = [sys.executable, tool, "--no-lint", "--seed", "0",
                "--streams", "4", "--fleet", "2", "--drain-async"]
        if jax.default_backend() == "tpu":
            argv += ["--d-model", "2048", "--layers", "24", "--heads",
                     "16", "--vocab", "51200", "--bf16",
                     "--prompt-mix", "128,512,1024",
                     "--prefill-chunk", "256", "--max-new", "64",
                     "--page-size", "16", "--rate", "64"]
        else:
            argv += ["--max-new", "24", "--rate", "200"]
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=1200)
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("{")]
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"serve_bench --fleet --drain-async "
                f"rc={proc.returncode}: {proc.stderr[-300:]}")
        print(lines[-1])
    elif kind == "--fleet-disagg":
        # disaggregated prefill/decode rung (ISSUE 20): serve_bench
        # --fleet 2 --disagg drives the same prefill-heavy skewed
        # workload symmetric-then-disaggregated and pins disagg <=
        # symmetric TTFT p99 with goodput held (serve_disagg_* +
        # fleet_spill_* keys; gate: TTFT UP = regression, goodput /
        # tokens_per_sec DOWN = regression). TPU targets (v5e-8, 2
        # replicas, prompt mix 2048,8192,16384, rate 32):
        # serve_disagg_p99_ttft_ms <= 0.7 * fleet_p99_ttft_ms with
        # serve_disagg_tokens_per_sec >= 0.95 * fleet_tokens_per_sec.
        import os
        import subprocess

        import jax

        tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "serve_bench.py")
        argv = [sys.executable, tool, "--no-lint", "--seed", "0",
                "--streams", "8", "--fleet", "2", "--disagg"]
        if jax.default_backend() == "tpu":
            argv += ["--d-model", "2048", "--layers", "24", "--heads",
                     "16", "--vocab", "51200", "--bf16",
                     "--prompt-mix", "2048,8192,16384",
                     "--prefill-chunk", "256", "--max-new", "64",
                     "--page-size", "16", "--rate", "32"]
        else:
            # 24 requests / 64 decode tokens: enough decode-SLO
            # pressure that the symmetric fleet's interleave tax
            # shows, enough TTFT samples that the rep-median p99
            # holds against shared-core scheduling noise
            argv += ["--max-new", "64", "--rate", "200"]
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=1200)
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("{")]
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"serve_bench --fleet --disagg "
                f"rc={proc.returncode}: {proc.stderr[-300:]}")
        print(lines[-1])
    elif kind == "--decode-spec":
        # speculative decoding at the acceptance ceiling (ISSUE 12):
        # replayed-greedy drafts -> accept rate 1.0, so the rung
        # measures pure verify amortization — the weight stack read
        # once per (k+1)-token window. Parity is asserted inside.
        # TPU target (ROADMAP item 1): decode_spec_vs_plain >= 1.5
        # on this acceptance-friendly workload, gated by bench_gate.
        # CPU runs (CI) get a tiny geometry — correctness/parity of
        # the rung only; the 1.3B numbers come from the chip.
        import jax

        if jax.default_backend() == "tpu":
            tps, tps_plain, rate, rounds = run_decode_spec_bench()
        else:
            tps, tps_plain, rate, rounds = run_decode_spec_bench(
                batch=2, prompt=16, new_tokens=16, d_model=64,
                n_layers=2, n_heads=4)
        print(json.dumps(
            {"decode_spec_tokens_per_sec": round(tps, 1),
             "decode_spec_plain_tokens_per_sec": round(tps_plain, 1),
             "decode_spec_vs_plain": round(tps / tps_plain, 3)
             if tps_plain else None,
             "decode_spec_accept_rate": rate,
             "decode_spec_rounds": rounds,
             "decode_spec_telemetry": _telemetry()}))
    elif kind == "--attn-varlen":
        # varlen / long-context attention rung (ISSUE 13): the packed
        # block-skipping kernel on a 32k-token pack — throughput plus
        # the O(T·d) peak-bytes pin, gated by bench_gate (tokens/s
        # DOWN, peak bytes UP)
        tps, peak, total, backend = run_attn_varlen_bench()
        print(json.dumps(
            {"attn_varlen_tokens_per_sec": round(tps, 1),
             "attn_varlen_peak_bytes": peak,
             "attn_varlen_total_tokens": total,
             "attn_varlen_backend": backend,
             "attn_varlen_telemetry": _telemetry()}))
    elif kind == "--serve-long":
        # long-context serving rung: chunked prefill over the paged
        # pool routed through the in-place varlen kernel (no per-chunk
        # dense gather) — serve_long_* keys, gated by bench_gate
        import os
        import subprocess

        import jax

        tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "serve_bench.py")
        argv = [sys.executable, tool, "--no-lint", "--seed", "0",
                "--streams", "8", "--long-context"]
        if jax.default_backend() == "tpu":
            argv += ["--d-model", "2048", "--layers", "24", "--heads",
                     "16", "--vocab", "51200", "--bf16",
                     "--prompt-mix", "2048,8192,16384",
                     "--prefill-chunk", "512", "--max-new", "32",
                     "--page-size", "16", "--rate", "8"]
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=2400)
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("{")]
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"serve_bench --long-context rc={proc.returncode}: "
                f"{proc.stderr[-300:]}")
        print(lines[-1])
    elif kind == "--decode-int8kv":
        # best-throughput serving config: int8 weights + int8 KV cache
        # (cache-KV quant pays once KV traffic rivals the weight
        # stream: +14% at b64, r5) at batch 64
        tps, _pct, _rl = run_decode_bench(batch=64, quant="int8",
                                          kv_dtype="int8")
        print(json.dumps(
            {"decode_int8kv_b64_tokens_per_sec": round(tps, 1)}))
    elif kind == "--serve":
        # serving-frontend SLO rung: Poisson-load TTFT/TPOT/throughput
        # through paddle_tpu.serving (tools/serve_bench.py owns the
        # load generator; gated by bench_gate — ttft regresses UP,
        # tokens/s DOWN). CPU runs the tiny default geometry; on a
        # chip the 1.3B serving shape at a saturating rate.
        import os
        import subprocess

        import jax

        tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "serve_bench.py")
        argv = [sys.executable, tool, "--no-lint", "--seed", "0",
                "--streams", "8"]
        if jax.default_backend() == "tpu":
            argv += ["--d-model", "2048", "--layers", "24", "--heads",
                     "16", "--vocab", "51200", "--bf16",
                     "--prompt-mix", "128,512,1024",
                     "--prefill-chunk", "256", "--max-new", "64",
                     "--page-size", "16", "--rate", "64"]
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=1200)
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("{")]
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"serve_bench rc={proc.returncode}: "
                f"{proc.stderr[-300:]}")
        print(lines[-1])
    elif kind == "--moe-train":
        # no-drop MoE training rung (ISSUE 15 / ROADMAP item 4): the
        # ragged grouped-GEMM MoE FFN in a whole-compiled train step.
        # TPU gets a ~1B-param 8-expert config; CPU a smoke geometry
        # (correctness of the rung plumbing + the no-drop pin only).
        # Gated by bench_gate: tokens/s and MFU regress DOWN,
        # moe.dropped_tokens regresses UP with NO noise floor.
        import jax

        if jax.default_backend() == "tpu":
            tps, mfu, n_active, n_params = run_moe_train_bench(
                d_model=1024, n_layers=12, n_heads=16, seq=1024,
                batch=4, num_experts=8)
        else:
            tps, mfu, n_active, n_params = run_moe_train_bench(
                d_model=64, n_layers=2, n_heads=4, seq=64, batch=2,
                num_experts=4, steps=2)
        print(json.dumps(
            {"moe_train_tokens_per_sec": round(tps, 1),
             "moe_train_mfu": mfu,
             "moe_train_params": n_params,
             "moe_train_activated_params": n_active,
             "moe_train_telemetry": _telemetry()}))
    elif kind == "--moe-decode":
        # MoE serving decode rung: the expert-bank FusedCausalLM
        # through GenerationEngine (no-drop ragged MoE FFN per layer);
        # EP-sharded decode is exercised by dryrun_multichip's MoE
        # phase — this rung is the single-chip throughput number.
        import jax

        if jax.default_backend() == "tpu":
            tps, n_params = run_moe_decode_bench()
        else:
            tps, n_params = run_moe_decode_bench(
                batch=2, prompt=16, new_tokens=9, d_model=64,
                n_layers=2, n_heads=4, num_experts=4)
        print(json.dumps(
            {"moe_decode_tokens_per_sec": round(tps, 1),
             "moe_decode_params": n_params,
             "moe_decode_telemetry": _telemetry()}))
    elif kind == "--bert":
        tps, mfu, roofline = run_bert_bench()
        print(json.dumps({"bert_train_tokens_per_sec": round(tps, 1),
                          "bert_mfu": mfu,
                          "bert_roofline": roofline}))
    elif kind == "--s2048":
        import jax

        name, d, L, h, s, b, ok = S2048
        tps, n_params, fpt, roofline = run_config(name, d, L, h, s, b,
                                                  steps=10, opt_kwargs=ok)
        mfu = tps * fpt / _chip_peak(jax.devices()[0])
        print(json.dumps({"s2048_tokens_per_sec": round(tps, 1),
                          "s2048_mfu": round(mfu, 4),
                          "s2048_batch": b,
                          "s2048_roofline": roofline}))


#: every secondary rung, in the accumulated BENCH_r06 order
SECONDARY_KINDS = ("--s2048", "--decode", "--decode-int8",
                   "--decode-a8w8", "--decode-bf16-grouped",
                   "--decode-tp", "--decode-tp-overlap",
                   "--decode-spec", "--decode-int8kv", "--serve",
                   "--serve-long", "--fleet", "--fleet-disagg",
                   "--attn-varlen", "--moe-train", "--moe-decode",
                   "--moe-decode-ep-overlap", "--bert")

#: rungs with CPU-sized fallback geometries — the --all manifest runs
#: exactly these off-chip (the rest are chip-only shapes)
CPU_KINDS = ("--decode-tp-overlap", "--decode-spec", "--serve",
             "--serve-long", "--fleet", "--fleet-disagg",
             "--attn-varlen", "--moe-train", "--moe-decode",
             "--moe-decode-ep-overlap")


def _sub(argv, timeout, env=None):
    """One rung in a fresh child process (a failed bigger config
    leaves no stale HBM buffers behind; children skip the lint
    preflight — the parent vetted the tree)."""
    import os
    import subprocess

    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--no-lint"]
        + argv,
        capture_output=True, text=True, timeout=timeout, env=env)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if proc.returncode == 0 and lines:
        return json.loads(lines[-1]), None
    return None, f"rc={proc.returncode}: {proc.stderr[-300:]}"


def _accumulate(result, kinds, env=None):
    """Run each secondary rung in its own subprocess, merging every
    emitted key into ``result`` (errors land as ``<rung>_error``)."""
    for kind in kinds:
        # s2048's flash-attention bwd compile alone can take ~25min
        # cold (measured r5); the run itself is seconds
        extra, err = _sub([kind], 2400 if kind == "--s2048" else 1500,
                          env=env)
        if extra is None:
            key = kind.strip("-").replace("-", "_")
            result[f"{key}_error"] = err
        else:
            result.update(extra)
    return result


def _run_all():
    """--all manifest mode (ISSUE 19): EVERY accumulated rung in one
    invocation — per-rung subprocesses merged into a single
    BENCH_r06-shaped JSON line, so clearing the standing bench debt is
    one command on a chip. Off-chip the chip-only shapes are skipped
    and each remaining rung runs its CPU geometry (rung plumbing +
    parity signal only)."""
    import os

    import jax

    if jax.default_backend() == "tpu":
        result = None
        for (name, *_rest) in LADDER:
            result, err = _sub(["--config", name], 3000)
            if result is not None:
                break
            print(f"bench: {name} failed ({err})", file=sys.stderr)
        if result is None:
            raise SystemExit("bench --all: all ladder configs failed")
        print(json.dumps(_accumulate(result, SECONDARY_KINDS)))
        return
    # CPU manifest: the smoke training rung + every CPU-sized rung;
    # children get 2 virtual devices so the mp2/ep2 overlap rungs
    # exercise their collective paths (must land pre-jax-import, hence
    # via the child environment)
    result, err = _sub([], 1800)
    if result is None:
        result = {"train_error": err}
    env = dict(os.environ)
    if "xla_force_host_platform_device_count" not in \
            env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2").strip()
    env.setdefault("JAX_PLATFORMS", "cpu")
    print(json.dumps(_accumulate(result, CPU_KINDS, env=env)))


def main():
    # tpu_lint preflight (ISSUE 7): never spend chip time on a program
    # the static analyzer already knows is broken. The parent process
    # vets once; the per-rung child processes below inherit --no-lint.
    no_lint = "--no-lint" in sys.argv
    if no_lint:
        sys.argv.remove("--no-lint")
    from paddle_tpu.analysis.preflight import preflight

    preflight("bench", no_lint=no_lint)

    if "--config" in sys.argv:
        _run_one(sys.argv[sys.argv.index("--config") + 1])
        return
    if "--all" in sys.argv:
        _run_all()
        return
    for kind in SECONDARY_KINDS:
        if kind in sys.argv:
            _run_secondary(kind)
            return

    import jax

    if jax.default_backend() != "tpu":
        # CPU smoke config (CI): tiny model, correctness of the path only
        tps, n_params, fpt, roofline = run_config(
            "gpt-smoke", 128, 2, 4, 256, 2, 2)
        print(json.dumps({
            "metric": "gpt_train_tokens_per_sec_cpu", "value": round(tps, 1),
            "unit": "tokens/s", "vs_baseline": 1.0, "model": "gpt-smoke",
            "roofline": roofline,
            "telemetry": _telemetry(),
        }))
        return

    for (name, *_rest) in LADDER:
        result, err = _sub(["--config", name], 3000)
        if result is None:
            print(f"bench: {name} failed ({err})", file=sys.stderr)
            continue
        # secondary rungs each get a FRESH process (and a fresh chip —
        # the training rung's buffers die with its process)
        print(json.dumps(_accumulate(result, SECONDARY_KINDS)))
        return
    raise SystemExit("bench: all ladder configs failed")


if __name__ == "__main__":
    main()
