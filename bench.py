"""Benchmark: whole-step-compiled training throughput on the real chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Measures tokens/sec on a GPT-style transformer training step (the
BASELINE.md north-star metric family), whole step compiled to one XLA
program. vs_baseline is relative to a conservative reference anchor
recorded in this file (see BASELINE.md: the reference repo publishes no
absolute numbers, so the anchor is our own first measurement — later
rounds must beat it).
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    import jax

    backend = jax.default_backend()

    paddle.seed(0)
    # model scale adapted to backend so CI/CPU smoke stays fast
    if backend == "tpu":
        d_model, n_layers, n_heads, seq, batch = 512, 8, 8, 512, 8
        steps = 20
    else:
        d_model, n_layers, n_heads, seq, batch = 128, 2, 4, 128, 4
        steps = 5

    class TinyGPT(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(32000, d_model)
            self.pos = nn.Embedding(seq, d_model)
            enc_layer = nn.TransformerEncoderLayer(
                d_model, n_heads, 4 * d_model, dropout=0.0,
                activation="gelu", normalize_before=True)
            self.blocks = nn.TransformerEncoder(enc_layer, n_layers)
            self.norm = nn.LayerNorm(d_model)
            self.head = nn.Linear(d_model, 32000)

        def forward(self, ids, pos_ids):
            h = self.embed(ids) + self.pos(pos_ids)
            h = self.blocks(h)
            return self.head(self.norm(h))

    model = TinyGPT()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(logits.reshape([-1, 32000]),
                               labels.reshape([-1]))

    step = paddle.jit.TrainStep(model, loss_fn, opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 32000, (batch, seq)))
    pos = paddle.to_tensor(np.tile(np.arange(seq), (batch, 1)))
    labels = paddle.to_tensor(rng.randint(0, 32000, (batch, seq)))

    # warmup (compile)
    loss = step([ids, pos], [labels])
    loss._data.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step([ids, pos], [labels])
    loss._data.block_until_ready()
    dt = time.perf_counter() - t0

    tokens_per_sec = steps * batch * seq / dt

    # anchor: first real-chip measurement of this config (round 1:
    # 896,685 tok/s on TPU v5e-1) — later rounds must beat vs_baseline=1.0
    baseline = {"tpu": 896_685.0, "cpu": 2_000.0}.get(backend, 2_000.0)
    print(json.dumps({
        "metric": f"gpt_train_tokens_per_sec_{backend}",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / baseline, 3),
    }))


if __name__ == "__main__":
    main()
