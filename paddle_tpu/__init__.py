"""paddle_tpu: a TPU-native deep-learning framework.

Brand-new framework with the capabilities of the PaddlePaddle reference
(surveyed in /root/repo/SURVEY.md), designed TPU-first: eager tensors over
immutable PJRT buffers, tape autograd whose VJPs come from jax.vjp,
whole-step jit compilation to StableHLO/XLA, sharding via jax.sharding
meshes + GSPMD, and Pallas kernels for the hot ops.

Top-level namespace mirrors ``paddle.*`` so reference users can switch.
"""
from __future__ import annotations

import jax as _jax

# dtype parity with the reference: paddle supports float64/int64 defaults
# (python ints create int64 tensors). TPU perf paths use explicit f32/bf16.
_jax.config.update("jax_enable_x64", True)

# f32 matmuls run 3-pass bf16 on the MXU (accuracy ≈ the reference's
# A100 TF32 default, which Paddle enables for cuBLAS); bf16 stays native
# single-pass. Explicit bf16 is the perf path either way.
_jax.config.update("jax_default_matmul_precision", "high")

from .core.dtype import (  # noqa: E402
    bfloat16, bool_, complex128, complex64, dtype, float16, float32, float64,
    get_default_dtype, int8, int16, int32, int64, set_default_dtype, uint8,
    uint16, uint32, uint64,
)
from .core.dtype import bool_ as bool  # noqa: E402,A001
from .core.place import (  # noqa: E402
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_tpu,
    set_device,
)

# paddle-compat alias: CUDAPlace maps onto the accelerator place
CUDAPlace = TPUPlace

from .core.flags import get_flags, set_flags  # noqa: E402
from .core.generator import get_rng_state, seed, set_rng_state  # noqa: E402
from .core.tensor import Parameter, Tensor, to_tensor  # noqa: E402
from .core.engine import no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: E402

from .ops import *  # noqa: E402,F401,F403
from .ops import registry as _op_registry  # noqa: E402

from . import autograd  # noqa: E402
from .autograd import grad  # noqa: E402

from . import nn  # noqa: E402
from .nn.layer_base import ParamAttr  # noqa: E402
from . import regularizer  # noqa: E402
from . import optimizer  # noqa: E402
from . import amp  # noqa: E402
from . import io  # noqa: E402
from . import metric  # noqa: E402
from . import jit  # noqa: E402
from .jit import to_static  # noqa: E402
from . import static  # noqa: E402
from . import distributed  # noqa: E402
from . import vision  # noqa: E402
from . import profiler  # noqa: E402
from . import incubate  # noqa: E402
from . import sparse  # noqa: E402
from . import device  # noqa: E402

# persistent XLA compilation cache (FLAGS_compile_cache_dir / env
# PADDLE_TPU_COMPILE_CACHE_DIR): applied once at import, before any
# program compiles
device.setup_compile_cache()
from . import framework  # noqa: E402
from .framework.io import load, save  # noqa: E402
from .hapi.model import Model  # noqa: E402
from . import hapi  # noqa: E402
from .hapi.dynamic_flops import flops, summary  # noqa: E402
from . import distribution  # noqa: E402
from . import quantization  # noqa: E402
from . import linalg  # noqa: E402
from . import fft  # noqa: E402
from . import onnx  # noqa: E402
from . import audio  # noqa: E402
from . import signal  # noqa: E402
from . import text  # noqa: E402
from . import geometric  # noqa: E402
from . import utils  # noqa: E402
from .hapi import hub  # noqa: E402
from . import inference  # noqa: E402

def is_compiled_with_cuda():
    """False by design: this build's accelerator backend is TPU/XLA
    (reference framework.py is_compiled_with_cuda)."""
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_custom_device(device_type: str = None):
    """TPU is the (PJRT) device backend here."""
    return device_type in (None, "tpu")


# `paddle.disable_static()/enable_static()` parity: we are always dynamic
# with jit-compiled regions, so these are state toggles kept for API compat.
_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_dynamic_mode():
    return not _static_mode


def is_grad_enabled_():  # pragma: no cover - compat shim
    return is_grad_enabled()


def version():
    return "0.1.0"


__version__ = "0.1.0"
