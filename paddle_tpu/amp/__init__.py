"""paddle_tpu.amp — mirrors python/paddle/amp."""
from .auto_cast import (  # noqa: F401
    amp_decorate, amp_guard, auto_cast, black_list, decorate,
    get_amp_dtype, is_auto_cast_enabled, white_list,
)
from .grad_scaler import AmpScaler, GradScaler, OptimizerState  # noqa: F401
from . import debugging  # noqa: F401

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate",
           "GradScaler", "AmpScaler", "debugging"]
