"""Automatic mixed precision — autocast.

TPU-native equivalent of the reference's AMP (reference:
python/paddle/amp/auto_cast.py:703 ``auto_cast``, ``amp_guard:273``;
op lists python/paddle/amp/amp_lists.py:28). bf16-first: TPU matmuls are
natively bf16 on the MXU and need no loss scaling; fp16 is kept for parity.

O1: per-op cast at dispatch time (white list → low precision, black list →
float32). O2: ``decorate`` casts the model's params (minus norms) to the
target dtype; optimizers keep fp32 master weights via ``multi_precision``.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Set

import jax.numpy as jnp

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate",
           "white_list", "black_list", "is_auto_cast_enabled",
           "get_amp_dtype"]

# reference amp_lists.py:28 — ops that benefit from low precision (matmul /
# conv MXU ops) vs ops needing fp32 accumulation (softmax/norm/exp/log).
WHITE_LIST: Set[str] = {
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "matmul", "mm", "bmm", "linear", "einsum",
    "scaled_dot_product_attention", "addmm",
}
BLACK_LIST: Set[str] = {
    "exp", "expm1", "log", "log2", "log10", "log1p", "square", "pow",
    "softmax", "log_softmax", "cross_entropy", "nll_loss", "mse_loss",
    "l1_loss", "kl_div", "bce_with_logits", "binary_cross_entropy",
    "layer_norm", "batch_norm", "group_norm", "instance_norm", "rms_norm",
    "mean", "sum", "cumsum", "logsumexp", "softmax_with_cross_entropy",
    "erf", "erfinv", "cos_sim", "sigmoid_focal_loss", "normalize",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white: Set[str] = set()
        self.custom_black: Set[str] = set()
        # effective sets precomputed on auto_cast entry (dispatch hot path)
        self.eff_white: Set[str] = WHITE_LIST
        self.eff_black: Set[str] = BLACK_LIST

    def recompute(self):
        self.eff_white = (WHITE_LIST | self.custom_white) - self.custom_black
        self.eff_black = (BLACK_LIST | self.custom_black) - self.custom_white


_STATE = _AmpState()


def is_auto_cast_enabled() -> bool:
    return _STATE.enabled


def get_amp_dtype():
    return _STATE.dtype


def white_list() -> Set[str]:
    return _STATE.eff_white


def black_list() -> Set[str]:
    return _STATE.eff_black


def _amp_cast_arrays(op_name: str, arrays):
    """Dispatch-time cast hook; no-op when autocast is off.

    O1: white list → low precision, black list → fp32, rest untouched.
    O2: EVERYTHING → low precision except the black list (reference O2
    semantics — without this, fp32 activations re-promote bf16-decorated
    params to fp32 at every op under jnp promotion rules)."""
    if not _STATE.enabled:
        return arrays
    target = None
    if op_name in _STATE.eff_black:
        target = jnp.float32
    elif _STATE.level == "O2" or op_name in _STATE.eff_white:
        target = _STATE.dtype
    if target is None:
        return arrays
    out = []
    for a in arrays:
        if jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != target and \
                a.dtype != jnp.float64:
            out.append(a.astype(target))
        else:
            out.append(a)
    return out


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """``paddle.amp.auto_cast`` parity (auto_cast.py:703)."""
    prev = (_STATE.enabled, _STATE.dtype, _STATE.level,
            _STATE.custom_white, _STATE.custom_black,
            _STATE.eff_white, _STATE.eff_black)
    _STATE.enabled = bool(enable)
    _STATE.dtype = jnp.float16 if str(dtype) in ("float16", "fp16") \
        else jnp.bfloat16
    _STATE.level = level
    _STATE.custom_white = set(custom_white_list or ())
    _STATE.custom_black = set(custom_black_list or ())
    _STATE.recompute()
    try:
        yield
    finally:
        (_STATE.enabled, _STATE.dtype, _STATE.level,
         _STATE.custom_white, _STATE.custom_black,
         _STATE.eff_white, _STATE.eff_black) = prev


amp_guard = auto_cast

_NORM_LAYER_NAMES = ("BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm",
                     "SyncBatchNorm", "RMSNorm")


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decorate (reference auto_cast.py ``amp_decorate``): cast params to
    the low-precision dtype except normalization layers; enable fp32 master
    weights on the optimizer."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level not in ("O1", "O2"):
        raise ValueError("level must be O1 or O2")
    if level == "O2":
        for m in model_list:
            _cast_model_to(m, dtype)
    if optimizers is not None:
        single_opt = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if single_opt else list(optimizers)
        for o in opt_list:
            # master_weight=False opts out of the fp32 shadow copy (the
            # optimizer may instead use stochastic-rounding writeback)
            o._multi_precision = master_weight is not False
        if single_model and single_opt:
            return model_list[0], opt_list[0]
        return model_list, opt_list
    return model_list[0] if single_model else model_list


amp_decorate = decorate


def _cast_model_to(layer, dtype):
    from ..core.dtype import convert_dtype

    np_dt = convert_dtype(dtype).np_dtype
    for _, sub in layer.named_sublayers(include_self=True):
        if type(sub).__name__.startswith(_NORM_LAYER_NAMES):
            continue
        for p in sub._parameters.values():
            if p is not None and jnp.issubdtype(p._data.dtype, jnp.floating):
                p._rebind(p._data.astype(np_dt))
    layer._casted_by_pure_fp16 = True
    return layer
