"""AMP debugging utilities (reference: python/paddle/amp/debugging.py —
tensor stat collection, nan/inf op tracking via FLAGS_check_nan_inf)."""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..core.flags import get_flags, set_flags
from ..core.tensor import Tensor

__all__ = ["enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "enable_tensor_checker", "disable_tensor_checker",
           "check_numerics", "TensorCheckerConfig"]

_op_stats = {"enabled": False, "counts": {}}


def enable_operator_stats_collection():
    _op_stats["enabled"] = True
    _op_stats["counts"] = {}


def disable_operator_stats_collection():
    _op_stats["enabled"] = False
    counts = _op_stats["counts"]
    if counts:
        print("<------------------------------------------------------->")
        print("Op list with dtype counts:")
        for k, v in sorted(counts.items()):
            print(f"  {k}: {v}")


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def _record_op(op_name: str, dtype) -> None:
    if _op_stats["enabled"]:
        key = f"{op_name}<{dtype}>"
        _op_stats["counts"][key] = _op_stats["counts"].get(key, 0) + 1


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=None, output_dir=None,
                 checked_op_list=None, skipped_op_list=None,
                 debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


def enable_tensor_checker(config: TensorCheckerConfig = None):
    set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    arr = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    num_nan = int(jnp.sum(jnp.isnan(arr)))
    num_inf = int(jnp.sum(jnp.isinf(arr)))
    stats = {
        "num_nan": num_nan,
        "num_inf": num_inf,
        "min": float(jnp.min(arr)) if arr.size else 0.0,
        "max": float(jnp.max(arr)) if arr.size else 0.0,
        "mean": float(jnp.mean(arr)) if arr.size else 0.0,
    }
    if num_nan or num_inf:
        print(f"[check_numerics] op={op_type} var={var_name} stats={stats}")
    return Tensor(jnp.asarray(num_nan)), Tensor(jnp.asarray(num_inf))
