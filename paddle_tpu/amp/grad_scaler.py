"""Dynamic loss scaling.

TPU-native equivalent of the reference's GradScaler (reference:
python/paddle/amp/grad_scaler.py:578 ``GradScaler``, ``AmpScaler:41`` —
dynamic loss scaling with found_inf skip). bf16 training needs no scaling
(``enable=False`` is a clean passthrough); kept for fp16 parity.
"""
from __future__ import annotations

from enum import Enum

import jax.numpy as jnp

from ..core.engine import no_grad
from ..core.tensor import Tensor

__all__ = ["GradScaler", "AmpScaler", "OptimizerState"]


class OptimizerState(Enum):
    INIT = 0
    UNSCALED = 1
    STEPPED = 2


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic = use_dynamic_loss_scaling
        self._incr_count = 0
        self._decr_count = 0
        self._found_inf = False
        self._opt_states = {}

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._use_dynamic

    def scale(self, var):
        if not self._enable:
            return var
        from ..ops.dispatch import eager_apply, as_tensor_args

        s = self._scale
        return eager_apply("amp_scale", lambda a: a * s, as_tensor_args(var))

    def _unscale(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        finite_flags = []
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data * inv
            finite_flags.append(jnp.all(jnp.isfinite(g)))
            p.grad._rebind(g)
        # one fused reduce + a single host sync (not one per parameter)
        self._found_inf = bool(finite_flags) and not bool(
            jnp.all(jnp.stack(finite_flags)))

    def unscale_(self, optimizer):
        self._unscale(optimizer)
        self._opt_states[id(optimizer)] = OptimizerState.UNSCALED

    @no_grad()
    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if self._opt_states.get(id(optimizer)) != OptimizerState.UNSCALED:
            self._unscale(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._opt_states[id(optimizer)] = OptimizerState.STEPPED

    def update(self):
        if not self._enable or not self._use_dynamic:
            self._opt_states.clear()
            return
        if self._found_inf:
            self._decr_count += 1
            self._incr_count = 0
            if self._decr_count >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._decr_count = 0
        else:
            self._incr_count += 1
            self._decr_count = 0
            if self._incr_count >= self._incr_every_n_steps:
                self._scale = self._scale * self._incr_ratio
                self._incr_count = 0
        self._found_inf = False
        self._opt_states.clear()

    def minimize(self, optimizer, loss):
        self.step(optimizer)
        self.update()

    # ----- introspection (reference API) -----
    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def get_incr_ratio(self):
        return self._incr_ratio

    def set_incr_ratio(self, v):
        self._incr_ratio = v

    def get_decr_ratio(self):
        return self._decr_ratio

    def set_decr_ratio(self, v):
        self._decr_ratio = v

    def get_incr_every_n_steps(self):
        return self._incr_every_n_steps

    def set_incr_every_n_steps(self, v):
        self._incr_every_n_steps = v

    def get_decr_every_n_nan_or_inf(self):
        return self._decr_every_n_nan_or_inf

    def set_decr_every_n_nan_or_inf(self, v):
        self._decr_every_n_nan_or_inf = v

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "incr_count": self._incr_count,
            "decr_count": self._decr_count,
            "use_dynamic_loss_scaling": self._use_dynamic,
        }

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._incr_ratio = state["incr_ratio"]
        self._decr_ratio = state["decr_ratio"]
        self._incr_every_n_steps = state["incr_every_n_steps"]
        self._decr_every_n_nan_or_inf = state["decr_every_n_nan_or_inf"]
        self._incr_count = state.get("incr_count", 0)
        self._decr_count = state.get("decr_count", 0)
        self._use_dynamic = state.get("use_dynamic_loss_scaling", True)


class GradScaler(AmpScaler):
    """User-facing scaler (grad_scaler.py:578)."""
    pass
