"""paddle_tpu.analysis — static analysis for TPU kernels and traced
code, runnable entirely on CPU.

Chip time is the scarcest resource in this repo (a cold s2048 compile
alone is ~25 min); this package proves on CPU the properties that
otherwise only fail on hardware:

- **Pass 1 — kernel geometry** (:mod:`.geometry` over :mod:`.audit` /
  :mod:`.sites`): every ``pallas_call`` launch spec is shim-recorded
  from an ``eval_shape`` dry-trace and validated — VMEM footprint vs
  the declared limit and the per-generation budget table
  (:mod:`paddle_tpu.device.vmem`), dtype tile alignment, grid
  divisibility, index-map bounds at grid edges, and no magic
  ``vmem_limit_bytes`` literals.
- **Pass 2 — use-after-donate** (:mod:`.donation`): a
  ``FLAGS_check_donation`` poison mode that makes CPU runs fail exactly
  where TPU donation would read freed HBM, plus a static audit of the
  registry's donation contracts.
- **Pass 3 — trace purity** (:mod:`.purity`): AST lint of traced code
  for concretization hazards (``bool/int/float``/``if`` on tracers,
  ``np.*`` on tracers, host time/RNG, python-state mutation in loop
  bodies), with an inline waiver syntax
  (``# tpu-lint: ok(<rule>) -- <reason>``).

Front-end: ``tools/tpu_lint.py`` (``--json`` for CI); the tier-1 test
``tests/test_tpu_lint.py`` asserts the repo is clean.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

from .audit import PallasCallRecord, record_pallas_calls  # noqa: F401
from .base import Finding, apply_waivers, parse_waivers  # noqa: F401
from .donation import (  # noqa: F401
    UseAfterDonateError, assert_not_poisoned, audit_donation_registry,
    clear_poisoned, is_poisoned, poison, poisoned_count,
)
from .flags_lint import env_var_for, run_flags_pass  # noqa: F401
from .geometry import (  # noqa: F401
    analyze_record, scan_magic_vmem_literals, tile_padded_bytes,
    vmem_footprint,
)
from .purity import run_purity_pass  # noqa: F401
from .sites import KERNEL_SITES, trace_all_sites, trace_site  # noqa: F401

__all__ = [
    "Finding", "PallasCallRecord", "record_pallas_calls",
    "UseAfterDonateError", "poison", "is_poisoned", "assert_not_poisoned",
    "poisoned_count", "clear_poisoned",
    "analyze_record", "vmem_footprint", "tile_padded_bytes",
    "scan_magic_vmem_literals", "audit_donation_registry",
    "run_geometry_pass", "run_donation_pass", "run_purity_pass",
    "run_flags_pass", "run_all_passes", "unwaivered",
    "KERNEL_SITES", "trace_site", "trace_all_sites", "env_var_for",
]


def _pkg_root() -> str:
    """The paddle_tpu/ package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_geometry_pass(generation: Optional[str] = None) -> List[Finding]:
    """Dry-trace every kernel site, analyze each recorded launch spec,
    and scan the tree for magic VMEM literals."""
    pkg = _pkg_root()
    findings: List[Finding] = []
    for name, records in trace_all_sites().items():
        for rec in records:
            for f in analyze_record(rec, generation=generation):
                f.site = f"{name} ({rec.kernel_name})"
                findings.append(f)
    src_findings = scan_magic_vmem_literals(pkg)
    waivers = {}
    for f in src_findings:
        if f.path and f.path not in waivers:
            path = os.path.join(os.path.dirname(pkg), f.path)
            try:
                with open(path, encoding="utf-8") as fh:
                    waivers[f.path] = parse_waivers(fh.read())
            except OSError:
                pass
    apply_waivers(src_findings, waivers)
    return findings + src_findings


def run_donation_pass() -> List[Finding]:
    return audit_donation_registry(_pkg_root())


def run_all_passes(generation: Optional[str] = None
                   ) -> Dict[str, List[Finding]]:
    """All four checks; keys: geometry / donation / purity / flags."""
    return {
        "geometry": run_geometry_pass(generation=generation),
        "donation": run_donation_pass(),
        "purity": run_purity_pass(_pkg_root()),
        "flags": run_flags_pass(),
    }


def unwaivered(findings: List[Finding]) -> List[Finding]:
    return [f for f in findings if not f.waived]
