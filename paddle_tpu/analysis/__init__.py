"""paddle_tpu.analysis — static analysis for TPU kernels and traced
code, runnable entirely on CPU.

Chip time is the scarcest resource in this repo (a cold s2048 compile
alone is ~25 min); this package proves on CPU the properties that
otherwise only fail on hardware:

- **Pass 1 — kernel geometry** (:mod:`.geometry` over :mod:`.audit` /
  :mod:`.sites`): every ``pallas_call`` launch spec is shim-recorded
  from an ``eval_shape`` dry-trace and validated — VMEM footprint vs
  the declared limit and the per-generation budget table
  (:mod:`paddle_tpu.device.vmem`), dtype tile alignment, grid
  divisibility, index-map bounds at grid edges, and no magic
  ``vmem_limit_bytes`` literals.
- **Pass 2 — use-after-donate** (:mod:`.donation`): a
  ``FLAGS_check_donation`` poison mode that makes CPU runs fail exactly
  where TPU donation would read freed HBM, plus a static audit of the
  registry's donation contracts.
- **Pass 3 — trace purity** (:mod:`.purity`): AST lint of traced code
  for concretization hazards (``bool/int/float``/``if`` on tracers,
  ``np.*`` on tracers, host time/RNG, python-state mutation in loop
  bodies), with an inline waiver syntax
  (``# tpu-lint: ok(<rule>) -- <reason>``).

PR 7 extends the suite one level up — from kernels to whole compiled
PROGRAMS (:mod:`.program_sites` dry-traces the repo's jit'd composites,
the train step, and the serving prefill/decode programs to closed
jaxprs):

- **Pass 4 — DTYPE** (:mod:`.dtype_flow`): silent bf16→f32 matmul
  promotion in declared-bf16 programs (``X-PROMOTE``) and f64 leakage
  (``X-F64``).
- **Pass 5 — SYNC** (:mod:`.host_sync`): host callbacks inside hot
  loops / decode programs (``X-SYNC``) and recompile-churn statics
  (``X-CHURN``).
- **Pass 6 — MEMORY** (:mod:`.hbm`): donation-aware liveness walk →
  static HBM-peak bound per program, vs the per-generation capacity
  table in ``device.vmem`` (``M-HBM``).
- **Pass 7 — SPMD** (:mod:`.spmd`): the distributed surfaces compiled
  on a virtual 8-device CPU mesh; undeclared collectives in the
  partitioned HLO (``S-GATHER``), asymmetric collective sequences
  across branches (``S-MATCH``), missing output sharding constraints
  (``S-UNSPEC``).
- **Pass 9 — OVERLAP** (:mod:`.overlap`): the comm/compute overlap
  sites (ring-reduce TP decode, double-buffered EP exchange) keep
  their exact collective census — phase counts, permute ordering, no
  stray blocking psum (``S-OVERLAP``).

Front-end: ``tools/tpu_lint.py`` (``--json`` for CI, ``--baseline``
ratchet); :mod:`.preflight` gates the bench/profiling drivers; the
tier-1 tests ``tests/test_tpu_lint.py`` + ``tests/test_graph_lint.py``
assert the repo is clean.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

from .audit import PallasCallRecord, record_pallas_calls  # noqa: F401
from .base import (  # noqa: F401
    Finding, apply_waivers, parse_waivers, waive_from_sources,
)
from .donation import (  # noqa: F401
    UseAfterDonateError, assert_not_poisoned, audit_donation_registry,
    clear_poisoned, is_poisoned, poison, poisoned_count,
)
from .dtype_flow import check_dtype_flow, run_dtype_pass  # noqa: F401
from .flags_lint import env_var_for, run_flags_pass  # noqa: F401
from .geometry import (  # noqa: F401
    analyze_record, scan_magic_vmem_literals, tile_padded_bytes,
    vmem_footprint,
)
from .hbm import (  # noqa: F401
    estimate_program, peak_live_bytes, run_memory_pass,
)
from .host_sync import run_sync_pass  # noqa: F401
from .program_sites import (  # noqa: F401
    PROGRAM_SITES, ProgramSite, TracedProgram, site_for_fn,
    trace_all_programs, trace_program,
)
from .purity import run_purity_pass  # noqa: F401
from .sites import KERNEL_SITES, trace_all_sites, trace_site  # noqa: F401
from .overlap import (  # noqa: F401
    OVERLAP_SITES, OverlapSite, check_overlap_program,
    run_overlap_pass,
)
from .spmd import (  # noqa: F401
    SPMD_SITES, SpmdSite, check_spmd_site, hlo_collective_counts,
    mesh_available, run_spmd_pass, trace_census, virtual_mesh,
)

__all__ = [
    "Finding", "PallasCallRecord", "record_pallas_calls",
    "UseAfterDonateError", "poison", "is_poisoned", "assert_not_poisoned",
    "poisoned_count", "clear_poisoned",
    "analyze_record", "vmem_footprint", "tile_padded_bytes",
    "scan_magic_vmem_literals", "audit_donation_registry",
    "run_geometry_pass", "run_donation_pass", "run_purity_pass",
    "run_flags_pass", "run_dtype_pass", "run_sync_pass",
    "run_memory_pass", "run_spmd_pass", "run_all_passes",
    "run_program_passes", "unwaivered", "rule_counts", "ratchet",
    "KERNEL_SITES", "trace_site", "trace_all_sites", "env_var_for",
    "PROGRAM_SITES", "ProgramSite", "TracedProgram", "site_for_fn",
    "trace_program", "trace_all_programs", "estimate_program",
    "peak_live_bytes", "SPMD_SITES", "SpmdSite", "check_spmd_site",
    "hlo_collective_counts", "mesh_available", "virtual_mesh",
    "waive_from_sources", "PASS_NAMES", "trace_census",
    "OVERLAP_SITES", "OverlapSite", "check_overlap_program",
    "run_overlap_pass",
]

#: every pass, in report order: 3 kernel-level + flags (PR 6), the
#: 4 program-level passes (PR 7), and the overlap-structure pass
#: (ISSUE 19)
PASS_NAMES = ("geometry", "donation", "purity", "flags",
              "dtype", "sync", "memory", "spmd", "overlap")


def _pkg_root() -> str:
    """The paddle_tpu/ package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_geometry_pass(generation: Optional[str] = None) -> List[Finding]:
    """Dry-trace every kernel site, analyze each recorded launch spec,
    and scan the tree for magic VMEM literals."""
    pkg = _pkg_root()
    findings: List[Finding] = []
    for name, records in trace_all_sites().items():
        for rec in records:
            for f in analyze_record(rec, generation=generation):
                f.site = f"{name} ({rec.kernel_name})"
                findings.append(f)
    src_findings = scan_magic_vmem_literals(pkg)
    waivers = {}
    for f in src_findings:
        if f.path and f.path not in waivers:
            path = os.path.join(os.path.dirname(pkg), f.path)
            try:
                with open(path, encoding="utf-8") as fh:
                    waivers[f.path] = parse_waivers(fh.read())
            except OSError:
                pass
    apply_waivers(src_findings, waivers)
    return findings + src_findings


def run_donation_pass() -> List[Finding]:
    return audit_donation_registry(_pkg_root())


def run_program_passes(generation: Optional[str] = None
                       ) -> Dict[str, List[Finding]]:
    """The four program-level checks (PR 7); the program inventory is
    traced ONCE and shared across dtype/sync/memory."""
    traced = trace_all_programs()
    return {
        "dtype": run_dtype_pass(traced=traced),
        "sync": run_sync_pass(traced=traced),
        "memory": run_memory_pass(generation=generation, traced=traced),
        "spmd": run_spmd_pass(),
        "overlap": run_overlap_pass(),
    }


def run_all_passes(generation: Optional[str] = None
                   ) -> Dict[str, List[Finding]]:
    """All checks; keys = ``PASS_NAMES`` (kernel-level geometry /
    donation / purity / flags + program-level dtype / sync / memory /
    spmd)."""
    out = {
        "geometry": run_geometry_pass(generation=generation),
        "donation": run_donation_pass(),
        "purity": run_purity_pass(_pkg_root()),
        "flags": run_flags_pass(),
    }
    out.update(run_program_passes(generation=generation))
    return out


def unwaivered(findings: List[Finding]) -> List[Finding]:
    return [f for f in findings if not f.waived]


def rule_counts(results: Dict[str, List[Finding]]) -> Dict[str, int]:
    """rule id -> UNWAIVERED finding count (the ratchet currency —
    waived legacy findings never count against a baseline)."""
    counts: Dict[str, int] = {}
    for fs in results.values():
        for f in unwaivered(fs):
            counts[f.rule] = counts.get(f.rule, 0) + 1
    return counts


def ratchet(current: Dict[str, int], baseline: Dict[str, int]
            ) -> List[str]:
    """Ratchet compare: lines describing every rule whose unwaivered
    count GREW vs the baseline (empty = no new findings; shrinkage and
    baseline-only rules are fine — the ratchet only tightens)."""
    bad = []
    for rule in sorted(current):
        cur, base = current[rule], baseline.get(rule, 0)
        if cur > base:
            bad.append(f"{rule}: {base} -> {cur} (+{cur - base} new)")
    return bad
