"""Audit shim around ``pl.pallas_call``: records every kernel launch
spec — BlockSpecs, grid, scratch shapes, operand avals, compiler
params — at trace time, without perturbing the call.

This is how the geometry pass sees kernels exactly as Mosaic will: the
sites driver (``analysis.sites``) dry-traces each kernel under
``jax.eval_shape`` with this shim installed, so the whole launch spec is
captured on CPU with zero device work (abstract evaluation never lowers
to Mosaic, so it works off-TPU regardless of ``interpret``).

The shim patches the ``pallas_call`` attribute of
``jax.experimental.pallas``; both the repo's kernels and the stock jax
kernels (flash attention, jax paged_attention) resolve it through the
module at call time, so all of them are captured.
"""
from __future__ import annotations

import contextlib
import dataclasses
import traceback
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["BlockSpecInfo", "ScratchInfo", "PallasCallRecord",
           "record_pallas_calls"]


@dataclasses.dataclass
class BlockSpecInfo:
    """One (possibly None) BlockSpec, normalized."""

    block_shape: Optional[Tuple[int, ...]]
    index_map: Optional[Any]          # the original callable, if any
    memory_space: Optional[str]       # e.g. "any", "vmem", None
    # filled by the analyzer from call-time operands / out_shape:
    aval_shape: Optional[Tuple[int, ...]] = None
    aval_dtype: Optional[str] = None

    @property
    def is_blocked(self) -> bool:
        return self.block_shape is not None


@dataclasses.dataclass
class ScratchInfo:
    shape: Tuple[int, ...]
    dtype: str
    memory_space: str                 # "vmem" | "smem" | "semaphore"


@dataclasses.dataclass
class PallasCallRecord:
    kernel_name: str
    path: str                         # call-site file
    line: int                         # call-site line
    grid: Tuple[int, ...]
    num_scalar_prefetch: int
    in_specs: List[BlockSpecInfo]
    out_specs: List[BlockSpecInfo]
    scratch: List[ScratchInfo]
    out_shapes: List[Optional[Tuple[Tuple[int, ...], str]]]
    vmem_limit_bytes: Optional[int]
    input_output_aliases: Dict[int, int]
    interpret: bool
    # call-time avals, one per operand INCLUDING scalar-prefetch args;
    # None for operands passed as literal None (optional flash inputs)
    operands: Optional[List[Optional[Tuple[Tuple[int, ...], str]]]] = None

    @property
    def site(self) -> str:
        return f"{self.path}:{self.line}:{self.kernel_name}"

    def scalar_operands(self):
        """Call-time avals of the scalar-prefetch operands."""
        ops = self.operands or []
        return ops[:self.num_scalar_prefetch]

    def blocked_operands(self):
        """(BlockSpecInfo, aval) pairs for the non-scalar inputs, spec
        order; aval is None when the operand was passed as None."""
        ops = (self.operands or [])[self.num_scalar_prefetch:]
        return list(zip(self.in_specs, list(ops) + [None] * (
            len(self.in_specs) - len(ops))))


def _space_name(space) -> Optional[str]:
    if space is None:
        return None
    name = getattr(space, "name", None) or str(space)
    return str(name).lower()


def _norm_spec(spec) -> BlockSpecInfo:
    if spec is None:
        return BlockSpecInfo(None, None, None)
    shape = getattr(spec, "block_shape", None)
    if shape is not None:
        shape = tuple(int(d) for d in shape)
    return BlockSpecInfo(
        block_shape=shape,
        index_map=getattr(spec, "index_map", None),
        memory_space=_space_name(getattr(spec, "memory_space", None)))


def _norm_scratch(ref) -> ScratchInfo:
    space = _space_name(getattr(ref, "memory_space", None)) or "vmem"
    dtype = getattr(ref, "dtype", None)
    dstr = str(getattr(dtype, "name", None)
               or getattr(dtype, "__name__", None) or dtype)
    if "sem" in dstr or "semaphore" in space:
        kind = "semaphore"
    elif "smem" in space:
        kind = "smem"
    else:
        kind = "vmem"
    shape = tuple(int(d) for d in getattr(ref, "shape", ()) or ())
    return ScratchInfo(shape=shape, dtype=dstr, memory_space=kind)


def _as_list(x) -> list:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _norm_out_shape(s):
    if s is None:
        return None
    return (tuple(int(d) for d in s.shape), str(s.dtype))


def _aval(x):
    if x is None:
        return None
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return None
    return (tuple(int(d) for d in shape), str(dtype))


def _call_site() -> Tuple[str, int]:
    """First stack frame outside this module and outside functools —
    the code that invoked pallas_call."""
    for frame in reversed(traceback.extract_stack()):
        if frame.filename != __file__ and "functools" not in frame.filename:
            return frame.filename, frame.lineno or 0
    return "<unknown>", 0


def _capture(kernel, args, kwargs) -> PallasCallRecord:
    grid_spec = kwargs.get("grid_spec")
    if grid_spec is not None:
        grid = getattr(grid_spec, "grid", ()) or ()
        nsp = int(getattr(grid_spec, "num_scalar_prefetch", 0) or 0)
        in_specs = _as_list(getattr(grid_spec, "in_specs", None))
        out_specs = _as_list(getattr(grid_spec, "out_specs", None))
        scratch = _as_list(getattr(grid_spec, "scratch_shapes", None))
    else:
        grid = kwargs.get("grid", ()) or ()
        nsp = 0
        in_specs = _as_list(kwargs.get("in_specs"))
        out_specs = _as_list(kwargs.get("out_specs"))
        scratch = _as_list(kwargs.get("scratch_shapes"))
    if isinstance(grid, int):
        grid = (grid,)
    cp = kwargs.get("compiler_params")
    vmem = getattr(cp, "vmem_limit_bytes", None) if cp is not None else None
    if isinstance(cp, dict):  # pallas also accepts a plain dict
        vmem = (cp.get("mosaic") or {}).get("vmem_limit_bytes",
                                            cp.get("vmem_limit_bytes"))
    path, line = _call_site()
    name = getattr(kernel, "__name__", None)
    if not name or name == "<lambda>":
        fn = getattr(kernel, "func", None)  # functools.partial
        name = getattr(fn, "__name__", name or "<kernel>")
    return PallasCallRecord(
        kernel_name=name,
        path=path,
        line=line,
        grid=tuple(int(g) for g in grid),
        num_scalar_prefetch=nsp,
        in_specs=[_norm_spec(s) for s in in_specs],
        out_specs=[_norm_spec(s) for s in out_specs],
        scratch=[_norm_scratch(r) for r in scratch],
        out_shapes=[_norm_out_shape(s)
                    for s in _as_list(kwargs.get("out_shape"))],
        vmem_limit_bytes=int(vmem) if vmem is not None else None,
        input_output_aliases=dict(
            kwargs.get("input_output_aliases") or {}),
        interpret=bool(kwargs.get("interpret", False)),
    )


@contextlib.contextmanager
def record_pallas_calls():
    """Patch ``pl.pallas_call`` to record every launch spec; yields the
    (live) list of :class:`PallasCallRecord`. The real pallas_call runs
    unchanged underneath, so this can wrap real executions as well as
    ``jax.eval_shape`` dry-traces."""
    from jax.experimental import pallas as pl

    records: List[PallasCallRecord] = []
    orig = pl.pallas_call

    def shim(kernel, *args, **kwargs):
        rec = _capture(kernel, args, kwargs)
        records.append(rec)
        inner = orig(kernel, *args, **kwargs)

        def invoke(*operands):
            rec.operands = [_aval(o) for o in operands]
            return inner(*operands)

        return invoke

    pl.pallas_call = shim
    try:
        yield records
    finally:
        pl.pallas_call = orig
