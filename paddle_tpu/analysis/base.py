"""Shared plumbing for the static-analysis passes: the ``Finding``
record every pass emits and the inline-waiver syntax that documents
intentional exceptions.

Waiver syntax (on the flagged line, or the line immediately above)::

    x = risky_thing()  # tpu-lint: ok(P-HOST-RNG) -- reseeded per trace

The rule id must match the finding's rule and a non-empty reason is
required — a bare ``ok(...)`` does not waive. True positives get fixed;
waivers exist so the intentional exceptions are documented in-line and
survive review.
"""
from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, List, Optional, Tuple

__all__ = ["Finding", "parse_waivers", "apply_waivers",
           "waive_from_sources"]

#: ``# tpu-lint: ok(RULE) <sep> reason`` — separator is any dash/em-dash
#: or colon; the reason must be non-empty
_WAIVER_RE = re.compile(
    r"#\s*tpu-lint:\s*ok\(\s*(?P<rule>[A-Za-z0-9_.-]+)\s*\)\s*"
    r"(?:[-—–:]+\s*)?(?P<reason>\S.*)?$")


@dataclasses.dataclass
class Finding:
    """One analysis finding, anchored (when source-level) to a line."""

    rule: str                      # e.g. "G-TILE", "P-TRACER-IF"
    message: str
    path: Optional[str] = None     # repo-relative when source-anchored
    line: Optional[int] = None
    site: Optional[str] = None     # kernel/op the finding is about
    waived: bool = False
    waive_reason: Optional[str] = None

    def location(self) -> str:
        if self.path and self.line:
            return f"{self.path}:{self.line}"
        return self.path or self.site or "<repo>"

    def render(self) -> str:
        tag = " [waived: %s]" % self.waive_reason if self.waived else ""
        where = self.location()
        at = f" @ {self.site}" if self.site and self.site != where else ""
        return f"{self.rule} {where}{at}: {self.message}{tag}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_waivers(source: str) -> Dict[int, Tuple[str, str]]:
    """line number (1-based) -> (rule, reason) for every waiver comment
    in ``source``. Waivers with an empty reason are ignored (and the
    lint itself flags them, see purity.check_waiver_hygiene)."""
    out: Dict[int, Tuple[str, str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _WAIVER_RE.search(text)
        if m and m.group("reason"):
            out[i] = (m.group("rule"), m.group("reason").strip())
    return out


def waive_from_sources(findings: List[Finding],
                       root: Optional[str] = None) -> List[Finding]:
    """Apply inline waivers by reading each finding's source file
    (relative paths resolve against ``root``, absolute paths — e.g.
    synthetic test modules — as-is). Returns ``findings``."""
    cache: Dict[str, Dict[int, Tuple[str, str]]] = {}
    for f in findings:
        if not f.path or not f.line:
            continue
        if f.path not in cache:
            path = f.path if os.path.isabs(f.path) else \
                os.path.join(root or os.getcwd(), f.path)
            try:
                with open(path, encoding="utf-8") as fh:
                    cache[f.path] = parse_waivers(fh.read())
            except OSError:
                cache[f.path] = {}
    return apply_waivers(findings, cache)


def apply_waivers(findings: List[Finding],
                  waivers_by_path: Dict[str, Dict[int, Tuple[str, str]]],
                  ) -> List[Finding]:
    """Mark findings waived when a matching-rule waiver sits on the
    flagged line or the line above it. Returns ``findings``."""
    for f in findings:
        if f.path is None or f.line is None:
            continue
        waivers = waivers_by_path.get(f.path, {})
        for ln in (f.line, f.line - 1):
            w = waivers.get(ln)
            if w and w[0] == f.rule:
                f.waived = True
                f.waive_reason = w[1]
                break
    return findings
