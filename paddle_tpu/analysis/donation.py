"""Pass 2 — use-after-donate detector.

Two halves:

**Runtime poison mode** (``FLAGS_check_donation``): the compiled-forward
fast path donates in-place op buffers (ops/dispatch.py). On TPU a
donated buffer is genuinely dead — any alias that slipped past the
``_donation_safe`` refcount guard reads freed HBM. CPU jaxlib ignores
donation, so such a bug is INVISIBLE in CI. With the flag on, dispatch
registers every donated buffer here after the call; every subsequent
dispatch (and ``Tensor.numpy()``) asserts none of its inputs is a
poisoned buffer and raises :class:`UseAfterDonateError` with the
donating op — so CPU tests reproduce the TPU failure mode
deterministically instead of silently passing.

The registry holds ids + weakrefs only (jax arrays are immutable; we
cannot scribble on the buffer itself), and entries self-purge when the
donated array object dies — id() reuse can never poison a fresh array.

**Static registry audit** (``audit_donation_registry``): proves the op
registry's donation metadata is consistent with the dispatch layer —
every ``OpDef.donates`` is the in-place contract ``(0,)`` with
``inplace_of`` naming a registered base op and an ``inplace`` tag; every
function that dispatches through ``inplace_apply`` (donating slot 0 at
runtime) is registered with that contract; and the donation path in
ops/dispatch.py still filters through ``_donation_safe``.
"""
from __future__ import annotations

import ast
import os
import weakref
from typing import Any, Dict, List, Optional

from .base import Finding

__all__ = ["UseAfterDonateError", "poison", "is_poisoned",
           "assert_not_poisoned", "poisoned_count", "clear_poisoned",
           "audit_donation_registry"]


class UseAfterDonateError(RuntimeError):
    """A live Tensor read a buffer that was donated to a compiled op."""


#: id(array) -> (weakref, donating op name). Weakref callbacks purge the
#: entry when the donated object dies, so a recycled id() is never
#: mistaken for the poisoned buffer.
_POISONED: Dict[int, Any] = {}


def poison(arr, op_name: str) -> None:
    """Mark ``arr``'s buffer as donated (dead) by ``op_name``."""
    key = id(arr)

    def _purge(ref, _key=key):
        ent = _POISONED.get(_key)
        if ent is not None and ent[0] is ref:
            _POISONED.pop(_key, None)

    try:
        _POISONED[key] = (weakref.ref(arr, _purge), op_name)
    except TypeError:  # non-weakref-able array impl: id-only (no purge)
        _POISONED[key] = (None, op_name)


def is_poisoned(arr) -> Optional[str]:
    """The donating op's name when ``arr`` is a poisoned buffer."""
    ent = _POISONED.get(id(arr))
    if ent is None:
        return None
    ref, op = ent
    if ref is not None and ref() is not arr:
        return None
    return op


def assert_not_poisoned(arrays, reader: str) -> None:
    """Raise when any of ``arrays`` was donated. ``reader`` names the
    consuming operation for the error message."""
    if not _POISONED:
        return
    for a in arrays:
        op = is_poisoned(a)
        if op is not None:
            raise UseAfterDonateError(
                f"{reader} read a buffer that `{op}` donated to its "
                "compiled executable — on TPU this is freed HBM. An "
                "alias escaped the _donation_safe refcount guard (or "
                "the guard was bypassed); hold a copy instead of an "
                "alias, or file the op's donation contract as a bug.")


def poisoned_count() -> int:
    return len(_POISONED)


def clear_poisoned() -> None:
    _POISONED.clear()


# ----------------------------------------------------------------- audit

def _inplace_apply_call_sites(pkg_root: str) -> List[dict]:
    """AST scan: every function def that calls ``inplace_apply`` —
    those donate their slot-0 buffer at runtime."""
    out = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(pkg_root))
            if rel.replace(os.sep, "/").endswith("ops/dispatch.py"):
                continue  # the definition itself
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.FunctionDef):
                    continue
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Name)
                            and sub.func.id == "inplace_apply"):
                        out.append({"fn": node.name, "path": rel,
                                    "line": sub.lineno})
                        break
    return out


def _dispatch_guard_ok(pkg_root: str) -> bool:
    """Does ops/dispatch.py still filter donate_idx through
    ``_donation_safe`` before building the donated executable?"""
    path = os.path.join(pkg_root, "ops", "dispatch.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return False
    for node in ast.walk(tree):
        if (isinstance(node, ast.FunctionDef)
                and node.name == "_forward_fast_path"):
            return any(isinstance(s, ast.Call)
                       and isinstance(s.func, ast.Name)
                       and s.func.id == "_donation_safe"
                       for s in ast.walk(node))
    return False


def audit_donation_registry(pkg_root: Optional[str] = None
                            ) -> List[Finding]:
    """Static consistency audit of the registry's donation metadata."""
    from ..ops.registry import all_ops

    if pkg_root is None:
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    findings: List[Finding] = []
    ops = all_ops()

    for name, d in sorted(ops.items()):
        if d.donates:
            if d.donates != (0,):
                findings.append(Finding(
                    rule="D-SLOT", site=name,
                    message=f"donates={d.donates}: inplace_apply only "
                            "donates slot 0 — other slots are never "
                            "rebound and would alias freed buffers"))
            if not d.inplace_of:
                findings.append(Finding(
                    rule="D-ORPHAN", site=name,
                    message="declares donates but no inplace_of — the "
                            "donated slot has no rebind contract"))
            if "inplace" not in d.tags:
                findings.append(Finding(
                    rule="D-TAG", site=name,
                    message="donating op missing the 'inplace' tag"))
        if d.inplace_of:
            if not d.donates:
                findings.append(Finding(
                    rule="D-NODONATE", site=name,
                    message=f"inplace_of={d.inplace_of!r} without a "
                            "donates contract — the fast path will "
                            "double-buffer this in-place op forever"))
            if d.inplace_of not in ops:
                findings.append(Finding(
                    rule="D-DANGLING", site=name,
                    message=f"inplace_of={d.inplace_of!r} is not a "
                            "registered op — the registry is supposed "
                            "to be the single source of truth"))

    # runtime donation sites must be declared in the registry
    by_fn_name = {}
    for name, d in ops.items():
        by_fn_name.setdefault(getattr(d.fn, "__name__", name), name)
    for site in _inplace_apply_call_sites(pkg_root):
        fn = site["fn"]
        # the contract may live under the def's name, its `*_` alias,
        # or any registry entry whose fn is this def (increment_)
        cands = [fn, fn + "_", by_fn_name.get(fn)]
        covered = any(c in ops and ops[c].donates for c in cands if c)
        if not covered:
            findings.append(Finding(
                rule="D-UNDECLARED", path=site["path"], line=site["line"],
                site=fn,
                message=(f"`{fn}` dispatches through inplace_apply "
                         "(donates slot 0 at runtime) but its OpDef "
                         "declares no donation contract")))

    if not _dispatch_guard_ok(pkg_root):
        findings.append(Finding(
            rule="D-GUARD", path="paddle_tpu/ops/dispatch.py",
            message="_forward_fast_path no longer filters donate_idx "
                    "through _donation_safe — aliased buffers would be "
                    "donated"))
    return findings
