"""Pass 4 — DTYPE: dtype-promotion lint over whole-program jaxprs.

A bf16 serving path is bandwidth-bound; one matmul that silently
promotes to f32 doubles its operand traffic AND halves MXU throughput,
and nothing fails — the program just runs at half speed on the chip.
This pass walks the closed jaxpr of every registered program site
(:mod:`.program_sites`) and flags:

- ``X-PROMOTE``: a ``dot_general`` / ``conv_general_dilated`` inside a
  declared-bf16 program (``ProgramSite.compute_dtype == "bfloat16"``)
  with a float32/float64 *operand*. Operands are the traffic; an f32
  operand means a bf16 value got upcast (or a weight never got cast)
  upstream. bf16xbf16 dots with ``preferred_element_type=f32`` are the
  INTENDED accumulation idiom and pass — accumulation is free, operand
  width is not.
- ``X-F64``: any float64 abstract value in any program — f64 is
  software-emulated on TPU (and means x64 leaked into a trace).

Findings anchor to the repo source line that built the op (jax
source_info), so the standard inline waiver syntax applies at the
offending call.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .base import Finding, waive_from_sources
from .jaxpr_util import eqn_anchor, repo_root, walk_eqns

__all__ = ["check_dtype_flow", "run_dtype_pass"]

#: the MXU ops whose operand dtype is the traffic/throughput lever
_DOT_PRIMS = ("dot_general", "conv_general_dilated")

#: operand dtypes that mean "this declared-bf16 dot got promoted"
_WIDE_FLOATS = ("float32", "float64")


def _anchor(eqn, site):
    path, line = eqn_anchor(eqn)
    if path is None:
        path, line = site.path, site.line
    return path, line


def check_dtype_flow(traced) -> List[Finding]:
    """All DTYPE findings for one :class:`TracedProgram`."""
    site = traced.site
    findings: List[Finding] = []
    declared_bf16 = site.compute_dtype == "bfloat16"
    seen_f64 = set()
    for eqn, _ in walk_eqns(traced.closed.jaxpr):
        if declared_bf16 and eqn.primitive.name in _DOT_PRIMS:
            bad = sorted({str(v.aval.dtype) for v in eqn.invars
                          if str(getattr(v.aval, "dtype", ""))
                          in _WIDE_FLOATS})
            if bad:
                path, line = _anchor(eqn, site)
                findings.append(Finding(
                    rule="X-PROMOTE", site=site.name, path=path,
                    line=line,
                    message=(f"{eqn.primitive.name} with "
                             f"{'/'.join(bad)} operand(s) inside the "
                             f"declared-bf16 program `{site.name}` — a "
                             "silent upcast doubles operand HBM traffic"
                             "; cast the operand (accumulate via "
                             "preferred_element_type instead)")))
        for v in list(eqn.invars) + list(eqn.outvars):
            if str(getattr(getattr(v, "aval", None), "dtype", "")) \
                    == "float64":
                path, line = _anchor(eqn, site)
                key = (path, line)
                if key in seen_f64:
                    continue
                seen_f64.add(key)
                findings.append(Finding(
                    rule="X-F64", site=site.name, path=path, line=line,
                    message=(f"float64 value in program `{site.name}` "
                             f"(primitive {eqn.primitive.name}) — f64 "
                             "is software-emulated on TPU; x64 leaked "
                             "into the trace")))
    return findings


def run_dtype_pass(traced: Optional[Dict] = None) -> List[Finding]:
    """DTYPE findings over the whole program inventory."""
    from .program_sites import trace_all_programs

    if traced is None:
        traced = trace_all_programs()
    findings: List[Finding] = []
    for tp in traced.values():
        findings += check_dtype_flow(tp)
    return waive_from_sources(findings, repo_root())
