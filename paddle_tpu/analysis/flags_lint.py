"""Flags/env/README parity check.

Every ``FLAGS_*`` defined in ``core/flags.py`` is settable by env var
two ways — ``FLAGS_<name>`` (reference parity) and ``PADDLE_TPU_<NAME>``
(the deployment convention PR 5's compile-cache flag established) — and
must carry a row in the README flags table so operators can discover
it. This pass asserts the parity holds for the whole registry:

- ``F-README``: flag missing from the README flags table (the row must
  mention both the ``FLAGS_<name>`` and ``PADDLE_TPU_<NAME>`` forms);
- ``F-ENV``: ``define_flag`` no longer honors the generic
  ``PADDLE_TPU_*`` override (source-level check on core/flags.py).
"""
from __future__ import annotations

import os
from typing import List, Optional

from .base import Finding

__all__ = ["env_var_for", "run_flags_pass"]


def env_var_for(flag_name: str) -> str:
    """The ``PADDLE_TPU_*`` env override for a flag name (delegates to
    core.flags so the convention has one definition)."""
    from ..core.flags import env_var_for as _impl

    return _impl(flag_name)


def run_flags_pass(repo_root: Optional[str] = None) -> List[Finding]:
    from ..core.flags import _FLAGS

    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    findings: List[Finding] = []

    readme = os.path.join(repo_root, "README.md")
    try:
        with open(readme, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        text = ""
        findings.append(Finding(rule="F-README", path="README.md",
                                message="README.md not found"))

    for name in sorted(_FLAGS):
        full, env = f"FLAGS_{name}", env_var_for(name)
        missing = [s for s in (full, env) if s not in text]
        if missing:
            findings.append(Finding(
                rule="F-README", path="README.md", site=full,
                message=(f"flag `{full}` has no conventions row naming "
                         f"{' and '.join(missing)} — add it to the "
                         "README flags table")))

    flags_py = os.path.join(repo_root, "paddle_tpu", "core", "flags.py")
    try:
        with open(flags_py, encoding="utf-8") as f:
            src = f.read()
    except OSError:
        src = ""
    if "PADDLE_TPU_" not in src:
        findings.append(Finding(
            rule="F-ENV", path="paddle_tpu/core/flags.py",
            message="define_flag no longer reads the generic "
                    "PADDLE_TPU_<NAME> env override"))
    return findings
