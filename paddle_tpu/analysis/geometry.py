"""Pass 1 — kernel geometry analyzer.

Consumes :class:`~paddle_tpu.analysis.audit.PallasCallRecord`s (shim-
captured launch specs) and proves, on CPU, the properties that
otherwise only fail on a real chip:

- **VMEM footprint** (``G-VMEM`` / ``G-BUDGET``): per-grid-step bytes =
  tile-padded block bytes for every blocked operand/output (x2 when its
  index map varies across the grid — Pallas double-buffers streamed
  blocks) + VMEM scratch. Checked against the kernel's declared
  ``vmem_limit_bytes`` (or Mosaic's 16 MiB scoped default when
  undeclared) and against the per-generation physical budget table in
  ``paddle_tpu.device.vmem``.
- **Tile alignment** (``G-TILE``): each of a block's last two dims must
  be 1, the full array dim, or a multiple of the dtype tile —
  (8, 128) f32/int32, (16, 128) bf16, (32, 128) int8.
- **Grid divisibility** (``G-DIV``): every blocked dim must divide its
  array dim exactly (Mosaic's edge-padding is where silent garbage
  reads come from in hand-rolled index maps).
- **Index-map bounds** (``G-BOUNDS``): index maps are evaluated at the
  grid edges with concrete indices; a block whose start exceeds the
  array is flagged. Dims whose index depends on scalar-prefetch values
  (traced layer ids, page tables) are skipped — they are dynamic by
  design and reported as such.
- **Magic VMEM literals** (``G-MAGIC``, source-level): any
  ``vmem_limit_bytes=<numeric literal>`` in the tree must instead come
  from ``device.vmem.KERNEL_VMEM_LIMIT_BYTES`` so the cap and the
  budget table can never drift apart.
"""
from __future__ import annotations

import ast
import dataclasses
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .audit import BlockSpecInfo, PallasCallRecord
from .base import Finding

__all__ = ["SUBLANES", "LANE", "tile_padded_bytes", "index_map_profile",
           "vmem_footprint", "analyze_record", "scan_magic_vmem_literals",
           "FootprintItem", "FootprintReport"]

LANE = 128

#: minimum VMEM tile (sublane count) per dtype itemsize — the
#: (8, 128) f32 / (16, 128) bf16 / (32, 128) int8 table
SUBLANES = {8: 8, 4: 8, 2: 16, 1: 32}

_DTYPE_SIZE_CACHE: Dict[str, int] = {}


def _itemsize(dtype: str) -> int:
    n = _DTYPE_SIZE_CACHE.get(dtype)
    if n is None:
        n = _DTYPE_SIZE_CACHE[dtype] = int(np.dtype(
            dtype.replace("bfloat16", "uint16")).itemsize)
    return n


def tile_padded_bytes(shape: Sequence[int], dtype: str) -> int:
    """Bytes one block of ``shape``/``dtype`` occupies in VMEM, with the
    last two dims padded up to the dtype's (sublane, lane) tile."""
    shape = tuple(int(d) for d in shape)
    size = _itemsize(dtype)
    sub = SUBLANES.get(size, 8)
    if not shape:
        return size
    if len(shape) == 1:
        return size * (-(-shape[0] // LANE) * LANE)
    lead = 1
    for d in shape[:-2]:
        lead *= d
    s2 = -(-shape[-2] // sub) * sub
    s1 = -(-shape[-1] // LANE) * LANE
    return size * lead * s2 * s1


def _scalar_args(record: PallasCallRecord, fill: int) -> List[np.ndarray]:
    """Concrete stand-ins for the scalar-prefetch operands the index
    maps index into (``l[0]`` etc.)."""
    out = []
    for aval in record.scalar_operands():
        shape = aval[0] if aval else (1,)
        out.append(np.full(shape, fill, dtype=np.int32))
    return out


def _grid_points(grid: Tuple[int, ...], cap: int = 512):
    """All grid points when the grid is small, otherwise the corners
    plus per-axis edge sweeps (the places index maps go out of bounds)."""
    if not grid:
        return [()]
    total = 1
    for g in grid:
        total *= max(g, 1)
    if total <= cap:
        import itertools

        return list(itertools.product(*(range(max(g, 1)) for g in grid)))
    points = set()
    corners = [(0, max(g - 1, 0)) for g in grid]
    import itertools

    points.update(itertools.product(*corners))
    for ax, g in enumerate(grid):
        base = [0] * len(grid)
        for v in range(max(g, 1)):
            p = list(base)
            p[ax] = v
            points.add(tuple(p))
            if len(points) >= cap:
                break
    return sorted(points)


def index_map_profile(record: PallasCallRecord, spec: BlockSpecInfo):
    """Evaluate a BlockSpec's index map over the grid.

    Returns ``(varies, dynamic_dims, points)`` where ``varies`` is True
    when the block index changes across the grid (the operand is
    streamed — double-buffered), ``dynamic_dims`` is the set of block
    dims whose index depends on scalar-prefetch VALUES (bounds cannot be
    proven statically), and ``points`` maps each evaluated grid point to
    its block index tuple (with scalar refs zeroed). Returns
    ``(True, None, None)`` when the map cannot be evaluated — the
    analyzer then assumes the conservative streamed case.
    """
    if spec.index_map is None:
        return False, set(), {}
    zeros = _scalar_args(record, 0)
    ones = _scalar_args(record, 1)

    def run(point, scalars):
        out = spec.index_map(*point, *scalars)
        if not isinstance(out, tuple):
            out = (out,)
        return tuple(int(v) for v in out)

    try:
        pts = _grid_points(record.grid)
        seen = {}
        dynamic = set()
        for p in pts:
            z = run(p, zeros)
            seen[p] = z
            if ones:
                o = run(p, ones)
                dynamic.update(i for i, (a, b) in enumerate(zip(z, o))
                               if a != b)
        varies = len(set(seen.values())) > 1
        return varies, dynamic, seen
    except Exception:
        return True, None, None


@dataclasses.dataclass
class FootprintItem:
    name: str                    # "in[3]", "out[0]", "scratch[2]"
    block_shape: Tuple[int, ...]
    dtype: str
    bytes: int                   # tile-padded, x2 when double-buffered
    buffers: int                 # 1 resident / 2 streamed
    streamed: bool


@dataclasses.dataclass
class FootprintReport:
    items: List[FootprintItem]

    @property
    def total_bytes(self) -> int:
        return sum(i.bytes for i in self.items)


def vmem_footprint(record: PallasCallRecord) -> FootprintReport:
    """Per-grid-step VMEM footprint of a recorded launch spec."""
    items: List[FootprintItem] = []

    def add(name, spec: BlockSpecInfo, aval):
        if spec is None or not spec.is_blocked:
            return  # memory_space=ANY stays in HBM (manual DMA)
        dtype = (aval[1] if aval else None) or "float32"
        varies, _, _ = index_map_profile(record, spec)
        n = 2 if varies else 1
        per = tile_padded_bytes(spec.block_shape, dtype)
        items.append(FootprintItem(
            name=name, block_shape=tuple(spec.block_shape), dtype=dtype,
            bytes=per * n, buffers=n, streamed=varies))

    for i, (spec, aval) in enumerate(record.blocked_operands()):
        add(f"in[{i}]", spec, aval)
    outs = record.out_shapes + [None] * (
        len(record.out_specs) - len(record.out_shapes))
    for i, spec in enumerate(record.out_specs):
        add(f"out[{i}]", spec, outs[i] if i < len(outs) else None)
    for i, sc in enumerate(record.scratch):
        if sc.memory_space != "vmem":
            continue  # semaphores/SMEM are not VMEM tiles
        items.append(FootprintItem(
            name=f"scratch[{i}]", block_shape=sc.shape, dtype=sc.dtype,
            bytes=tile_padded_bytes(sc.shape, sc.dtype), buffers=1,
            streamed=False))
    return FootprintReport(items)


def _check_tile(record, name, spec: BlockSpecInfo, aval, findings):
    if not spec.is_blocked:
        return
    dtype = (aval[1] if aval else None) or "float32"
    sub = SUBLANES.get(_itemsize(dtype), 8)
    shape = spec.block_shape
    full = aval[0] if aval else None
    for pos, need in ((-1, LANE), (-2, sub)):
        if len(shape) < -pos:
            continue
        d = shape[pos]
        full_d = full[pos] if full and len(full) >= -pos else None
        if d == 1 or d % need == 0 or (full_d is not None and d == full_d):
            continue
        findings.append(Finding(
            rule="G-TILE", site=record.site, path=record.path,
            line=record.line,
            message=(f"{name} block {shape} dim {pos} = {d} is not a "
                     f"multiple of the {dtype} tile ({sub}, {LANE}) nor "
                     "the full array dim")))


def _check_div_bounds(record, name, spec: BlockSpecInfo, aval, findings):
    if not spec.is_blocked or aval is None:
        return
    shape, arr = spec.block_shape, aval[0]
    if len(shape) != len(arr):
        findings.append(Finding(
            rule="G-RANK", site=record.site, path=record.path,
            line=record.line,
            message=f"{name} block rank {len(shape)} != operand rank "
                    f"{len(arr)} (shape {arr})"))
        return
    for i, (b, a) in enumerate(zip(shape, arr)):
        if b and a % b:
            findings.append(Finding(
                rule="G-DIV", site=record.site, path=record.path,
                line=record.line,
                message=(f"{name} dim {i}: array {a} not divisible by "
                         f"block {b} — the edge block reads Mosaic pad "
                         "garbage")))
    varies, dynamic, points = index_map_profile(record, spec)
    if points is None or dynamic is None:
        return  # un-evaluable map: dynamic by construction
    for point, idx in points.items():
        if len(idx) != len(shape):
            findings.append(Finding(
                rule="G-RANK", site=record.site, path=record.path,
                line=record.line,
                message=f"{name} index map returns {len(idx)} indices "
                        f"for a rank-{len(shape)} block"))
            return
        for i, (bi, b, a) in enumerate(zip(idx, shape, arr)):
            if i in dynamic or not b:
                continue
            if bi * b + b > a or bi < 0:
                findings.append(Finding(
                    rule="G-BOUNDS", site=record.site, path=record.path,
                    line=record.line,
                    message=(f"{name} dim {i}: block index {bi} at grid "
                             f"point {point} maps to "
                             f"[{bi * b}, {bi * b + b}) outside array "
                             f"dim {a}")))
                return  # one bound finding per operand is enough


def analyze_record(record: PallasCallRecord,
                   generation: Optional[str] = None) -> List[Finding]:
    """Run every geometry check on one recorded launch spec."""
    from ..device import vmem as dv

    findings: List[Finding] = []
    pairs = [(f"in[{i}]", s, a)
             for i, (s, a) in enumerate(record.blocked_operands())]
    outs = record.out_shapes + [None] * (
        len(record.out_specs) - len(record.out_shapes))
    pairs += [(f"out[{i}]", s, outs[i] if i < len(outs) else None)
              for i, s in enumerate(record.out_specs)]
    for name, spec, aval in pairs:
        if spec is None:
            continue
        _check_tile(record, name, spec, aval, findings)
        _check_div_bounds(record, name, spec, aval, findings)

    fp = vmem_footprint(record)
    limit = record.vmem_limit_bytes
    declared = limit is not None
    if limit is None:
        limit = dv.MOSAIC_DEFAULT_VMEM_LIMIT_BYTES
    if fp.total_bytes > limit:
        findings.append(Finding(
            rule="G-VMEM", site=record.site, path=record.path,
            line=record.line,
            message=(f"footprint {fp.total_bytes / dv.MiB:.1f} MiB exceeds "
                     + (f"declared vmem_limit_bytes {limit / dv.MiB:.1f} MiB"
                        if declared else
                        f"Mosaic's {limit / dv.MiB:.0f} MiB scoped default "
                        "(declare vmem_limit_bytes)"))))
    budget = dv.vmem_budget_bytes(generation)
    if max(fp.total_bytes, limit if declared else 0) > budget:
        findings.append(Finding(
            rule="G-BUDGET", site=record.site, path=record.path,
            line=record.line,
            message=(f"declared limit/footprint "
                     f"{max(fp.total_bytes, limit) / dv.MiB:.1f} MiB exceeds "
                     f"the {generation or dv.detect_generation()} physical "
                     f"VMEM budget {budget / dv.MiB:.0f} MiB")))
    return findings


# ----------------------------------------------------------------- source
def scan_magic_vmem_literals(root: str) -> List[Finding]:
    """``G-MAGIC``: flag every ``vmem_limit_bytes=<numeric literal>`` in
    the tree — the cap must come from device.vmem so it can't drift from
    the budget table."""

    def is_const_num(node) -> bool:
        if isinstance(node, ast.Constant):
            return isinstance(node.value, (int, float))
        if isinstance(node, ast.BinOp):
            return is_const_num(node.left) and is_const_num(node.right)
        return False

    findings: List[Finding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(root))
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if kw.arg == "vmem_limit_bytes" and is_const_num(kw.value):
                        findings.append(Finding(
                            rule="G-MAGIC", path=rel, line=kw.value.lineno,
                            message=("vmem_limit_bytes is a magic numeric "
                                     "literal; use device.vmem."
                                     "KERNEL_VMEM_LIMIT_BYTES")))
    return findings
