"""Pass 6 — MEMORY: static HBM-peak estimator (liveness over jaxprs).

"This 13B config OOMs on v5e" should be a CPU-side lint finding, not a
burned 25-minute chip session. The estimator walks a program's closed
jaxpr in execution order tracking live buffer bytes:

- program inputs are live from entry; DONATED inputs (the engine's KV
  cache, TrainStep's param/opt-state buffers — ``donate_argnums``) die
  at their last use (XLA aliases their pages into outputs), while
  non-donated inputs stay live to the end (the caller holds them);
- each equation's outputs allocate while its inputs are still live
  (that overlap is exactly where real peaks live);
- intermediates die after their last use;
- control-flow bodies (scan/while/cond/pjit) contribute their own
  inner peak NET of their boundary values (carries are already counted
  at the outer level).

The resulting ``peak_bytes`` is an upper bound that ignores XLA fusion
(fused elementwise chains never materialize) — tight in practice
because programs here are dominated by weights/caches, not elementwise
temps; the tier-1 test pins it within 20% of
``compiled.memory_analysis()`` for the decode program.

``M-HBM`` fires when a program's peak exceeds the per-generation HBM
capacity table (``device.vmem.HBM_BUDGET_BYTES`` minus the runtime
reserve).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from .base import Finding, waive_from_sources
from .jaxpr_util import aval_bytes, repo_root, sub_jaxprs

__all__ = ["HbmEstimate", "peak_live_bytes", "estimate_program",
           "run_memory_pass"]


@dataclasses.dataclass
class HbmEstimate:
    peak_bytes: int          # max live bytes at any execution point
    arg_bytes: int           # program inputs (incl. consts)
    out_bytes: int           # program outputs
    n_eqns: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _jaxpr_peak(jaxpr, donated_invars=frozenset(),
                const_bytes: int = 0) -> Tuple[int, int]:
    """(peak_bytes, boundary_bytes) of one jaxpr. ``donated_invars`` are
    flat invar INDICES whose buffers may die at last use."""
    from jax.core import Var

    last_use: Dict[object, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, Var):
                last_use[v] = i
    outset = {v for v in jaxpr.outvars if isinstance(v, Var)}
    donated = {v for i, v in enumerate(jaxpr.invars)
               if i in donated_invars}

    live: Dict[object, int] = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        live[v] = aval_bytes(v.aval)
    cur = sum(live.values()) + const_bytes
    peak = cur
    for i, eqn in enumerate(jaxpr.eqns):
        out_b = sum(aval_bytes(v.aval) for v in eqn.outvars)
        inner_extra = 0
        for sj in sub_jaxprs(eqn):
            # inner bodies may donate everything: their carries are the
            # outer eqn's operands, counted here already
            p, boundary = _jaxpr_peak(
                sj, donated_invars=frozenset(range(len(sj.invars))))
            inner_extra = max(inner_extra, max(0, p - boundary))
        peak = max(peak, cur + out_b + inner_extra)
        for v in eqn.outvars:
            live[v] = aval_bytes(v.aval)
            cur += live[v]
        for v in {v for v in eqn.invars if isinstance(v, Var)}:
            if last_use.get(v) != i or v in outset or v not in live:
                continue
            if v in jaxpr.invars and v not in donated:
                continue  # caller still holds a non-donated input
            cur -= live.pop(v)
    boundary = (sum(aval_bytes(v.aval) for v in jaxpr.invars)
                + sum(aval_bytes(v.aval) for v in jaxpr.constvars)
                + sum(aval_bytes(v.aval) for v in jaxpr.outvars
                      if isinstance(v, Var)))
    return peak, boundary


def peak_live_bytes(closed, donated_invars=frozenset()) -> HbmEstimate:
    """Donation-aware peak-live-bytes bound for a ClosedJaxpr."""
    jaxpr = closed.jaxpr
    const_bytes = sum(aval_bytes(getattr(c, "aval", None)) or
                      getattr(c, "nbytes", 0) for c in closed.consts)
    peak, _ = _jaxpr_peak(jaxpr, donated_invars=donated_invars,
                          const_bytes=const_bytes)
    return HbmEstimate(
        peak_bytes=int(peak),
        arg_bytes=int(sum(aval_bytes(v.aval) for v in jaxpr.invars)
                      + const_bytes),
        out_bytes=int(sum(aval_bytes(getattr(v, "aval", None))
                          for v in jaxpr.outvars)),
        n_eqns=len(jaxpr.eqns))


def estimate_program(traced) -> HbmEstimate:
    """Estimate for one :class:`TracedProgram` (donation-aware)."""
    return peak_live_bytes(traced.closed,
                           donated_invars=traced.donated_invars)


def run_memory_pass(generation: Optional[str] = None,
                    traced: Optional[Dict] = None) -> List[Finding]:
    """M-HBM findings over the program inventory, against the HBM
    capacity of ``generation`` (default: attached chip, else v5e)."""
    from ..device import vmem as dv
    from .program_sites import trace_all_programs

    if traced is None:
        traced = trace_all_programs()
    budget = dv.hbm_budget_bytes(generation)
    gen = generation or dv.detect_generation()
    findings: List[Finding] = []
    for tp in traced.values():
        est = estimate_program(tp)
        if est.peak_bytes <= budget:
            continue
        site = tp.site
        findings.append(Finding(
            rule="M-HBM", site=site.name, path=site.path, line=site.line,
            message=(f"static peak-live estimate "
                     f"{est.peak_bytes / dv.GiB:.2f} GiB for "
                     f"`{site.name}` exceeds the {gen} usable HBM "
                     f"{budget / dv.GiB:.1f} GiB "
                     f"({dv.HBM_BUDGET_BYTES.get(gen, 0) / dv.GiB:.0f} "
                     "GiB capacity - runtime reserve) — this program "
                     "OOMs on the chip")))
    return waive_from_sources(findings, repo_root())
