"""Pass 5 — SYNC: host round-trips in hot loops + recompile churn.

A decode loop that hides one host callback runs at tunnel latency
instead of chip latency (every scan iteration round-trips the host),
and a jit site keyed on an unhashable or per-step-varying static
recompiles every call — both are invisible in CPU runs and catastrophic
on the chip. Over the traced program inventory
(:mod:`.program_sites`):

- ``X-SYNC``: a host-callback-lowering primitive (``pure_callback`` /
  ``io_callback`` / ``debug_callback`` — the lowering of
  ``jax.debug.print`` — and friends) inside a ``scan`` / ``while`` /
  ``fori_loop`` body, or ANYWHERE in a site marked ``hot_loop`` (the
  decode-step program: one sync per token is the whole latency budget).
- ``X-CHURN``: a program site whose declared jit static kwargs fail the
  dispatch layer's bakeable-statics discipline
  (``ops.dispatch._static_ok`` — the PR 3 admission-key helper): lists,
  dicts, arrays and Tensors are unhashable or freeze per-step values
  into the trace, i.e. a retrace storm or a stale constant.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .base import Finding, waive_from_sources
from .jaxpr_util import eqn_anchor, repo_root, walk_eqns

__all__ = ["check_host_sync", "check_churn", "run_sync_pass"]

#: primitives that lower to a host round-trip
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                   "outside_call", "host_callback_call")


def check_host_sync(traced) -> List[Finding]:
    site = traced.site
    findings: List[Finding] = []
    for eqn, in_loop in walk_eqns(traced.closed.jaxpr):
        if eqn.primitive.name not in _CALLBACK_PRIMS:
            continue
        if not (in_loop or site.hot_loop):
            continue
        where = "a traced loop body" if in_loop else \
            f"the hot-loop program `{site.name}`"
        path, line = eqn_anchor(eqn)
        if path is None:
            path, line = site.path, site.line
        findings.append(Finding(
            rule="X-SYNC", site=site.name, path=path, line=line,
            message=(f"host callback `{eqn.primitive.name}` inside "
                     f"{where} — every execution round-trips the host "
                     "(tunnel latency per decode step); hoist it out of "
                     "the compiled program")))
    return findings


def check_churn(site) -> List[Finding]:
    """X-CHURN over one site's declared static kwargs."""
    if not site.static_kwargs:
        return []
    from ..ops.dispatch import _static_ok

    bad = sorted(k for k, v in site.static_kwargs.items()
                 if not _static_ok(v))
    if not bad:
        return []
    return [Finding(
        rule="X-CHURN", site=site.name, path=site.path, line=site.line,
        message=(f"static kwarg(s) {bad} of `{site.name}` fail the "
                 "bakeable-statics allowlist (ops.dispatch._static_ok) "
                 "— unhashable or value-baking statics retrace the "
                 "program per call; pass them as traced operands or "
                 "hashable scalars"))]


def run_sync_pass(traced: Optional[Dict] = None) -> List[Finding]:
    """SYNC findings over the whole program inventory."""
    from .program_sites import trace_all_programs

    if traced is None:
        traced = trace_all_programs()
    findings: List[Finding] = []
    for tp in traced.values():
        findings += check_host_sync(tp)
        findings += check_churn(tp.site)
    return waive_from_sources(findings, repo_root())
