"""Shared jaxpr plumbing for the program-level passes: sub-jaxpr
enumeration, aval byte sizing, and source anchoring of equations (so
findings land on the repo line that built the op and inline waivers
apply there).
"""
from __future__ import annotations

import os
from typing import Iterator, List, Optional, Tuple

import numpy as np

__all__ = ["sub_jaxprs", "walk_eqns", "aval_bytes", "eqn_anchor",
           "repo_root"]


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _jaxpr_types():
    from jax.core import ClosedJaxpr, Jaxpr

    return ClosedJaxpr, Jaxpr


def sub_jaxprs(eqn) -> List[object]:
    """Inner jaxprs of one equation (scan/while/cond/pjit/shard_map/
    custom_* all carry theirs under different param keys — enumerate by
    type instead of by name)."""
    ClosedJaxpr, Jaxpr = _jaxpr_types()
    out = []
    for v in eqn.params.values():
        if isinstance(v, ClosedJaxpr):
            out.append(v.jaxpr)
        elif isinstance(v, Jaxpr):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            for b in v:
                if isinstance(b, ClosedJaxpr):
                    out.append(b.jaxpr)
                elif isinstance(b, Jaxpr):
                    out.append(b)
    return out


#: primitives whose sub-jaxpr is a LOOP body (runs per iteration)
LOOP_PRIMS = ("scan", "while")


def walk_eqns(jaxpr, in_loop: bool = False) -> Iterator[Tuple[object, bool]]:
    """Yield ``(eqn, in_loop)`` over a jaxpr and all sub-jaxprs, where
    ``in_loop`` is True for equations inside a scan/while body."""
    for eqn in jaxpr.eqns:
        yield eqn, in_loop
        inner_loop = in_loop or eqn.primitive.name in LOOP_PRIMS
        for sj in sub_jaxprs(eqn):
            yield from walk_eqns(sj, inner_loop)


def aval_bytes(aval) -> int:
    """HBM bytes of one abstract value (bf16 counts 2; non-array avals
    count 0)."""
    try:
        size = int(aval.size)
        dt = str(aval.dtype).replace("bfloat16", "uint16")
        return size * int(np.dtype(dt).itemsize)
    except Exception:
        return 0


def eqn_anchor(eqn) -> Tuple[Optional[str], Optional[int]]:
    """(path, line) of the user frame that built this equation —
    repo-relative when inside the repo — or (None, None)."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return None, None
        path, line = frame.file_name, int(frame.start_line)
    except Exception:
        return None, None
    root = repo_root()
    if path.startswith(root + os.sep):
        path = os.path.relpath(path, root)
    return path, line
