"""Pass 9 — OVERLAP: comm/compute overlap structure lint (S-OVERLAP).

The ring-reduce TP decode and the double-buffered EP exchange
(ISSUE 19) only hide collective latency while their PROGRAM STRUCTURE
holds: the exact chunked ppermute sequence (P chunks x P-1 steps per
reduction, interleaved with the chunk GEMMs) and the two half-capacity
all_to_all pairs. A refactor that collapses the ring back into one
blocking ``psum`` — or fuses the double buffer back into a single
exchange — still produces bitwise-correct tokens on CPU, so no parity
test catches it; only the collective census changes. This pass pins
that census EXACTLY for every overlap-declared site:

- the traced collective sequence must equal the site's expected
  sequence (primitive + axes, in order — phase counts and permute
  ordering included);
- no blocking collective from the site's ``forbidden`` set may appear
  anywhere in the trace (a stray ``psum`` inside a ring site is the
  regression signature).

Sites are skipped (not failed) without the virtual device mesh, same
as the SPMD pass; waivers use the standard inline syntax at the site
builder's line.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Tuple

from .base import Finding, waive_from_sources
from .jaxpr_util import repo_root
from .spmd import mesh_available, trace_census

__all__ = ["OverlapSite", "OVERLAP_SITES", "check_overlap_program",
           "run_overlap_pass"]


@dataclasses.dataclass
class OverlapSite:
    name: str                 # "overlap.tp_decode_ring", ...
    build: Callable           # () -> (fn, args)
    expected: Callable        # () -> exact [(prim, axes_str)] census
    forbidden: tuple = ("psum",)   # blocking collectives banned here
    path: str = ""
    line: int = 0

    def __post_init__(self):
        import os

        code = getattr(self.build, "__code__", None)
        if code is not None and not self.path:
            repo = repo_root()
            fname = code.co_filename
            self.path = os.path.relpath(fname, repo) \
                if fname.startswith(repo) else fname
            self.line = code.co_firstlineno


def check_overlap_program(site: OverlapSite) -> List[Finding]:
    """Trace one overlap site and pin its collective structure."""
    findings: List[Finding] = []
    fn, args = site.build()
    seq = trace_census(fn, *args)
    expected = list(site.expected())

    stray = sorted({p for p, _ in seq if p in site.forbidden})
    if stray:
        findings.append(Finding(
            rule="S-OVERLAP", site=site.name, path=site.path,
            line=site.line,
            message=(f"overlap-declared site `{site.name}` traces "
                     f"blocking collective(s) {stray} — the pipelined "
                     "ring/double-buffer structure collapsed back to a "
                     "serialized reduce (the overlap knob is being "
                     "bypassed somewhere in the call chain)")))
    if seq != expected:
        findings.append(Finding(
            rule="S-OVERLAP", site=site.name, path=site.path,
            line=site.line,
            message=(f"collective census of `{site.name}` is {seq}, "
                     f"expected exactly {expected} — phase counts / "
                     "permute ordering drifted, so the comm/compute "
                     "interleave the overlap mode promises no longer "
                     "holds")))
    return findings


# ------------------------------------------------------------ repo sites

def _ring_expected() -> List[Tuple[str, str]]:
    """mp2 ring decode: 2 reductions per layer body (O-proj + FFN2),
    each P*(P-1)=2 ppermutes at P=2 — the fori_loop body is traced
    once, so the census carries one layer's sequence."""
    from ..distributed.tp import ring_census

    return ring_census("mp", 2, reductions=2)


def _ep_double_expected() -> List[Tuple[str, str]]:
    """ep2 double-buffered MoE decode: both half-buffer dispatches,
    then combine0 / combine1 (the FFNs between them are not
    collectives), then the replicated-hidden all_gather."""
    # all_to_all carries its axis as a bare name, all_gather as the
    # normalized tuple — the census keeps each primitive's raw form
    a2a = ("all_to_all", "ep")
    return [a2a] * 4 + [("all_gather", str(("ep",)))]


def _sites() -> List[OverlapSite]:
    from .spmd import (_build_moe_ep_decode_double,
                       _build_tp_decode_ring)

    return [
        OverlapSite("overlap.tp_decode_ring", _build_tp_decode_ring,
                    expected=_ring_expected),
        OverlapSite("overlap.moe_ep_double",
                    _build_moe_ep_decode_double,
                    expected=_ep_double_expected),
    ]


OVERLAP_SITES: List[OverlapSite] = _sites()


def run_overlap_pass(sites=None) -> List[Finding]:
    """S-OVERLAP findings over the overlap-site inventory. Returns []
    without checking when the virtual device mesh is unavailable
    (same skip contract as the SPMD pass)."""
    if not mesh_available():
        return []
    findings: List[Finding] = []
    for site in (OVERLAP_SITES if sites is None else sites):
        findings += check_overlap_program(site)
    return waive_from_sources(findings, repo_root())
