"""Preflight gate: no chip time for a program the analyzer already
knows is broken.

``bench.py`` and the ``tools/{decode,bert,train}_profile.py`` ablation
drivers call :func:`preflight` before any TPU work: the full tpu_lint
suite runs on CPU (seconds) and the tool REFUSES to start when any
unwaivered finding exists — a 25-minute s2048 compile must never be
spent proving what the linter already knew. Escape hatches: the tool's
``--no-lint`` flag, or env ``PADDLE_TPU_NO_LINT=1`` (for drivers that
re-exec themselves per rung, the parent vets once and children skip).

Telemetry: every lint run (preflight or CLI) publishes
``lint.{findings,waived}`` counters (profiler.stats), so bench
telemetry blocks record the lint state the numbers were measured under
and ``tools/bench_gate.py`` can ratchet on them.
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List

from .base import Finding

__all__ = ["preflight", "publish_lint_stats"]


def publish_lint_stats(results: Dict[str, List[Finding]]) -> None:
    """Bump ``lint.{findings,waived}`` from one suite run's results."""
    from ..profiler import stats as _stats
    from . import unwaivered

    n_live = sum(len(unwaivered(fs)) for fs in results.values())
    n_waived = sum(sum(1 for f in fs if f.waived)
                   for fs in results.values())
    _stats.inc("lint.findings", n_live)
    _stats.inc("lint.waived", n_waived)
    # snapshot() drops zero-valued counters (sparse by design), so a
    # CLEAN run's lint.findings=0 would be invisible in telemetry and
    # bench_gate could never compare clean-vs-regressed; mirror into
    # gauges (never value-filtered) so every block records the lint
    # state its numbers were measured under.
    _stats.set_gauge("lint.findings", n_live)
    _stats.set_gauge("lint.waived", n_waived)


def preflight(tool: str, no_lint: bool = False) -> None:
    """Run the full analysis suite; SystemExit(2) on unwaivered
    findings. ``no_lint=True`` (the tool's ``--no-lint``) or env
    ``PADDLE_TPU_NO_LINT`` skips."""
    if no_lint or os.environ.get("PADDLE_TPU_NO_LINT"):
        return
    from . import run_all_passes, unwaivered

    print(f"{tool}: tpu_lint preflight...", file=sys.stderr)
    results = run_all_passes()
    publish_lint_stats(results)
    live = [f for fs in results.values() for f in unwaivered(fs)]
    if not live:
        n_waived = sum(1 for fs in results.values()
                       for f in fs if f.waived)
        print(f"{tool}: preflight clean ({len(results)} passes, "
              f"0 unwaivered / {n_waived} waived findings)",
              file=sys.stderr)
        return
    print(f"{tool}: REFUSING to start — {len(live)} unwaivered lint "
          "finding(s); chip time is never spent on a program the "
          "analyzer knows is broken:", file=sys.stderr)
    for f in live:
        print("  " + f.render(), file=sys.stderr)
    print(f"(fix or waive them — see tools/tpu_lint.py — or rerun "
          f"with --no-lint / PADDLE_TPU_NO_LINT=1 to override)",
          file=sys.stderr)
    raise SystemExit(2)
