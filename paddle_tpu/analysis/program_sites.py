"""The repo's whole-program inventory + the jaxpr dry-trace driver.

Where :mod:`.sites` enumerates ``pallas_call`` KERNEL launch sites, this
module enumerates the compiled PROGRAMS the repo actually runs — the
jit'd composite raws from ``ops/dispatch``, the whole-training-step
program (``jit/train_step.py``) and the serving prefill/decode programs
(``inference/engine.py``) — and dry-traces each one to a closed jaxpr
with ``jax.make_jaxpr`` over ShapeDtypeStructs (abstract eval: no
arrays are materialized, no XLA compile happens, so a 13B-shaped decode
program "runs" here in milliseconds on CPU).

The program-level passes consume these traces:

- :mod:`.dtype_flow`  (X-PROMOTE / X-F64)  — silent precision changes
- :mod:`.host_sync`   (X-SYNC / X-CHURN)   — host round-trips in loops
- :mod:`.hbm`         (M-HBM)              — static HBM-peak bound

Each :class:`ProgramSite` declares the properties the passes verify:
``compute_dtype`` ("bfloat16" marks a declared-bf16 serving path whose
matmuls must not silently upcast), ``hot_loop`` (decode-step semantics:
no host callback anywhere, not just inside loop bodies), and
``donate_argnums`` (feeds the donation-aware liveness walk). Findings
anchor to the site's builder, so inline ``tpu-lint: ok(...)`` waivers
work at the registration point.
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["ProgramSite", "TracedProgram", "PROGRAM_SITES",
           "trace_program", "trace_all_programs", "site_for_fn"]


@dataclasses.dataclass
class ProgramSite:
    name: str                   # "inference.decode", "jit.train_step", ...
    build: Callable             # () -> (fn, args) for jax.make_jaxpr
    compute_dtype: Optional[str] = None  # "bfloat16" => declared-bf16 path
    hot_loop: bool = False      # decode-step: host sync forbidden anywhere
    donate_argnums: Tuple[int, ...] = ()
    static_kwargs: Optional[Dict] = None  # jit statics to churn-check
    path: str = ""              # builder location (waiver anchor)
    line: int = 0

    def __post_init__(self):
        code = getattr(self.build, "__code__", None)
        if code is not None and not self.path:
            import os

            repo = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            fname = code.co_filename
            self.path = os.path.relpath(fname, repo) \
                if fname.startswith(repo) else fname
            self.line = code.co_firstlineno


@dataclasses.dataclass
class TracedProgram:
    site: ProgramSite
    closed: object                    # jax.core.ClosedJaxpr
    donated_invars: frozenset         # flat invar indices that may die


def site_for_fn(name: str, fn, args, **kwargs) -> ProgramSite:
    """Ad-hoc site over an explicit (fn, args) pair — the synthetic-
    bad-program tests and one-off checks use this."""
    return ProgramSite(name=name, build=lambda: (fn, args), **kwargs)


@contextlib.contextmanager
def _trace_regime():
    """Trace under x64=False — the regime every compiled program in the
    repo runs with on TPU (mirrors sites._force_tpu_routing)."""
    import jax

    x64 = bool(jax.config.jax_enable_x64)
    try:
        jax.config.update("jax_enable_x64", False)
        yield
    finally:
        jax.config.update("jax_enable_x64", x64)


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _donated_flat(args, donate_argnums) -> frozenset:
    """Map positional donate_argnums to FLAT invar indices of the traced
    jaxpr (jaxpr.invars follow tree_flatten order over the args)."""
    if not donate_argnums:
        return frozenset()
    from jax import tree_util as jtu

    donated = set()
    offset = 0
    dset = set(donate_argnums)
    for i, a in enumerate(args):
        n = len(jtu.tree_leaves(a))
        if i in dset:
            donated.update(range(offset, offset + n))
        offset += n
    return frozenset(donated)


def trace_program(site: ProgramSite) -> TracedProgram:
    """Dry-trace one program site to its closed jaxpr."""
    import jax

    fn, args = site.build()
    with _trace_regime():
        closed = jax.make_jaxpr(fn)(*args)
    return TracedProgram(site=site, closed=closed,
                         donated_invars=_donated_flat(
                             args, site.donate_argnums))


def trace_all_programs(sites=None) -> Dict[str, TracedProgram]:
    """name -> trace for the full program inventory (or ``sites``)."""
    return {s.name: trace_program(s)
            for s in (PROGRAM_SITES if sites is None else sites)}


# --------------------------------------------------------------- builders
# Serving-shaped but tiny: make_jaxpr is abstract, so shapes only affect
# trace time, not memory — the composites use real serving widths, the
# engine programs a scaled-down stack (trace cost is per-eqn, and the
# decode jaxpr is shape-generic over the model dims).

def _build_gelu():
    import jax.numpy as jnp

    from ..nn.functional.activation import gelu

    return gelu.raw_fn, (_sds((32, 8192), jnp.bfloat16),)


def _build_softmax():
    import jax.numpy as jnp

    from ..nn.functional.activation import softmax

    return softmax.raw_fn, (_sds((8, 16, 512, 512), jnp.bfloat16),)


def _build_layer_norm():
    import functools

    import jax.numpy as jnp

    from ..nn.functional.norm import _layer_norm_raw

    fn = functools.partial(_layer_norm_raw, n_norm=1, epsilon=1e-5,
                           has_w=True, has_b=True)
    return fn, (_sds((32, 2048), jnp.bfloat16),
                _sds((2048,), jnp.float32), _sds((2048,), jnp.float32))


def _build_cross_entropy():
    import jax.numpy as jnp

    from ..nn.functional.loss import _cross_entropy_raw

    return _cross_entropy_raw, (_sds((64, 51200), jnp.bfloat16),
                                _sds((64,), jnp.int32))


def _build_train_step():
    """Whole-step program (fwd+bwd+AdamW) over a small MLP — the same
    ``TrainStep._pure_step`` bench.py compiles, traced with its real
    argument assembly (``_build_args``)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
    opt = paddle.optimizer.AdamW(1e-3, parameters=net.parameters())
    step = paddle.jit.TrainStep(net, F.mse_loss, opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 32).astype(np.float32))
    y = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
    return step._pure_step, step._build_args([x], [y])


_ENGINE_CACHE: dict = {}


def _tiny_engine(cast_bf16: bool = True):
    """A serving GenerationEngine over a scaled-down FusedCausalLM
    (d64 L2) with a live paged pool — cached: prefill and decode sites
    share it. With ``cast_bf16`` the stack weights are cast first, so
    the engine's compute dtype matches the serving deployment
    (``_cdtype`` follows the weights) and the DTYPE pass actually
    guards the bf16 contract; the f32 variant exists for the XLA
    memory-analysis cross-check (CPU emulates bf16 through f32 temp
    copies, which would skew the comparison)."""
    if cast_bf16 in _ENGINE_CACHE:
        return _ENGINE_CACHE[cast_bf16]
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..inference.engine import FusedCausalLM, GenerationEngine
    from ..inference.kv_cache import BlockKVCacheManager

    paddle.seed(0)
    model = FusedCausalLM(vocab_size=256, embed_dim=64, num_heads=2,
                          dim_feedforward=128, num_layers=2,
                          max_position=256)
    st = model.stack
    if cast_bf16:
        for n in ("qkv", "out", "ffn1", "ffn2"):
            for suffix in ("weight", "bias"):
                p = getattr(st, f"{n}_{suffix}")
                p._rebind(p._data.astype(jnp.bfloat16))
    eng = GenerationEngine(model, page_size=16, max_length=64)
    b, pages_per_seq = 4, 4
    mgr = BlockKVCacheManager(st.num_layers, st.num_kv_heads,
                              st.head_dim, 16, num_pages=64,
                              dtype=eng._kv_dtype, reserve_scratch=True)
    for i in range(b):
        mgr.allocate(i, 16)
    tables = mgr.block_tables(range(b), pages_per_seq)
    cache = mgr.fresh_cache()
    _ENGINE_CACHE[cast_bf16] = (model, eng, cache, tables, b)
    return _ENGINE_CACHE[cast_bf16]


def _engine_common_args(model, eng, cache, tables):
    return (model.stack._stack(), model.embed._data, eng._head_t,
            model.lnf_scale._data, model.lnf_bias._data)


def _build_prefill():
    import jax.numpy as jnp

    model, eng, cache, tables, b = _tiny_engine()
    head = _engine_common_args(model, eng, cache, tables)
    args = head + (_sds((b, 16), jnp.int32), _sds((b,), jnp.int32),
                   cache.k, cache.v, tables)
    return eng._prefill_fn, args


def _build_decode():
    return build_decode_program(cast_bf16=True)


def build_decode_program(cast_bf16: bool = True):
    """(fn, args) for the k-step decode program; the f32 variant backs
    the memory_analysis cross-check test."""
    import functools

    import jax.numpy as jnp

    model, eng, cache, tables, b = _tiny_engine(cast_bf16)
    head = _engine_common_args(model, eng, cache, tables)
    fn = functools.partial(eng._decode_k_fn, k=8, sample_cfg=None)
    args = head + (_sds((b,), jnp.int32), _sds((b,), jnp.int32),
                   cache.k, cache.v, tables)
    return fn, args


def _build_decode_lora():
    """The adaptered k-step decode program (ISSUE 18): the same decode
    loop with the per-slot adapter ids and the AdapterBank's traced
    ``{proj}_a``/``{proj}_b`` operands riding along — every adapter's
    ragged grouped delta is fused onto the weight stream inside the
    step, so the hot-loop/host-sync and donation contracts must hold
    exactly as on the plain decode program."""
    import functools

    import jax.numpy as jnp

    from ..serving.adapters import AdapterBank

    model, eng, cache, tables, b = _tiny_engine()
    head = _engine_common_args(model, eng, cache, tables)
    bank = AdapterBank.from_stack(model.stack._stack(), slots=4,
                                  rank=8)
    bank.load(bank.random_adapter("site"))
    fn = functools.partial(eng._decode_k_fn, k=8, sample_cfg=None)
    args = head + (_sds((b,), jnp.int32), _sds((b,), jnp.int32),
                   cache.k, cache.v, tables, None, None,
                   _sds((b,), jnp.int32), bank.operands())
    return fn, args


def _build_spec_verify():
    """The speculative-decoding batched verify program (ISSUE 12,
    inference/speculative.py): one streamed prefill-chunk pass over the
    (k+1)-token draft window with the fused accept-prefix/bonus tail.
    Built over a bf16-cast tiny ContinuousBatchingEngine so the DTYPE
    pass guards the serving bf16 contract on the verify path too."""
    import functools

    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..inference.engine import (ContinuousBatchingEngine,
                                    FusedCausalLM)

    paddle.seed(0)
    model = FusedCausalLM(vocab_size=256, embed_dim=64, num_heads=2,
                          dim_feedforward=128, num_layers=2,
                          max_position=256)
    st = model.stack
    for n in ("qkv", "out", "ffn1", "ffn2"):
        for suffix in ("weight", "bias"):
            p = getattr(st, f"{n}_{suffix}")
            p._rebind(p._data.astype(jnp.bfloat16))
    eng = ContinuousBatchingEngine(model, max_batch=4, page_size=16,
                                   max_length=64, speculative="self",
                                   spec_k=4)
    spec = eng._spec
    b, k = eng.max_batch, spec.k
    tables = eng._mgr.block_tables(
        [("slot", i) for i in range(b)], eng._pages_per_seq,
        allow_missing=True)
    fn = functools.partial(spec._verify_fn, k=k)
    args = (eng._gen._weights(), eng._gen._embed(), eng._gen._head_t,
            model.lnf_scale._data, model.lnf_bias._data,
            _sds((b, k + 1), jnp.int32), _sds((b,), jnp.int32),
            _sds((b,), jnp.int32), _sds((b, k), jnp.int32),
            eng._ck, eng._cv, tables)
    return fn, args


def _build_varlen_packed():
    """The packed varlen flash-attention program (ISSUE 13) as the
    dispatch layer compiles it: cu_seqlens ride as TRACED operands
    (the recompile-storm fix), the XLA tile-walk fallback is the
    CPU-traced body. bf16 inputs so the DTYPE pass guards the fp32
    softmax-accumulator waivers."""
    import functools

    import jax.numpy as jnp

    from ..nn.functional.attention import _unpadded_varlen_raw

    fn = functools.partial(_unpadded_varlen_raw, scale=0.088,
                           causal=True)
    T, h, d = 1024, 8, 128
    return fn, (_sds((T, h, d), jnp.bfloat16),
                _sds((T, h, d), jnp.bfloat16),
                _sds((T, h, d), jnp.bfloat16),
                _sds((5,), jnp.int32), _sds((5,), jnp.int32))


def _build_moe_ffn():
    """The no-drop MoE FFN program (ISSUE 15): fp32 router → stable
    sort by expert → two ragged grouped GEMMs → scatter-combine, as
    the dispatch layer compiles it off-TPU (the math-identical XLA
    tile walk). bf16 inputs so the DTYPE pass guards the fp32-router
    waivers; serving-ish expert-bank widths."""
    import functools

    import jax.numpy as jnp

    from ..nn.functional.grouped_gemm import moe_ffn_nodrop

    T, d, dff, E = 256, 512, 1024, 8
    fn = functools.partial(moe_ffn_nodrop, top_k=2, activation="gelu",
                           backend="xla")
    return fn, (_sds((T, d), jnp.bfloat16),
                _sds((d, E), jnp.float32),
                _sds((E, d, dff), jnp.bfloat16),
                _sds((E, dff), jnp.float32),
                _sds((E, dff, d), jnp.bfloat16),
                _sds((E, d), jnp.float32))


def _build_kv_restore():
    """The host-tier KV restore scatter (ISSUE 20): a run of spilled
    pages lands back in the paged pool as one row-indexed scatter,
    pool donated so XLA updates in place instead of copying the whole
    cache. Pool geometry mirrors the serving default (2 layers x 64
    pages worth of rows at serving head widths)."""
    import jax.numpy as jnp

    from ..inference.kv_cache import restore_scatter

    L, P, H, ps, hd = 2, 64, 4, 8, 16
    n = 4       # pages restored per run
    return restore_scatter, (_sds((L * P, H, ps, hd), jnp.bfloat16),
                             _sds((L * n,), jnp.int32),
                             _sds((L * n, H, ps, hd), jnp.bfloat16))


PROGRAM_SITES: List[ProgramSite] = [
    ProgramSite("dispatch.gelu", _build_gelu,
                compute_dtype="bfloat16",
                static_kwargs={"approximate": False}),
    ProgramSite("dispatch.softmax", _build_softmax,
                compute_dtype="bfloat16", static_kwargs={"axis": -1}),
    ProgramSite("dispatch.layer_norm", _build_layer_norm,
                compute_dtype="bfloat16",
                static_kwargs={"n_norm": 1, "epsilon": 1e-5,
                               "has_w": True, "has_b": True}),
    ProgramSite("dispatch.cross_entropy", _build_cross_entropy,
                compute_dtype="bfloat16",
                static_kwargs={"reduction": "mean", "axis": -1}),
    ProgramSite("jit.train_step", _build_train_step,
                donate_argnums=(0, 1)),
    ProgramSite("inference.prefill", _build_prefill,
                compute_dtype="bfloat16", donate_argnums=(7, 8)),
    ProgramSite("inference.decode", _build_decode,
                compute_dtype="bfloat16", hot_loop=True,
                donate_argnums=(7, 8)),
    ProgramSite("inference.decode_lora", _build_decode_lora,
                compute_dtype="bfloat16", hot_loop=True,
                donate_argnums=(7, 8)),
    ProgramSite("serve.verify", _build_spec_verify,
                compute_dtype="bfloat16", donate_argnums=(9, 10)),
    ProgramSite("attn.varlen_packed", _build_varlen_packed,
                compute_dtype="bfloat16"),
    ProgramSite("moe.ffn", _build_moe_ffn, compute_dtype="bfloat16"),
    ProgramSite("serve.kv_restore", _build_kv_restore,
                compute_dtype="bfloat16", donate_argnums=(0,)),
]
