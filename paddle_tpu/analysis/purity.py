"""Pass 3 — trace-purity lint.

An AST pass over the package flagging concretization hazards inside
TRACED code — the class of bug that works in eager/CPU runs and then
explodes (or silently bakes stale state) the first time the same code
is traced for the chip.

What counts as traced code (the contexts the pass scans):

- Pallas kernel bodies (functions passed to ``pl.pallas_call``) and
  ``@pl.when(...)`` sub-bodies — kind ``kernel`` / ``when``;
- control-flow bodies handed to ``lax.fori_loop`` / ``while_loop`` /
  ``scan`` / ``cond`` / ``switch`` — kind ``loop``;
- functions wrapped by ``jax.jit`` — kind ``jit``.

Rules (waivable in-line with ``# tpu-lint: ok(<rule>) -- <reason>``):

- ``P-TRACER-IF``: python ``if``/``while``/ternary on a traced
  parameter — concretizes the tracer (``is None`` identity checks are
  exempt: they never read the value).
- ``P-CONCRETIZE``: ``bool()/int()/float()`` applied to a traced
  parameter.
- ``P-NP-TRACER``: ``np.*`` applied to a traced parameter — silently
  falls back to host numpy via ``__array__`` (a device sync + constant
  bake) or fails to trace.
- ``P-HOST-TIME`` / ``P-HOST-RNG``: ``time.*`` / python ``random.*`` /
  ``np.random.*`` inside traced code — evaluated ONCE at trace time,
  then frozen into every execution.
- ``P-STATE-MUT``: python-state mutation inside ``fori_loop`` / ``scan``
  / ``cond`` / ``while_loop`` bodies (``global``/``nonlocal``, attribute
  stores or ``.append()``-family calls on closed-over objects) — the
  body runs once at trace time, so the mutation happens once, not per
  iteration. Stores through Pallas Refs (params of an enclosing kernel)
  are device stores and exempt.
- ``P-WAIVER``: a ``tpu-lint: ok(...)`` comment with no reason — a
  waiver must document WHY.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, apply_waivers, parse_waivers

__all__ = ["run_purity_file", "run_purity_pass"]

#: call-wrapper name -> traced-context kind
_WRAPPERS = {
    "pallas_call": "kernel",
    "fori_loop": "loop",
    "while_loop": "loop",
    "scan": "loop",
    "cond": "loop",
    "switch": "loop",
    "jit": "jit",
}

_MUTATORS = {"append", "extend", "insert", "update", "add", "pop",
             "setdefault", "remove", "clear", "discard"}

#: a waiver-looking comment; ``ok(<`` is documentation of the syntax
#: itself (placeholder brackets), not a waiver attempt
_BARE_WAIVER_RE = re.compile(r"#\s*tpu-lint:\s*ok\b(?!\(<)")


def _attr_tail(node) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _attr_chain(node) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


def _params_of(fn) -> Set[str]:
    a = fn.args
    names = [p.arg for p in
             list(getattr(a, "posonlyargs", [])) + list(a.args)
             + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


#: attribute reads that are static under trace (aval metadata)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize"}


def _names_in(node) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _hazard_names(node) -> Set[str]:
    """Names in ``node`` whose VALUE would be read under trace —
    excludes structural accesses that stay python-static: ``len(x)``,
    ``isinstance(x, ...)``, and ``x.shape``/``.ndim``/``.dtype``/etc."""
    out: Set[str] = set()

    def walk(n):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id in ("len", "isinstance", "type"):
            return
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return
        if isinstance(n, ast.Name):
            out.add(n.id)
        for child in ast.iter_child_nodes(n):
            walk(child)

    walk(node)
    return out


def _is_none_identity(test) -> bool:
    """``x is None`` / ``x is not None`` (possibly under BoolOp/not):
    identity checks never concretize a tracer."""
    if isinstance(test, ast.Compare):
        return all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in test.ops)
    if isinstance(test, ast.BoolOp):
        return all(_is_none_identity(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_none_identity(test.operand)
    return False


class _FileLint:
    def __init__(self, rel_path: str, tree: ast.AST):
        self.rel = rel_path
        self.tree = tree
        self.findings: List[Finding] = []
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # name -> FunctionDef nodes (for resolving fn names passed to
        # wrappers; local names, so collisions are harmless)
        self.defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                self.defs.setdefault(node.name, []).append(node)

    # ---------------------------------------------------- traced contexts
    def traced_contexts(self) -> List[Tuple[ast.AST, str]]:
        out: List[Tuple[ast.AST, str]] = []
        seen: Set[ast.AST] = set()

        def mark(fn_node, kind):
            if fn_node is not None and fn_node not in seen:
                seen.add(fn_node)
                out.append((fn_node, kind))

        def ancestors(n):
            out = []
            cur = self.parents.get(n)
            while cur is not None:
                out.append(cur)
                cur = self.parents.get(cur)
            return out

        def resolve(arg):
            if isinstance(arg, ast.Lambda):
                return arg
            if isinstance(arg, ast.Name):
                cands = self.defs.get(arg.id)
                if not cands:
                    return None
                if len(cands) == 1:
                    return cands[0]
                # several same-named defs (every kernel is `kernel`,
                # every loop body `body`): pick the one whose enclosing
                # scope is the nearest ancestor of this call site
                chain = ancestors(arg)
                best, best_depth = cands[-1], -1
                for c in cands:
                    parent = self.parents.get(c)
                    if parent in chain:
                        depth = len(chain) - chain.index(parent)
                        if depth > best_depth:
                            best, best_depth = c, depth
                return best
            if (isinstance(arg, ast.Call)
                    and _attr_tail(arg.func) == "partial" and arg.args):
                return resolve(arg.args[0])
            return None

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                kind = _WRAPPERS.get(_attr_tail(node.func) or "")
                if kind:
                    for arg in node.args:
                        fn = resolve(arg)
                        if fn is not None:
                            mark(fn, kind)
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    tail = _attr_tail(dec.func if isinstance(dec, ast.Call)
                                      else dec)
                    if tail == "when":
                        mark(node, "when")
                    elif tail == "jit":
                        mark(node, "jit")
        return out

    # -------------------------------------------------------------- rules
    def _flag(self, rule, node, msg):
        self.findings.append(Finding(
            rule=rule, path=self.rel, line=getattr(node, "lineno", 0),
            message=msg))

    def _enclosing_param_names(self, node) -> Set[str]:
        """Params of every enclosing FunctionDef/Lambda (Pallas Refs
        closed over by pl.when/loop bodies live here)."""
        names: Set[str] = set()
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.Lambda)):
                names |= _params_of(cur)
            cur = self.parents.get(cur)
        return names

    def lint_context(self, fn, kind: str) -> None:
        params = _params_of(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]

        local_stores: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            local_stores.add(sub.id)
            elif isinstance(node, (ast.For, ast.comprehension)):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        local_stores.add(sub.id)

        enclosing_params = self._enclosing_param_names(fn)

        for stmt in body:
            for node in ast.walk(stmt):
                # --- tracer concretization ---------------------------
                if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                    test = node.test
                    hit = _hazard_names(test) & params
                    if not _is_none_identity(test) and hit:
                        which = {ast.If: "if", ast.While: "while",
                                 ast.IfExp: "conditional expression"}[
                                     type(node)]
                        self._flag(
                            "P-TRACER-IF", node,
                            f"python {which} on traced value(s) "
                            f"{sorted(hit)} inside "
                            f"a {kind} body — concretizes the tracer; "
                            "use lax.cond/select or pl.when")
                if isinstance(node, ast.Call):
                    tail = _attr_tail(node.func)
                    chain = _attr_chain(node.func)
                    arg_names: Set[str] = set()
                    for a in list(node.args) + [kw.value
                                                for kw in node.keywords]:
                        arg_names |= _hazard_names(a)
                    if (isinstance(node.func, ast.Name)
                            and node.func.id in ("bool", "int", "float")
                            and arg_names & params):
                        self._flag(
                            "P-CONCRETIZE", node,
                            f"{node.func.id}() on traced value(s) "
                            f"{sorted(arg_names & params)} inside a "
                            f"{kind} body — forces a device sync / "
                            "trace error")
                    if (chain and chain[0] in ("np", "numpy")
                            and chain[1:2] != ["random"]
                            and arg_names & params):
                        self._flag(
                            "P-NP-TRACER", node,
                            f"np.{'.'.join(chain[1:])} applied to traced "
                            f"value(s) {sorted(arg_names & params)} — "
                            "host numpy bakes a constant (or fails) "
                            "under trace; use jnp")
                    if chain and chain[0] == "time":
                        self._flag(
                            "P-HOST-TIME", node,
                            f"time.{'.'.join(chain[1:])}() inside a "
                            f"{kind} body runs ONCE at trace time")
                    if chain and (chain[0] == "random"
                                  or chain[:2] == ["np", "random"]
                                  or chain[:2] == ["numpy", "random"]):
                        self._flag(
                            "P-HOST-RNG", node,
                            f"host RNG {'.'.join(chain)} inside a {kind} "
                            "body is frozen at trace time; use jax.random "
                            "with a threaded key")
                # --- python-state mutation in loop bodies ------------
                if kind == "loop":
                    self._lint_state_mut(node, params, local_stores,
                                         enclosing_params)

    def _lint_state_mut(self, node, params, local_stores,
                        enclosing_params) -> None:
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            self._flag(
                "P-STATE-MUT", node,
                f"{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                f" {', '.join(node.names)} inside a traced loop body — "
                "the body runs once at trace time, not per iteration")
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                base = t
                kinds = []
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    kinds.append(type(base))
                    base = base.value
                if not kinds or not isinstance(base, ast.Name):
                    continue
                name = base.id
                if name in params or name in local_stores:
                    continue
                if ast.Subscript in kinds and name in enclosing_params:
                    continue  # Pallas Ref store through a kernel param
                self._flag(
                    "P-STATE-MUT", node,
                    f"store into closed-over `{name}` inside a traced "
                    "loop body happens once at trace time — carry it "
                    "through the loop state instead")
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            if (node.func.attr in _MUTATORS
                    and isinstance(node.func.value, ast.Name)):
                name = node.func.value.id
                if (name not in params and name not in local_stores
                        and name not in enclosing_params):
                    self._flag(
                        "P-STATE-MUT", node,
                        f"`{name}.{node.func.attr}(...)` mutates "
                        "closed-over python state inside a traced loop "
                        "body — runs once at trace time")

    def run(self) -> List[Finding]:
        for fn, kind in self.traced_contexts():
            self.lint_context(fn, kind)
        return self.findings


def _waiver_hygiene(rel: str, source: str) -> List[Finding]:
    good = parse_waivers(source)
    out = []
    for i, line in enumerate(source.splitlines(), start=1):
        if _BARE_WAIVER_RE.search(line) and i not in good:
            out.append(Finding(
                rule="P-WAIVER", path=rel, line=i,
                message="waiver without a rule id + reason: use "
                        "`# tpu-lint: ok(<rule>) -- <reason>`"))
    return out


def run_purity_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    rel = rel or path
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rule="P-SYNTAX", path=rel, line=e.lineno or 0,
                        message=f"unparsable: {e.msg}")]
    findings = _FileLint(rel, tree).run()
    findings += _waiver_hygiene(rel, source)
    apply_waivers(findings, {rel: parse_waivers(source)})
    return findings


def run_purity_pass(pkg_root: Optional[str] = None) -> List[Finding]:
    """Lint every .py under the package root (default: paddle_tpu/)."""
    if pkg_root is None:
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    findings: List[Finding] = []
    base = os.path.dirname(pkg_root)
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                findings += run_purity_file(
                    path, os.path.relpath(path, base))
    return findings
