"""The repo's Pallas kernel inventory + the interpret-free dry-trace
driver that exercises every site through the audit shim.

Each :class:`KernelSite` names one ``pallas_call`` site, builds a
representative serving-shaped launch (as ShapeDtypeStructs — no real
arrays), and dry-traces it with ``jax.eval_shape`` under
``record_pallas_calls``. Abstract evaluation captures the full launch
spec without lowering to Mosaic, so this runs on CPU in milliseconds
per kernel, with the exact BlockSpecs/grid/scratch the chip would get.

``expected_vmem`` is an INDEPENDENT hand-written block list per site
(kept in sync with the kernel by eye, not by code): the tier-1
regression test asserts the analyzer's footprint over the shim-recorded
spec equals this closed form, so either the analyzer drifting or a
kernel's geometry changing silently fails CI until both are
re-reconciled.

The TPU-only routing gates (``_on_tpu``) are monkeypatched for the
duration of a dry-trace so the Pallas path is taken off-chip; x64 is
disabled around each trace to mirror the on-TPU tracing regime (the
stock flash kernel's index maps require it).
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import Callable, List, Optional

from .audit import PallasCallRecord, record_pallas_calls
from .geometry import tile_padded_bytes as _B

__all__ = ["KernelSite", "KERNEL_SITES", "trace_site", "trace_all_sites"]


@dataclasses.dataclass
class KernelSite:
    name: str                 # "stream_linear.bf16", ...
    module: str               # module that owns the pallas_call
    build: Callable           # () -> (fn, args) for jax.eval_shape
    expected_vmem: Optional[Callable[[], int]]  # closed-form footprint
    n_calls: int = 1          # pallas_calls the dry-trace must record


@contextlib.contextmanager
def _force_tpu_routing():
    """Patch the kernel modules' ``_on_tpu`` gates so dry-traces take
    the Pallas path off-chip, and trace under x64=False (the regime the
    kernels are written for — see paged_attention._enable_x64)."""
    import jax

    import paddle_tpu.nn.functional.attention as att
    import paddle_tpu.nn.functional.flash_varlen as fv
    import paddle_tpu.nn.functional.grouped_gemm as gg
    import paddle_tpu.nn.functional.lora as lora
    import paddle_tpu.nn.functional.stream_linear as sl

    # lora.py binds grouped_gemm's _on_tpu by name at import, so it
    # carries its own module-level reference to patch
    saved = [(sl, "_on_tpu", sl._on_tpu), (att, "_on_tpu", att._on_tpu),
             (fv, "_on_tpu", fv._on_tpu), (gg, "_on_tpu", gg._on_tpu),
             (lora, "_on_tpu", lora._on_tpu)]
    x64 = bool(jax.config.jax_enable_x64)
    try:
        for mod, name, _ in saved:
            setattr(mod, name, lambda: True)
        jax.config.update("jax_enable_x64", False)
        yield
    finally:
        for mod, name, orig in saved:
            setattr(mod, name, orig)
        jax.config.update("jax_enable_x64", x64)


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


# --------------------------------------------------------------- builders
# Representative serving shapes: GPT-1.3B-ish projections (d=2048,
# dff=8192), a b=8 GQA-free decode batch over a 16-token-page pool with
# 1024-token stream chunks, and a bert-ish s=512 flash block.

def _build_stream_linear():
    import jax.numpy as jnp

    import paddle_tpu.nn.functional.stream_linear as sl

    def fn(x, w):
        return sl.stream_linear(x, w)

    return fn, (_sds((32, 2048), jnp.bfloat16),
                _sds((2048, 8192), jnp.bfloat16))


def _expected_stream_linear():
    # bn = 2048 (8 MiB bf16 target / K=2048 rows), nb = 4, Mp = 32
    return (_B((32, 2048), "bfloat16")           # x, resident
            + 2 * _B((1, 2048, 2048), "bfloat16")  # w stream, dbl-buffered
            + 2 * _B((32, 2048), "bfloat16"))      # out blocks, streamed


def _build_stream_linear_a8w8():
    import jax.numpy as jnp

    import paddle_tpu.nn.functional.stream_linear as sl

    def fn(x, w, s):
        return sl.stream_linear(x, w, scale=s, act_quant=True)

    return fn, (_sds((32, 2048), jnp.bfloat16),
                _sds((2048, 8192), jnp.int8),
                _sds((8192,), jnp.float32))


def _expected_stream_linear_a8w8():
    # bn = 2048 (4 MiB int8 target), nb = 4, Mp = 32 (int8 sublane tile)
    return (_B((32, 2048), "int8")                 # x_q, resident
            + _B((32, 1), "float32")               # per-token scales
            + 2 * _B((1, 2048, 2048), "int8")      # w stream
            + 2 * _B((1, 1, 2048), "float32")      # dequant scales
            + 2 * _B((32, 2048), "bfloat16"))      # out blocks


def _build_stream_layer_tail():
    import jax.numpy as jnp

    import paddle_tpu.nn.functional.stream_linear as sl

    L, d, dff, nq = 4, 2048, 8192, 3 * 2048
    bf = jnp.bfloat16

    def fn(att, h, wo, w1, w2, bo, b1, b2, ln2s, ln2b, wq, bq, ln1s,
           ln1b):
        return sl.stream_layer_tail(
            att, h, wo, w1, w2, layer=0, bo=bo, b1=b1, b2=b2,
            ln2_scale=ln2s, ln2_bias=ln2b, epsilon=1e-5,
            activation="gelu",
            next_qkv={"w": wq, "b": bq, "ln_s": ln1s, "ln_b": ln1b,
                      "layer": 1},
            interpret=True)

    args = (_sds((32, d), bf), _sds((32, d), bf),
            _sds((L, d, d), bf), _sds((L, d, dff), bf),
            _sds((L, dff, d), bf),
            _sds((L, d), bf), _sds((L, dff), bf), _sds((L, d), bf),
            _sds((L, d), bf), _sds((L, d), bf),
            _sds((L, d, nq), bf), _sds((L, nq), bf),
            _sds((L, d), bf), _sds((L, d), bf))
    return fn, args


def _expected_stream_layer_tail():
    # bn_o = bn_f = bn_q = 512 (2 MiB grouped per-stream target);
    # grid = nb_o + nb_f + nb_q = 4 + 16 + 12
    d, dff, nq = 2048, 8192, 3 * 2048
    bf = "bfloat16"
    return (
        _B((32, d), bf) + _B((32, d), bf)          # att, h: resident
        + 2 * _B((1, d, 512), bf)                  # Wo stream
        + _B((1, 1, d), bf)                        # bo (whole row)
        + 2 * _B((1, d, 512), bf)                  # W1 stream
        + 2 * _B((1, 1, 512), bf)                  # b1 blocks
        + 2 * _B((1, 512, d), bf)                  # W2 stream
        + _B((1, 1, d), bf)                        # b2 (whole row)
        + _B((1, d), bf) * 2                       # ln2 scale+bias
        + 2 * _B((1, d, 512), bf)                  # Wq prefetch stream
        + 2 * _B((1, 1, 512), bf)                  # bq blocks
        + _B((1, d), bf) * 2                       # ln1 scale+bias
        + _B((32, d), bf)                          # out_h
        + 2 * _B((32, 512), bf)                    # out_q blocks
        + _B((32, d), "float32") * 2               # s_h2 + s_acc scratch
        + _B((32, d), bf))                         # s_hn scratch


_POOL = dict(b=8, n_kv=8, d=128, ps=16)


def _paged_args(P, pp, dtype_name="bfloat16"):
    import jax.numpy as jnp

    b, n_kv, d, ps = (_POOL[k] for k in ("b", "n_kv", "d", "ps"))
    dt = getattr(jnp, dtype_name)
    return (_sds((b, n_kv, d), dt),
            _sds((P, n_kv, ps, d), dt),
            _sds((P, n_kv, ps, d), dt),
            _sds((b,), jnp.int32),
            _sds((b, pp), jnp.int32))


def _build_fused_paged():
    from paddle_tpu.nn.functional.paged_attention import _fused_paged

    q, kc, vc, lens, tables = _paged_args(P=64, pp=8)

    def fn(q, kc, vc, lens, tables):
        return _fused_paged(q, kc, vc, lens, tables)

    return fn, (q, kc, vc, lens, tables)


def _expected_fused_paged():
    b, n_kv, d, ps = (_POOL[k] for k in ("b", "n_kv", "d", "ps"))
    return (2 * _B((1, n_kv, d), "bfloat16")       # q block per sequence
            + 2 * _B((1, n_kv, d), "float32")      # out block
            + 2 * _B((2, n_kv, ps, d), "bfloat16"))  # k_buf + v_buf scratch


def _build_stream_paged():
    from paddle_tpu.nn.functional.paged_attention import _stream_paged

    q, kc, vc, lens, tables = _paged_args(P=128, pp=8)

    def fn(q, kc, vc, lens, tables):
        return _stream_paged(q, kc, vc, lens, tables, pool_base=0,
                             pool_pages=128)

    return fn, (q, kc, vc, lens, tables)


def _expected_stream_paged():
    # cp = 64 pages -> C = 1024 tokens/chunk, nchunks = 2, bg = 8
    b, n_kv, d, ps = (_POOL[k] for k in ("b", "n_kv", "d", "ps"))
    return (_B((n_kv, b, d), "bfloat16")           # qt, resident
            + 2 * _B((1, b, 1024), "int32")        # ownership mask chunk
            + 2 * 2 * _B((64, n_kv, ps, d), "bfloat16")  # k+v chunk streams
            + _B((n_kv, b, d), "float32")          # out
            + 2 * _B((n_kv, b), "float32")         # m + l scratch
            + _B((n_kv, b, d), "float32"))         # acc scratch


def _build_decode_inplace():
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.paged_attention import (
        paged_decode_attention_inplace)

    q, kc, vc, lens, tables = _paged_args(P=128, pp=8)
    nk = _sds((_POOL["b"], _POOL["n_kv"], _POOL["d"]), jnp.bfloat16)

    def fn(q, nk, nv, kc, vc, lens, tables):
        return paged_decode_attention_inplace(
            q, nk, nv, kc, vc, lens, tables, pool_base=0, pool_pages=128)

    return fn, (q, nk, nk, kc, vc, lens, tables)


def _expected_decode_inplace():
    b, n_kv, d, ps = (_POOL[k] for k in ("b", "n_kv", "d", "ps"))
    bf = "bfloat16"
    return (_B((n_kv, b, d), bf)                   # qt
            + 2 * _B((1, b, 1024), "int32")        # ownership mask chunk
            + 2 * _B((n_kv, b, d), bf)             # nk_t + nv_t operands
            + 2 * _B((b, n_kv, ps, d), bf)         # nk_w + nv_w page patch
            + _B((b, 1, ps, 1), "float32")         # slot selector
            + _B((n_kv, b, d), "float32")          # out
            + 2 * 2 * _B((64, n_kv, ps, d), bf)    # kb + vb chunk scratch
            + 2 * _B((b, n_kv, ps, d), bf)         # pgk + pgv page RMW
            + 2 * _B((n_kv, b), "float32")         # m + l
            + _B((n_kv, b, d), "float32"))         # acc


def _build_decode_inplace_q():
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.paged_attention import (
        paged_decode_attention_inplace_q)

    b, n_kv, d, ps = (_POOL[k] for k in ("b", "n_kv", "d", "ps"))
    P = 128
    q = _sds((b, n_kv, d), jnp.bfloat16)
    nk = _sds((b, n_kv, d), jnp.bfloat16)
    pool = _sds((P, n_kv, ps, d), jnp.int8)
    plane = _sds((n_kv, P * ps), jnp.float32)
    lens = _sds((b,), jnp.int32)
    tables = _sds((b, 8), jnp.int32)

    def fn(q, nk, nv, kq, ks, vq, vs, lens, tables):
        return paged_decode_attention_inplace_q(
            q, nk, nv, kq, ks, vq, vs, lens, tables, pool_base=0,
            pool_pages=P)

    return fn, (q, nk, nk, pool, plane, pool, plane, lens, tables)


def _expected_decode_inplace_q():
    # rows_pp = n_kv*ps = 128 int8 rows/page; C = 1024, nchunks = 2
    b, n_kv, d, ps = (_POOL[k] for k in ("b", "n_kv", "d", "ps"))
    rp = n_kv * ps
    return (_B((n_kv, b, d), "int8")               # qq
            + _B((n_kv, b), "float32")             # qs
            + 2 * _B((1, b, 1024), "int32")        # ownership mask chunk
            + 2 * _B((n_kv, b, d), "bfloat16")     # nk_t + nv_t (exact)
            + 2 * _B((b, rp, d), "int8")           # quantized page patches
            + _B((b, rp, 1), "float32")            # flat slot selector
            + 2 * _B((1, 1024), "float32")         # plane patch column sel
            + 2 * 2 * _B((n_kv, 1024), "float32")  # kval+vval patch values
            + 2 * 2 * _B((n_kv, 1024), "float32")  # ks+vs plane blocks in
            + _B((n_kv, b, d), "float32")          # out
            + 2 * 2 * _B((n_kv, 1024), "float32")  # kso+vso plane blocks out
            + 2 * _B((2, 64, rp, d), "int8")       # kb + vb chunk scratch
            + 2 * _B((b, rp, d), "int8")           # pgq + pgv page RMW
            + 2 * _B((n_kv, b), "float32")         # m + l
            + _B((n_kv, b, d), "float32"))         # acc


def _build_flash():
    import jax.numpy as jnp

    import paddle_tpu.nn.functional.attention as att

    q = _sds((2, 512, 8, 128), jnp.float32)

    def fn(q, k, v):
        return att._attention_raw(q, k, v, causal=True)

    return fn, (q, q, q)


# varlen flash (ISSUE 13): a serving-shaped packed batch — 8 heads,
# d128, 1024 tokens in 4 segments, 128x128 tiles, bf16
_VARLEN = dict(h=8, T=1024, d=128, nseg=4, bq=128, bk=128)


def _varlen_args():
    import jax.numpy as jnp

    h, T, d, nseg = (_VARLEN[k] for k in ("h", "T", "d", "nseg"))
    return (_sds((T, h, d), jnp.bfloat16),
            _sds((T, h, d), jnp.bfloat16),
            _sds((T, h, d), jnp.bfloat16),
            _sds((nseg + 1,), jnp.int32),
            _sds((nseg + 1,), jnp.int32))


def _build_flash_varlen_fwd():
    from paddle_tpu.nn.functional.flash_varlen import flash_varlen_packed

    def fn(q, k, v, cu_q, cu_k):
        return flash_varlen_packed(q, k, v, cu_q, cu_k, causal=True,
                                   backend="pallas")

    return fn, _varlen_args()


def _expected_flash_varlen_fwd():
    h, d, bq, bk = (_VARLEN[k] for k in ("h", "d", "bq", "bk"))
    return (2 * _B((2, bq), "int32")               # qmeta tile stream
            + 2 * _B((h, bq, d), "bfloat16")       # q tile stream
            + 2 * _B((h, bq, d), "float32")        # out tile stream
            + 2 * _B((h, bq), "float32")           # lse tile stream
            + _B((2, h, bk, d), "bfloat16") * 2    # k + v DMA scratch
            + _B((2, 2, bk), "int32"))             # kmeta DMA scratch


def _build_flash_varlen_bwd():
    import jax

    from paddle_tpu.nn.functional.flash_varlen import flash_varlen_packed

    def fn(q, k, v, cu_q, cu_k):
        def loss(q, k, v):
            out = flash_varlen_packed(q, k, v, cu_q, cu_k, causal=True,
                                      backend="pallas")
            return jax.numpy.sum(out.astype(jax.numpy.float32))

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    return fn, _varlen_args()


def _expected_flash_varlen_bwd():
    h, d, bq, bk = (_VARLEN[k] for k in ("h", "d", "bq", "bk"))
    bf = "bfloat16"
    fwd = _expected_flash_varlen_fwd()
    dq = (2 * _B((2, bq), "int32")                 # qmeta tile stream
          + 2 * _B((h, bq, d), bf)                 # q tile stream
          + 2 * _B((h, bq, d), bf)                 # dout tile stream
          + 2 * _B((2, h, bq), "float32")          # lse+delta stream
          + 2 * _B((h, bq, d), "float32")          # dq tile stream
          + _B((2, h, bk, d), bf) * 2              # k + v DMA scratch
          + _B((2, 2, bk), "int32"))               # kmeta DMA scratch
    dkv = (2 * _B((2, bk), "int32")                # kmeta tile stream
           + 2 * _B((h, bk, d), bf) * 2            # k + v tile streams
           + 2 * _B((h, bk, d), "float32") * 2     # dk + dv tile streams
           + _B((2, h, bq, d), bf) * 2             # q + dout DMA scratch
           + _B((2, 2, h, bq), "float32")          # lse+delta DMA scratch
           + _B((2, 2, bq), "int32"))              # qmeta DMA scratch
    return fwd + dq + dkv


def _build_flash_varlen_paged():
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.flash_varlen import (
        paged_prefill_attention)

    b, n_kv, d, ps = (_POOL[k] for k in ("b", "n_kv", "d", "ps"))
    c, pp, P = 64, 16, 256

    def fn(q, kc, vc, tables, start):
        return paged_prefill_attention(q, kc, vc, tables, start,
                                       n_kv=n_kv, backend="pallas")

    return fn, (_sds((b, c, n_kv, d), jnp.bfloat16),
                _sds((P, n_kv, ps, d), jnp.bfloat16),
                _sds((P, n_kv, ps, d), jnp.bfloat16),
                _sds((b, pp), jnp.int32),
                _sds((b,), jnp.int32))


def _expected_flash_varlen_paged():
    # bk = 8 pages x ps16 = 128 tokens; q/out blocks stream per row
    n_kv, d, ps = (_POOL[k] for k in ("n_kv", "d", "ps"))
    c, npp = 64, 8
    return (2 * _B((1, n_kv, c, d), "bfloat16")    # q row stream
            + 2 * _B((1, n_kv, c, d), "float32")   # out row stream
            + _B((2, npp, n_kv, ps, d), "bfloat16") * 2)  # k+v page DMA


# ragged grouped-GEMM MoE kernel (ISSUE 15): a serving-shaped FFN1
# bank — 8 experts, d=2048 -> dff=8192, 1024 expert-sorted rows, bf16
# weights. bn = 2048 (8 MiB bf16 stream target / K=2048), bm = 128;
# nwu = 1024/128 + 2*8 + 1 = 25 work units.
_GROUPED = dict(T=1024, K=2048, N=8192, E=8, bm=128, bn=2048)


def _grouped_args():
    import jax.numpy as jnp

    T, K, N, E = (_GROUPED[k] for k in ("T", "K", "N", "E"))
    return (_sds((T, K), jnp.bfloat16),
            _sds((E, K, N), jnp.bfloat16),
            _sds((E, N), jnp.float32),
            _sds((E + 1,), jnp.int32))


def _build_grouped_gemm_fwd():
    from paddle_tpu.nn.functional.grouped_gemm import grouped_gemm

    def fn(x, w, b, offsets):
        return grouped_gemm(x, w, offsets, bias=b, activation="gelu",
                            backend="pallas")

    return fn, _grouped_args()


def _expected_grouped_gemm_fwd():
    K, N, bm, bn = (_GROUPED[k] for k in ("K", "N", "bm", "bn"))
    return (_B((bm, K), "bfloat16")            # x row tile (dynamic map)
            + 2 * _B((1, K, bn), "bfloat16")   # expert weight stream
            + 2 * _B((1, 1, bn), "float32")    # bias blocks
            + 2 * _B((bm, bn), "float32"))     # out tile stream


def _build_grouped_gemm_bwd():
    import jax

    from paddle_tpu.nn.functional.grouped_gemm import grouped_gemm

    def fn(x, w, b, offsets):
        def loss(x, w, b):
            y = grouped_gemm(x, w, offsets, bias=b, activation="gelu",
                             backend="pallas")
            return jax.numpy.sum(y.astype(jax.numpy.float32))

        return jax.grad(loss, argnums=(0, 1, 2))(x, w, b)

    return fn, _grouped_args()


def _expected_grouped_gemm_bwd():
    # grad trace records fwd + pre-activation recompute (same geometry
    # as fwd), the dx walk against the transposed bank (bn = 512: the
    # 8 MiB bf16 target over K = dff = 8192), and the dw segment
    # accumulation
    K, N, bm, bn = (_GROUPED[k] for k in ("K", "N", "bm", "bn"))
    bn_dx = 512
    fwd = _expected_grouped_gemm_fwd()
    dx = (_B((bm, N), "float32")               # dz row tile (dynamic)
          + 2 * _B((1, N, bn_dx), "bfloat16")  # transposed weight stream
          + 2 * _B((1, 1, bn_dx), "float32")   # zero-bias blocks
          + 2 * _B((bm, bn_dx), "float32"))    # dx tile stream
    dw = (_B((bm, K), "bfloat16")              # x row tile (dynamic)
          + 2 * _B((bm, bn), "float32")        # dz tile stream
          + 2 * _B((1, K, bn), "float32"))     # dw expert-block stream
    return 2 * fwd + dx + dw


# batched multi-LoRA delta kernel (ISSUE 18): a serving-shaped ffn1
# delta bank — 8 adapter slots, rank 8 padded to the bf16 sublane tile
# (R = 16), d=2048 -> dff=8192, 1024 adapter-sorted rows. The bank
# dtype drives the same bm=128 / bn=2048 stream geometry as the MoE
# bank above; each work unit chains TWO dots (down to the rank, back
# up) inside one launch.
_LORA = dict(T=1024, K=2048, N=8192, S=8, R=16, bm=128, bn=2048)


def _build_lora_delta():
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.lora import lora_delta

    T, K, N, S, R = (_LORA[k] for k in ("T", "K", "N", "S", "R"))

    def fn(x, a, b, offsets):
        return lora_delta(x, a, b, offsets, backend="pallas")

    return fn, (_sds((T, K), jnp.bfloat16),
                _sds((S, K, R), jnp.bfloat16),
                _sds((S, R, N), jnp.bfloat16),
                _sds((S + 1,), jnp.int32))


def _expected_lora_delta():
    # x and the A tile index only on the work unit (the slow grid
    # axis), so neither double-buffers against the bn walk; the A
    # tile's R=16 lane axis pads to the full 128-lane tile
    K, R, bm, bn = (_LORA[k] for k in ("K", "R", "bm", "bn"))
    return (_B((bm, K), "bfloat16")            # x row tile (dynamic map)
            + _B((1, K, R), "bfloat16")        # A down-proj tile
            + 2 * _B((1, R, bn), "bfloat16")   # B up-proj stream
            + 2 * _B((bm, bn), "float32"))     # delta tile stream


KERNEL_SITES: List[KernelSite] = [
    KernelSite("stream_linear.bf16", "nn/functional/stream_linear.py",
               _build_stream_linear, _expected_stream_linear),
    KernelSite("stream_linear.a8w8", "nn/functional/stream_linear.py",
               _build_stream_linear_a8w8, _expected_stream_linear_a8w8),
    KernelSite("stream_linear.layer_tail",
               "nn/functional/stream_linear.py",
               _build_stream_layer_tail, _expected_stream_layer_tail),
    KernelSite("paged_attention.fused", "nn/functional/paged_attention.py",
               _build_fused_paged, _expected_fused_paged),
    KernelSite("paged_attention.stream",
               "nn/functional/paged_attention.py",
               _build_stream_paged, _expected_stream_paged),
    KernelSite("paged_attention.decode_inplace",
               "nn/functional/paged_attention.py",
               _build_decode_inplace, _expected_decode_inplace),
    KernelSite("paged_attention.decode_inplace_q",
               "nn/functional/paged_attention.py",
               _build_decode_inplace_q, _expected_decode_inplace_q),
    # the stock jax flash kernel: geometry-checked but no hand block
    # list (its internals are jax's, not ours)
    KernelSite("attention.flash", "nn/functional/attention.py",
               _build_flash, None),
    KernelSite("flash_varlen.packed_fwd",
               "nn/functional/flash_varlen.py",
               _build_flash_varlen_fwd, _expected_flash_varlen_fwd),
    # grad trace records fwd (residuals) + dq + dk/dv kernels
    KernelSite("flash_varlen.packed_bwd",
               "nn/functional/flash_varlen.py",
               _build_flash_varlen_bwd, _expected_flash_varlen_bwd,
               n_calls=3),
    KernelSite("flash_varlen.paged", "nn/functional/flash_varlen.py",
               _build_flash_varlen_paged, _expected_flash_varlen_paged),
    # ragged grouped-GEMM MoE (ISSUE 15): fwd, and the grad trace's
    # fwd + pre-activation recompute + dx walk + dw segment kernel
    KernelSite("grouped_gemm.fwd", "nn/functional/grouped_gemm.py",
               _build_grouped_gemm_fwd, _expected_grouped_gemm_fwd),
    KernelSite("grouped_gemm.bwd", "nn/functional/grouped_gemm.py",
               _build_grouped_gemm_bwd, _expected_grouped_gemm_bwd,
               n_calls=4),
    # batched multi-LoRA delta (ISSUE 18): one ragged launch carrying
    # every adapter's x·A·B for an adapter-sorted chunk
    KernelSite("lora.delta", "nn/functional/lora.py",
               _build_lora_delta, _expected_lora_delta),
]


def trace_site(site: KernelSite) -> List[PallasCallRecord]:
    """Dry-trace one site; returns its recorded launch specs."""
    import jax

    fn, args = site.build()
    with _force_tpu_routing(), record_pallas_calls() as records:
        jax.eval_shape(fn, *args)
    if len(records) != site.n_calls:
        raise AssertionError(
            f"{site.name}: expected {site.n_calls} pallas_call(s), "
            f"recorded {len(records)} — kernel routing changed; update "
            "analysis/sites.py")
    return records


def trace_all_sites():
    """name -> records for the full kernel inventory."""
    return {site.name: trace_site(site) for site in KERNEL_SITES}
