"""Pass 7 — SPMD: collective-safety lint on a virtual 8-device mesh.

GSPMD "fixes" a missing sharding annotation by inserting collectives:
an accidental all-gather silently replicates a sharded tensor (HBM and
ICI paid per step, no error anywhere), and asymmetric collective
sequences across branches deadlock a real mesh while running fine on
one host. Both are CPU-detectable: the repo's distributed surfaces
(mp_layers column/row linears, ring attention, the MoE EP exchange)
are dry-traced and XLA-compiled on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8`` — the same fake-device
trick tests/conftest.py uses), and the partitioned HLO + jaxpr are
linted:

- ``S-GATHER``: a collective kind (``all-gather`` / ``all-reduce`` /
  ``all-to-all`` / ``collective-permute`` / ``reduce-scatter``) in the
  partitioned HLO that the site did not declare — the signature of a
  dropped sharding constraint (GSPMD gathered to replicate).
- ``S-MATCH``: ``lax.cond``/``switch`` branches inside a traced
  program whose collective sequences differ (primitive + axis) — on a
  real mesh a data-dependent branch picking different collectives per
  device is a deadlock; CPU runs never notice.
- ``S-UNSPEC``: a site that declares its outputs sharded
  (``expects_constraint``) but whose trace carries no
  ``with_sharding_constraint`` (and no shard_map, which fixes output
  layout via ``out_specs``) — GSPMD is free to replicate the output.

Sites are skipped (not failed) when fewer than 8 CPU devices exist —
the virtual mesh needs the XLA flag set before backend init (the
tpu_lint CLI and tests/conftest.py both set it).
"""
from __future__ import annotations

import dataclasses
import re
from collections import Counter
from typing import Callable, List, Optional, Tuple

from .base import Finding, waive_from_sources
from .jaxpr_util import repo_root, sub_jaxprs

__all__ = ["SpmdSite", "SPMD_SITES", "virtual_mesh", "mesh_available",
           "hlo_collective_counts", "check_spmd_site", "run_spmd_pass",
           "VIRTUAL_MESH_DEVICES", "trace_census"]

#: devices the virtual CPU mesh needs (matches tests/conftest.py)
VIRTUAL_MESH_DEVICES = 8

_HLO_COLLECTIVE_RE = re.compile(
    r"\b(all-gather|all-reduce|all-to-all|collective-permute|"
    r"reduce-scatter)\b")

#: jaxpr-level collective primitives (for the branch-symmetry check)
_COLLECTIVE_PRIMS = ("psum", "pmax", "pmin", "ppermute", "pgather",
                     "all_to_all", "all_gather", "reduce_scatter",
                     "psum_scatter")


@dataclasses.dataclass
class SpmdSite:
    name: str                 # "mp.column_row_linear", ...
    build: Callable           # () -> (fn, args) — args committed arrays
    allowed: frozenset        # HLO collective kinds the source declares
    expects_constraint: bool = False
    path: str = ""
    line: int = 0

    def __post_init__(self):
        import os

        code = getattr(self.build, "__code__", None)
        if code is not None and not self.path:
            repo = repo_root()
            fname = code.co_filename
            self.path = os.path.relpath(fname, repo) \
                if fname.startswith(repo) else fname
            self.line = code.co_firstlineno


def mesh_available() -> bool:
    import jax

    try:
        return len(jax.devices("cpu")) >= VIRTUAL_MESH_DEVICES
    except Exception:
        return False


def virtual_mesh(shape: Tuple[int, ...] = (VIRTUAL_MESH_DEVICES,),
                 names: Tuple[str, ...] = ("x",)):
    """A jax Mesh over the virtual CPU devices, or None when the
    process was started without the fake-device XLA flag."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    if not mesh_available():
        return None
    devs = jax.devices("cpu")[:VIRTUAL_MESH_DEVICES]
    return Mesh(np.array(devs).reshape(shape), names)


def hlo_collective_counts(hlo_text: str) -> Counter:
    """collective kind -> occurrence count in partitioned HLO text."""
    return Counter(_HLO_COLLECTIVE_RE.findall(hlo_text))


# ----------------------------------------------------------- jaxpr checks

def _collective_seq(jaxpr) -> List[Tuple[str, str]]:
    """Flat (primitive, axes) sequence of a jaxpr incl. sub-jaxprs —
    order matters: it is the device's collective schedule."""
    seq: List[Tuple[str, str]] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _COLLECTIVE_PRIMS:
            axes = eqn.params.get("axes", eqn.params.get("axis_name"))
            seq.append((eqn.primitive.name, str(axes)))
        for sj in sub_jaxprs(eqn):
            seq += _collective_seq(sj)
    return seq


def trace_census(fn, *args) -> List[Tuple[str, str]]:
    """The traced collective census of ``fn(*args)``: the ordered
    (primitive, axes) sequence of every collective in the jaxpr,
    sub-jaxprs included — a loop body's collectives appear ONCE (the
    body is traced once), so a fori_loop decode layer contributes its
    per-layer sequence exactly once. The shared helper behind the
    census pins in test_tp_serving, test_moe_ep_decode, the dryrun
    multichip/overlap phases, and the S-OVERLAP lint."""
    import jax

    return _collective_seq(jax.make_jaxpr(fn)(*args).jaxpr)


def _check_branch_symmetry(jaxpr, site, findings):
    from jax.core import ClosedJaxpr

    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "cond":
            branches = [b.jaxpr if isinstance(b, ClosedJaxpr) else b
                        for b in eqn.params.get("branches", ())]
            seqs = [_collective_seq(b) for b in branches]
            if len({tuple(s) for s in seqs}) > 1:
                findings.append(Finding(
                    rule="S-MATCH", site=site.name, path=site.path,
                    line=site.line,
                    message=(f"cond branches in `{site.name}` issue "
                             f"different collective sequences {seqs} — "
                             "devices taking different branches "
                             "deadlock the mesh; hoist the collectives "
                             "out of the branch bodies")))
        for sj in sub_jaxprs(eqn):
            _check_branch_symmetry(sj, site, findings)


def _has_prim(jaxpr, names) -> bool:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in names:
            return True
        for sj in sub_jaxprs(eqn):
            if _has_prim(sj, names):
                return True
    return False


# ------------------------------------------------------------- site check

def check_spmd_site(site: SpmdSite) -> List[Finding]:
    """Trace + partition one site on the virtual mesh and lint it."""
    import jax

    findings: List[Finding] = []
    fn, args = site.build()
    closed = jax.make_jaxpr(fn)(*args)

    _check_branch_symmetry(closed.jaxpr, site, findings)

    if site.expects_constraint and not _has_prim(
            closed.jaxpr, ("sharding_constraint", "shard_map")):
        findings.append(Finding(
            rule="S-UNSPEC", site=site.name, path=site.path,
            line=site.line,
            message=(f"`{site.name}` declares sharded outputs but the "
                     "trace has no with_sharding_constraint (and no "
                     "shard_map out_specs) — GSPMD may replicate the "
                     "output (all-gather per step)")))

    hlo = jax.jit(fn).lower(*args).compile().as_text()
    for kind, n in sorted(hlo_collective_counts(hlo).items()):
        if kind in site.allowed:
            continue
        findings.append(Finding(
            rule="S-GATHER", site=site.name, path=site.path,
            line=site.line,
            message=(f"partitioned HLO of `{site.name}` contains {n} "
                     f"undeclared `{kind}` op(s) (declared: "
                     f"{sorted(site.allowed) or 'none'}) — GSPMD "
                     "inserted it to repair a missing sharding "
                     "annotation; add the with_sharding_constraint "
                     "(or declare the collective at the site)")))
    return findings


# ------------------------------------------------------------ repo sites

def _fleet_mesh_2x4():
    """The dp4 x mp2 hybrid mesh via fleet.init — the same global-state
    setup the distributed tests use."""
    from ..distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        **strategy.hybrid_configs,
        "dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
        "sharding_degree": 1, "sep_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group().mesh


def _build_mp_linear():
    """Column-parallel -> row-parallel linear pair (fleet mpu layers):
    the contraction over the mp-sharded dim must lower to exactly one
    all-reduce; output pinned dp-sharded via with_sharding_constraint."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..core import engine as ce
    from ..core.tensor import Tensor
    from ..distributed.fleet.layers.mpu.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)
    from ..nn import functional as F

    mesh = _fleet_mesh_2x4()
    col = ColumnParallelLinear(32, 64, gather_output=False)
    row = RowParallelLinear(64, 32, input_is_parallel=True)
    jmesh = mesh.jax_mesh()
    out_sharding = NamedSharding(jmesh, P("dp", None))

    def fn(xa, wc, bc, wr, br):
        with ce.no_grad():
            h = F.relu(F.linear(Tensor(xa), Tensor(wc), Tensor(bc)))
            y = F.linear(h, Tensor(wr), Tensor(br))
        return jax.lax.with_sharding_constraint(y._data, out_sharding)

    x = jax.device_put(jnp.ones((8, 32), jnp.float32),
                       NamedSharding(jmesh, P("dp", None)))
    return fn, (x, col.weight._data, col.bias._data, row.weight._data,
                row.bias._data)


def _build_ring_attention():
    """The ring-attention shard_map body: K/V rotate via ppermute only —
    any all-gather here means the seq sharding got dropped."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..nn.functional import ring_attention as ra

    mesh = virtual_mesh((VIRTUAL_MESH_DEVICES,), ("sep",))
    body = functools.partial(
        ra._ring_attention_sharded, axis_name="sep", causal=True,
        scale=8.0 ** -0.5, axis_size=VIRTUAL_MESH_DEVICES)
    pspec = P(None, "sep", None, None)
    kwargs = {}
    if getattr(jax.lax, "pcast", None) is None:
        kwargs["check_rep"] = False
    fn = ra._shard_map()(body, mesh=mesh, in_specs=(pspec,) * 3,
                         out_specs=pspec, **kwargs)
    q = jax.device_put(
        jnp.ones((1, 2 * VIRTUAL_MESH_DEVICES, 2, 8), jnp.float32),
        NamedSharding(mesh, pspec))
    return fn, (q, q, q)


def _build_moe_ep():
    """The MoE expert-parallel exchange: dispatch/combine must stay two
    all-to-alls (plus the aux/drop psum) — a reduce-formulated exchange
    or a gather means the EP sharding broke."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from ..core import engine as ce
    from ..core.tensor import Tensor
    from ..incubate.moe import MoELayer

    mesh = virtual_mesh((VIRTUAL_MESH_DEVICES,), ("x",))
    paddle.seed(0)
    moe = MoELayer(d_model=16, num_experts=8, gate="gshard",
                   d_hidden=32, capacity_factor=2.0, ep_mesh=(mesh, "x"))

    def fn(xa):
        with ce.no_grad():
            return moe(Tensor(xa))._data

    x = jax.device_put(jnp.ones((8, 4, 16), jnp.float32),
                       NamedSharding(mesh, P("x", None, None)))
    return fn, (x,)


def _build_moe_ep_decode():
    """The ep2 expert-parallel MoE decode step (ISSUE 15): the only
    collectives its partitioned HLO may carry are the per-MoE-layer
    all-to-all dispatch/combine PAIR plus the replicated-hidden
    all-gather — a reduce-formulated exchange or an extra gather means
    the expert-bank sharding broke."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..distributed.tp import TPContext, serving_mesh
    from ..incubate.nn.fused_transformer import (FusedMultiTransformer,
                                                 PagedKV, rope_table)
    from ..inference.kv_cache import BlockKVCacheManager

    paddle.seed(0)
    st = FusedMultiTransformer(32, 4, 64, 2, num_kv_heads=2,
                               max_position=64, moe_num_experts=4,
                               moe_top_k=2)
    tp = TPContext.create(
        st.num_heads, st.num_kv_heads, st.head_dim,
        mesh=serving_mesh(2, devices=jax.devices("cpu")[:2],
                          axis="ep"))
    w_tp = tp.shard_stack(st._stack())
    mgr = BlockKVCacheManager(st.num_layers, st.num_kv_heads,
                              st.head_dim, page_size=4, num_pages=16,
                              reserve_scratch=True, mp_degree=tp.mp,
                              mesh=tp.mesh)
    for i in range(2):
        mgr.allocate(i, 8)
    tables = mgr.block_tables(range(2), 4)
    cache = mgr.fresh_cache()
    cos, sin = rope_table(64, st.head_dim)
    lens = jnp.array([6, 6], jnp.int32)
    x = jnp.ones((2, st.embed_dim), jnp.float32)

    def fn(w, xb, ck, cv):
        h, cache2 = st.decode_raw(w, xb, PagedKV(ck, cv), tables,
                                  lens, cos, sin, tp=tp)
        return h, cache2.k, cache2.v

    return fn, (w_tp, x, cache.k, cache.v)


def _tp_serving_setup():
    """Shared builder state for the TP serving sites: a tiny
    FusedMultiTransformer, its shard-at-load mp2 stacks, and a
    kv-head-sharded pool over two of the virtual devices."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..distributed.tp import TPContext, serving_mesh
    from ..incubate.nn.fused_transformer import (FusedMultiTransformer,
                                                 rope_table)
    from ..inference.kv_cache import BlockKVCacheManager

    paddle.seed(0)
    st = FusedMultiTransformer(32, 4, 64, 2, num_kv_heads=2,
                               max_position=64)
    tp = TPContext.create(
        st.num_heads, st.num_kv_heads, st.head_dim,
        mesh=serving_mesh(2, devices=jax.devices("cpu")[:2]))
    w_tp = tp.shard_stack(st._stack())
    mgr = BlockKVCacheManager(st.num_layers, st.num_kv_heads,
                              st.head_dim, page_size=4, num_pages=16,
                              reserve_scratch=True, mp_degree=tp.mp,
                              mesh=tp.mesh)
    for i in range(2):
        mgr.allocate(i, 8)
    tables = mgr.block_tables(range(2), 4)
    cache = mgr.fresh_cache()
    cos, sin = rope_table(64, st.head_dim)
    lens = jnp.array([6, 6], jnp.int32)
    return st, tp, w_tp, cache, tables, cos, sin, lens


def _build_tp_decode():
    """The mp2 tensor-parallel decode step: the ONLY collectives the
    partitioned HLO may carry are the per-layer psum pair (all-reduce
    after the row-parallel O-proj and FFN2 — the reference's
    fused_multi_transformer_op.cu:220,529 ring_id points); a gather
    here means a weight/pool sharding annotation got dropped."""
    import jax.numpy as jnp

    from ..incubate.nn.fused_transformer import PagedKV

    st, tp, w_tp, cache, tables, cos, sin, lens = _tp_serving_setup()
    x = jnp.ones((2, st.embed_dim), jnp.float32)

    def fn(w, xb, ck, cv):
        h, cache2 = st.decode_raw(w, xb, PagedKV(ck, cv), tables,
                                  lens, cos, sin, tp=tp)
        return h, cache2.k, cache2.v

    return fn, (w_tp, x, cache.k, cache.v)


def _build_tp_prefill_chunk():
    """The mp2 chunked-prefill program: same psum-only contract as the
    decode site (the chunk attends to cached pages + its causal
    triangle entirely shard-locally)."""
    import jax.numpy as jnp

    from ..incubate.nn.fused_transformer import PagedKV

    st, tp, w_tp, cache, tables, cos, sin, _l = _tp_serving_setup()
    x = jnp.ones((2, 4, st.embed_dim), jnp.float32)
    start = jnp.zeros((2,), jnp.int32)
    clens = jnp.full((2,), 4, jnp.int32)

    def fn(w, xb, ck, cv):
        h, cache2 = st.prefill_chunk_raw(
            w, xb, PagedKV(ck, cv), tables, start, clens, cos, sin,
            tp=tp)
        return h, cache2.k, cache2.v

    return fn, (w_tp, x, cache.k, cache.v)


def _build_tp_decode_ring():
    """The mp2 decode step under ``overlap="ring"`` (ISSUE 19): the
    row-parallel reductions pipeline as chunked ppermute rings, so the
    partitioned HLO may carry collective-permutes ONLY — an all-reduce
    here means a site bypassed the overlap knob (a stray blocking
    psum), a gather means a sharding annotation dropped."""
    import jax.numpy as jnp

    from ..incubate.nn.fused_transformer import PagedKV

    st, tp, w_tp, cache, tables, cos, sin, lens = _tp_serving_setup()
    x = jnp.ones((2, st.embed_dim), jnp.float32)

    def fn(w, xb, ck, cv):
        h, cache2 = st.decode_raw(w, xb, PagedKV(ck, cv), tables,
                                  lens, cos, sin, tp=tp,
                                  overlap="ring")
        return h, cache2.k, cache2.v

    return fn, (w_tp, x, cache.k, cache.v)


def _build_moe_ep_decode_double():
    """The ep2 MoE decode step with the double-buffered exchange
    (``overlap=True`` via moe_ffn_ep): two half-capacity dispatch/
    combine all_to_all pairs per MoE layer plus the replicated-hidden
    all-gather — and nothing else."""
    fn0, args = _build_moe_ep_decode()

    # moe_ffn_ep resolves FLAGS_ep_overlap at trace time: pin the flag
    # around every trace of fn so the site is independent of the
    # process-wide setting
    from ..core.flags import flag, set_flags

    prev = flag("ep_overlap")

    def fn(*a):
        set_flags({"ep_overlap": True})
        try:
            return fn0(*a)
        finally:
            set_flags({"ep_overlap": prev})

    return fn, args


SPMD_SITES: List[SpmdSite] = [
    SpmdSite("mp.column_row_linear", _build_mp_linear,
             allowed=frozenset({"all-reduce"}),
             expects_constraint=True),
    SpmdSite("ring_attention.sharded", _build_ring_attention,
             allowed=frozenset({"collective-permute"})),
    SpmdSite("moe.expert_parallel", _build_moe_ep,
             allowed=frozenset({"all-to-all", "all-reduce"})),
    # tensor-parallel serving (ISSUE 10): the TP decode/prefill
    # programs declare their per-layer psum pair; shard_map fixes the
    # output layout via out_specs (S-UNSPEC)
    SpmdSite("tp.decode", _build_tp_decode,
             allowed=frozenset({"all-reduce"}),
             expects_constraint=True),
    SpmdSite("tp.prefill_chunk", _build_tp_prefill_chunk,
             allowed=frozenset({"all-reduce"}),
             expects_constraint=True),
    # expert-parallel MoE decode (ISSUE 15): the per-layer all-to-all
    # dispatch/combine pair + the replicated-hidden all-gather
    SpmdSite("moe.ep_decode", _build_moe_ep_decode,
             allowed=frozenset({"all-to-all", "all-gather"}),
             expects_constraint=True),
    # collective overlap (ISSUE 19): the ring-reduce TP decode carries
    # collective-permutes ONLY (an all-reduce is a stray blocking
    # psum); the double-buffered EP exchange keeps the a2a/gather
    # contract with doubled pair count (checked exactly by S-OVERLAP)
    SpmdSite("overlap.tp_decode_ring", _build_tp_decode_ring,
             allowed=frozenset({"collective-permute"}),
             expects_constraint=True),
    SpmdSite("overlap.moe_ep_double", _build_moe_ep_decode_double,
             allowed=frozenset({"all-to-all", "all-gather"}),
             expects_constraint=True),
]


def run_spmd_pass(sites=None) -> List[Finding]:
    """SPMD findings over the distributed-surface inventory. Returns []
    without checking when the virtual mesh is unavailable (process
    started without the fake-device flag — e.g. attached to a real
    TPU); the tier-1 test always runs with the mesh."""
    if not mesh_available():
        return []
    findings: List[Finding] = []
    for site in (SPMD_SITES if sites is None else sites):
        findings += check_spmd_site(site)
    return waive_from_sources(findings, repo_root())
