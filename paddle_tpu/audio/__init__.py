"""paddle_tpu.audio — audio feature extraction.

TPU-native equivalent of the reference's audio package (reference:
python/paddle/audio — features/layers.py Spectrogram/MelSpectrogram/
LogMelSpectrogram/MFCC over functional/window.py + functional/
functional.py hz_to_mel/mel_frequencies/compute_fbank_matrix). The STFT
rides the framework's fft ops; feature layers are nn.Layers so they
compose into models.
"""
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from . import features  # noqa: F401
from . import functional  # noqa: F401
from .backends import info, load, save  # noqa: F401

__all__ = ["features", "functional", "backends", "datasets",
           "info", "load", "save"]
