"""Audio I/O backends — PCM16 WAV over the stdlib ``wave`` module.

TPU-native equivalent of the reference's audio backend layer (reference:
python/paddle/audio/backends/{backend.py,init_backend.py,wave_backend.py}
— an info/load/save trio with a pluggable backend registry whose built-in
implementation is the stdlib wave reader). Zero-egress build: the only
built-in backend is ``wave``; ``set_backend`` of anything else raises
with guidance (the reference downloads paddleaudio for soundfile).
"""
from __future__ import annotations

import wave
from typing import Optional, Tuple

import numpy as np

__all__ = ["AudioInfo", "info", "load", "save",
           "list_available_backends", "get_current_backend", "set_backend"]


class AudioInfo:
    """Signal metadata (reference backends/backend.py:21)."""

    def __init__(self, sample_rate: int, num_samples: int,
                 num_channels: int, bits_per_sample: int, encoding: str):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (f"AudioInfo(sample_rate={self.sample_rate}, "
                f"num_samples={self.num_samples}, "
                f"num_channels={self.num_channels}, "
                f"bits_per_sample={self.bits_per_sample}, "
                f"encoding='{self.encoding}')")


def info(filepath: str) -> AudioInfo:
    """Metadata of a PCM16 WAV file (reference wave_backend.py:37)."""
    with wave.open(str(filepath), "rb") as f:
        return AudioInfo(
            sample_rate=f.getframerate(), num_samples=f.getnframes(),
            num_channels=f.getnchannels(),
            bits_per_sample=f.getsampwidth() * 8, encoding="PCM_S")


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True
         ) -> Tuple["paddle_tpu.Tensor", int]:
    """Load a PCM16 WAV file (reference wave_backend.py:89).

    Returns (waveform Tensor [channels, time] — or int16 un-normalized
    when ``normalize=False`` — and the sample rate).
    """
    from ..core.tensor import Tensor

    with wave.open(str(filepath), "rb") as f:
        sr, nch, width = f.getframerate(), f.getnchannels(), f.getsampwidth()
        if width != 2:
            raise RuntimeError(
                "only PCM16 WAV is supported by the built-in `wave` "
                "backend (got sample width "
                f"{width * 8} bits); convert the file or extend via a "
                "custom backend")
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    data = np.frombuffer(raw, dtype="<i2").reshape(-1, nch)
    if normalize:
        data = (data / 32768.0).astype(np.float32)
    wavef = data.T if channels_first else data
    return Tensor(np.ascontiguousarray(wavef)), sr


def save(filepath: str, src, sample_rate: int,
         channels_first: bool = True, bits_per_sample: int = 16) -> None:
    """Save a waveform as PCM16 WAV (reference wave_backend.py:168).

    ``src``: Tensor/ndarray [channels, time] (or [time, channels] when
    ``channels_first=False``); float inputs are assumed in [-1, 1].
    """
    from ..core.tensor import Tensor

    if bits_per_sample != 16:
        raise RuntimeError("the built-in `wave` backend writes PCM16 "
                           f"only (got bits_per_sample={bits_per_sample})")
    arr = np.asarray(src._data if isinstance(src, Tensor) else src)
    if arr.ndim == 1:
        arr = arr[None, :]
    if not channels_first:
        arr = arr.T
    if np.issubdtype(arr.dtype, np.floating):
        arr = np.clip(arr, -1.0, 1.0)
        arr = (arr * 32767.0).astype("<i2")
    else:
        arr = arr.astype("<i2")
    with wave.open(str(filepath), "wb") as f:
        f.setnchannels(arr.shape[0])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(arr.T).tobytes())


_BACKEND = "wave"


def list_available_backends():
    """(reference init_backend.py:37) Only the stdlib backend ships in
    the zero-egress build."""
    return ["wave"]


def get_current_backend() -> str:
    return _BACKEND


def set_backend(backend_name: str) -> None:
    """(reference init_backend.py:139)"""
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"audio backend '{backend_name}' is not available in this "
            "zero-egress build; available: "
            f"{list_available_backends()} (the reference installs "
            "paddleaudio for 'soundfile')")
