"""Audio classification datasets — synthetic-backed, zero-egress.

TPU-native equivalent of the reference's audio datasets (reference:
python/paddle/audio/datasets/{dataset.py,esc50.py,tess.py}). The
reference downloads ESC-50/TESS archives and reads WAVs; this build is
zero-egress, so the datasets synthesize deterministic class-conditioned
waveforms in memory (same pattern as ``text.datasets`` and
``vision.datasets``): each class has its own fundamental frequency and
harmonic stack, so feature extractors + classifiers genuinely learn.
The fold/split train-dev protocol matches the reference exactly.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..io import Dataset
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram

__all__ = ["AudioClassificationDataset", "ESC50", "TESS"]

feat_funcs = {
    "raw": None,
    "melspectrogram": MelSpectrogram,
    "mfcc": MFCC,
    "logmelspectrogram": LogMelSpectrogram,
    "spectrogram": Spectrogram,
}


class AudioClassificationDataset(Dataset):
    """Base class (reference audio/datasets/dataset.py:29): pairs
    waveforms with labels and applies the configured feature extractor
    in ``__getitem__``."""

    def __init__(self, waveforms: List[np.ndarray], labels: List[int],
                 feat_type: str = "raw", sample_rate: int = 8000,
                 **kwargs):
        super().__init__()
        if feat_type not in feat_funcs:
            raise RuntimeError(
                f"Unknown feat_type: {feat_type}, it must be one in "
                f"{list(feat_funcs)}")
        self.waveforms = waveforms
        self.labels = labels
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_config = kwargs
        self._feat_layer = None

    def _feature(self, wave_np: np.ndarray):
        if self.feat_type == "raw":
            return wave_np.astype(np.float32)
        if self._feat_layer is None:
            cls = feat_funcs[self.feat_type]
            cfg = dict(self.feat_config)
            if "sr" in cls.__init__.__code__.co_varnames:
                cfg.setdefault("sr", self.sample_rate)
            self._feat_layer = cls(**cfg)
        out = self._feat_layer(wave_np.astype(np.float32))
        return np.asarray(out._data)

    def __getitem__(self, idx):
        return self._feature(self.waveforms[idx]), self.labels[idx]

    def __len__(self):
        return len(self.waveforms)


def _class_wave(class_id: int, item: int, sample_rate: int,
                duration: float, base_f0: float = 110.0) -> np.ndarray:
    """Deterministic class-conditioned waveform: class-specific
    fundamental + harmonic amplitudes, item-specific phase/noise."""
    rng = np.random.RandomState(class_id * 1000 + item)
    n = int(sample_rate * duration)
    t = np.arange(n) / sample_rate
    f0 = base_f0 * (1.0 + 0.13 * class_id)
    sig = np.zeros(n, np.float32)
    for h in range(1, 4):
        amp = 1.0 / h * (1.0 + 0.2 * ((class_id + h) % 3))
        sig += amp * np.sin(2 * np.pi * f0 * h * t
                            + rng.uniform(0, 2 * np.pi))
    sig += 0.05 * rng.randn(n)
    return (0.3 * sig / np.abs(sig).max()).astype(np.float32)


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental-sound protocol (reference
    audio/datasets/esc50.py:26): 50 classes, 5 folds; ``mode='dev'``
    takes fold ``split``, train takes the rest."""

    n_classes = 50
    folds = 5
    clips_per_class = 5  # per fold in the synthetic build

    label_list = [f"class-{i}" for i in range(50)]

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw", sample_rate: int = 8000,
                 duration: float = 1.0, **kwargs):
        if split not in range(1, self.folds + 1):
            raise ValueError(
                f"split must be in [1, {self.folds}], got {split}")
        waves, labels = [], []
        for c in range(self.n_classes):
            for fold in range(1, self.folds + 1):
                in_dev = fold == split
                if (mode == "dev") != in_dev:
                    continue
                for j in range(self.clips_per_class):
                    waves.append(_class_wave(
                        c, fold * 100 + j, sample_rate, duration))
                    labels.append(c)
        super().__init__(waves, labels, feat_type=feat_type,
                         sample_rate=sample_rate, **kwargs)


class TESS(AudioClassificationDataset):
    """TESS emotional-speech protocol (reference
    audio/datasets/tess.py:26): 7 emotions, ``n_folds`` round-robin
    split; ``mode='dev'`` takes fold ``split``."""

    label_list = ["angry", "disgust", "fear", "happy", "neutral",
                  "pleasant_surprise", "sad"]
    items_per_class = 10

    def __init__(self, mode: str = "train", n_folds: int = 5,
                 split: int = 1, feat_type: str = "raw",
                 sample_rate: int = 8000, duration: float = 1.0,
                 **kwargs):
        if not (isinstance(n_folds, int) and n_folds >= 1):
            raise ValueError(f"n_folds must be a positive int, "
                             f"got {n_folds}")
        if split not in range(1, n_folds + 1):
            raise ValueError(
                f"split must be in [1, {n_folds}], got {split}")
        waves, labels = [], []
        for c in range(len(self.label_list)):
            for j in range(self.items_per_class):
                fold = j % n_folds + 1
                in_dev = fold == split
                if (mode == "dev") != in_dev:
                    continue
                waves.append(_class_wave(c, j, sample_rate, duration,
                                         base_f0=150.0))
                labels.append(c)
        super().__init__(waves, labels, feat_type=feat_type,
                         sample_rate=sample_rate, **kwargs)
