"""Audio feature layers (reference: python/paddle/audio/features/
layers.py — Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame(x, frame_length: int, hop_length: int):
    """[..., T] -> [..., n_frames, frame_length] (delegates to the
    shared strided-framing helper in paddle_tpu.signal)."""
    from ..signal import _frame_raw

    return _frame_raw(x, frame_length, hop_length)


class Spectrogram(Layer):
    """STFT power spectrogram [..., n_fft//2+1, n_frames] (reference:
    features/layers.py Spectrogram). Center-padding (reflect) like the
    reference's default."""

    def __init__(self, n_fft: int = 512, hop_length=None, win_length=None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 dtype=None):
        super().__init__()
        self.n_fft = n_fft
        self.win_length = win_length or n_fft
        self.hop_length = hop_length or self.win_length // 2
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = F.get_window(window, self.win_length)._data
        if self.win_length < n_fft:  # center-pad window to n_fft
            lpad = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - self.win_length - lpad))
        self.register_buffer("window", Tensor(w), persistable=False)

    def forward(self, x):
        from ..ops.dispatch import as_tensor_args, eager_apply

        (t,) = as_tensor_args(x)
        win = self.window._data
        n_fft, hop = self.n_fft, self.hop_length
        power, center, pad_mode = self.power, self.center, self.pad_mode

        def raw(sig):
            if center:
                pad = [(0, 0)] * (sig.ndim - 1) + [(n_fft // 2,
                                                    n_fft // 2)]
                sig = jnp.pad(sig, pad, mode=pad_mode)
            frames = _frame(sig, n_fft, hop) * win  # [..., F, n_fft]
            spec = jnp.fft.rfft(frames, axis=-1)
            mag = jnp.abs(spec) ** power
            return jnp.swapaxes(mag, -1, -2)  # [..., bins, frames]

        import jax

        from ..fft import to_cpu_op

        # rfft: complex intermediates stay off the TPU (see fft.py)
        t = to_cpu_op(t)
        with jax.default_device(jax.devices("cpu")[0]):
            return eager_apply("spectrogram", raw, [t])


class MelSpectrogram(Layer):
    """Spectrogram × mel filterbank (reference: MelSpectrogram)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window: str = "hann",
                 power: float = 2.0, n_mels: int = 64, f_min: float = 50.0,
                 f_max=None, htk: bool = False, norm: str = "slaney",
                 dtype=None):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power)
        fbank = F.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                       htk, norm)
        self.register_buffer("fbank", fbank, persistable=False)

    def forward(self, x):
        spec = self.spectrogram(x)  # [..., bins, frames]
        from ..ops.dispatch import as_tensor_args, eager_apply

        fb = self.fbank._data

        def raw(s):
            return jnp.einsum("mb,...bf->...mf", fb, s)

        (t,) = as_tensor_args(spec)
        return eager_apply("mel_fbank", raw, [t])


class LogMelSpectrogram(Layer):
    """power_to_db(MelSpectrogram) (reference: LogMelSpectrogram)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window: str = "hann",
                 power: float = 2.0, n_mels: int = 64, f_min: float = 50.0,
                 f_max=None, ref_value: float = 1.0, amin: float = 1e-10,
                 top_db=None, dtype=None):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, n_mels, f_min, f_max)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return F.power_to_db(self.mel(x), self.ref_value, self.amin,
                             self.top_db)


class MFCC(Layer):
    """DCT-II over log-mel (reference: MFCC)."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40,
                 n_fft: int = 512, hop_length=None, n_mels: int = 64,
                 f_min: float = 50.0, f_max=None, top_db=None, dtype=None):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length,
                                        n_mels=n_mels, f_min=f_min,
                                        f_max=f_max, top_db=top_db)
        self.register_buffer("dct", F.create_dct(n_mfcc, n_mels),
                             persistable=False)

    def forward(self, x):
        lm = self.logmel(x)  # [..., n_mels, frames]
        from ..ops.dispatch import as_tensor_args, eager_apply

        dct = self.dct._data

        def raw(s):
            return jnp.einsum("mc,...mf->...cf", dct, s)

        (t,) = as_tensor_args(lm)
        return eager_apply("mfcc_dct", raw, [t])
