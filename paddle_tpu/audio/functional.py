"""Audio DSP functional primitives (reference: python/paddle/audio/
functional/functional.py — hz_to_mel:*, mel_to_hz, mel_frequencies,
compute_fbank_matrix, power_to_db; window.py get_window)."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies",
           "compute_fbank_matrix", "power_to_db", "get_window",
           "create_dct"]


def hz_to_mel(freq, htk: bool = False):
    f = np.asarray(freq, np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        # Slaney formula (librosa/reference default)
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = np.log(6.4) / 27.0
        if np.ndim(f):
            log_t = f >= min_log_hz
            out = np.where(log_t, min_log_mel
                           + np.log(np.maximum(f, min_log_hz)
                                    / min_log_hz) / logstep, out)
        elif f >= min_log_hz:
            out = min_log_mel + np.log(f / min_log_hz) / logstep
    return out


def mel_to_hz(mel, htk: bool = False):
    m = np.asarray(mel, np.float64)
    if htk:
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    out = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = np.log(6.4) / 27.0
    if np.ndim(m):
        log_t = m >= min_log_mel
        out = np.where(log_t,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)),
                       out)
    elif m >= min_log_mel:
        out = min_log_hz * np.exp(logstep * (m - min_log_mel))
    return out


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                       n_mels)
    return mel_to_hz(mels, htk)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max=None,
                         htk: bool = False, norm: str = "slaney"):
    """[n_mels, n_fft//2+1] triangular mel filterbank (reference:
    functional.py compute_fbank_matrix)."""
    f_max = f_max if f_max is not None else sr / 2.0
    fft_freqs = np.linspace(0, sr / 2.0, n_fft // 2 + 1)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return Tensor(jnp.asarray(weights.astype(np.float32)))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db=80.0):
    """10*log10(power) with clipping (reference: power_to_db)."""
    from ..ops.dispatch import as_tensor_args, eager_apply

    (t,) = as_tensor_args(spect)

    def raw(x):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
        log_spec = log_spec - 10.0 * jnp.log10(
            jnp.maximum(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec

    return eager_apply("power_to_db", raw, [t])


def get_window(window: str, win_length: int, fftbins: bool = True):
    """hann/hamming/blackman/... (reference: window.py get_window)."""
    n = win_length if not fftbins else win_length + 1
    k = np.arange(n)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / (n - 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / (n - 1))
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / (n - 1))
             + 0.08 * np.cos(4 * np.pi * k / (n - 1)))
    elif window in ("boxcar", "rect", "ones"):
        w = np.ones(n)
    else:
        raise ValueError(f"unsupported window {window!r}")
    if fftbins:
        w = w[:-1]
    return Tensor(jnp.asarray(w.astype(np.float32)))


def create_dct(n_mfcc: int, n_mels: int, norm: str = "ortho"):
    """[n_mels, n_mfcc] DCT-II basis (reference: create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[None, :]
    dct = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / np.sqrt(2.0)
        dct *= np.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct.astype(np.float32)))
