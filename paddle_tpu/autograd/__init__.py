"""Autograd user API.

Reference surface: python/paddle/autograd (backward(), PyLayer, no_grad,
hooks) over the eager engine (paddle/fluid/eager/backward.cc:428).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax

from ..core import engine
from ..core.engine import enable_grad, is_grad_enabled, no_grad, set_grad_enabled
from ..core.tensor import Tensor

__all__ = [
    "backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
    "is_grad_enabled", "PyLayer", "PyLayerContext",
]


def _listify(x):
    if x is None:
        return None
    if isinstance(x, Tensor):
        return [x]
    return list(x)


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = _listify(tensors)
    grad_tensors = _listify(grad_tensors)
    engine.run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None, name=None) -> List[Optional[Tensor]]:
    """``paddle.grad``: grads of outputs wrt inputs without polluting .grad.

    ``create_graph=True`` (double grad, reference: eager double-grad via
    generated higher-order GradNodes) runs every node's backward as a
    dispatched op over (primals, cotangents), so the returned grads carry
    their own GradNodes and can be differentiated again.
    """
    outputs = _listify(outputs)
    inputs = _listify(inputs)
    grad_outputs = _listify(grad_outputs)
    retain = bool(retain_graph) if retain_graph is not None \
        else bool(create_graph)
    raws = engine.run_backward(outputs, grad_outputs, retain_graph=retain,
                               inputs=inputs, allow_unused=allow_unused,
                               create_graph=create_graph)
    return [None if g is None else
            (g if isinstance(g, Tensor) else Tensor(g)) for g in raws]


class PyLayerContext:
    """Mirror of paddle's PyLayerContext (reference:
    paddle/fluid/eager/pylayer/py_layer_node.h + python/paddle/autograd/
    py_layer.py): save_for_backward / saved_tensor + not_inplace marks."""

    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayer:
    """User-defined autograd op: subclass with static forward/backward.

    forward(ctx, *args) -> Tensor(s); backward(ctx, *grad_outputs) ->
    grads for each Tensor input of forward, positionally.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()

        tensor_positions = [i for i, a in enumerate(args)
                            if isinstance(a, Tensor)]
        with engine.set_grad_enabled(False):
            outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (tuple, list))
        out_list = [outputs] if single else list(outputs)

        if not engine.is_grad_enabled() or not any(
                not args[i].stop_gradient for i in tensor_positions):
            return outputs

        out_avals = [(tuple(o._data.shape), o._data.dtype) for o in out_list]

        # backward() returns one grad per tensor input of forward, in
        # order; the engine only needs those for non-stop-gradient inputs.
        diff_mask = [not args[i].stop_gradient for i in tensor_positions]

        def vjp_fn(cotangents):
            cot_tensors = [Tensor(c) for c in cotangents]
            with engine.set_grad_enabled(False):
                grads = cls.backward(ctx, *cot_tensors)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            out = []
            for keep, g in zip(diff_mask, grads):
                if not keep:
                    continue
                out.append(None if g is None else
                           (g._data if isinstance(g, Tensor) else g))
            return tuple(out)

        edges = []
        for i in tensor_positions:
            t = args[i]
            if t.stop_gradient:
                continue
            if t._grad_node is not None:
                edges.append(("node", t._grad_node, t._out_idx))
            else:
                edges.append(("leaf", t))

        node = engine.GradNode(cls.__name__, vjp_fn, edges, out_avals)
        for idx, o in enumerate(out_list):
            o.stop_gradient = False
            o._grad_node = node
            o._out_idx = idx
        return outputs


class Function(PyLayer):
    """torch-style alias."""
