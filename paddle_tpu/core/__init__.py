from . import dtype, engine, flags, generator, place, tensor  # noqa: F401
