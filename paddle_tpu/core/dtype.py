"""Dtype system for paddle_tpu.

TPU-native equivalent of the reference's ``phi::DataType`` enum
(reference: paddle/phi/common/data_type.h). We wrap numpy/jax dtypes in a
small ``DType`` value class so user code can write ``paddle_tpu.float32``
exactly like ``paddle.float32`` while the backing representation stays a
``jnp.dtype`` that XLA understands. bfloat16 is first-class (the TPU MXU's
native matmul dtype).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = [
    "DType", "dtype", "convert_dtype", "to_jax_dtype",
    "float16", "float32", "float64", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "bool_", "complex64", "complex128",
]


class DType:
    """A framework dtype: hashable, comparable with strings/numpy/jax dtypes."""

    __slots__ = ("name", "np_dtype")

    _registry: dict = {}

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = jnp.dtype(np_dtype)
        DType._registry[name] = self

    # -- comparisons ---------------------------------------------------
    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            try:
                return self.name == convert_dtype(other).name
            except (TypeError, ValueError):
                return False
        try:
            return self.np_dtype == jnp.dtype(other)
        except TypeError:
            return NotImplemented

    def __hash__(self):
        return hash(self.name)

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    # -- property helpers ---------------------------------------------
    @property
    def is_floating_point(self) -> bool:
        return jnp.issubdtype(self.np_dtype, jnp.floating)

    @property
    def is_integer(self) -> bool:
        return jnp.issubdtype(self.np_dtype, jnp.integer)

    @property
    def is_complex(self) -> bool:
        return jnp.issubdtype(self.np_dtype, jnp.complexfloating)

    @property
    def itemsize(self) -> int:
        return self.np_dtype.itemsize


float16 = DType("float16", jnp.float16)
float32 = DType("float32", jnp.float32)
float64 = DType("float64", jnp.float64)
bfloat16 = DType("bfloat16", jnp.bfloat16)
int8 = DType("int8", jnp.int8)
int16 = DType("int16", jnp.int16)
int32 = DType("int32", jnp.int32)
int64 = DType("int64", jnp.int64)
uint8 = DType("uint8", jnp.uint8)
uint16 = DType("uint16", jnp.uint16)
uint32 = DType("uint32", jnp.uint32)
uint64 = DType("uint64", jnp.uint64)
bool_ = DType("bool", jnp.bool_)
complex64 = DType("complex64", jnp.complex64)
complex128 = DType("complex128", jnp.complex128)

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bool_": "bool",
}


def convert_dtype(d) -> DType:
    """Normalize any dtype-like (str, np.dtype, jnp dtype, DType) to DType."""
    if d is None:
        raise TypeError("dtype must not be None")
    if isinstance(d, DType):
        return d
    if isinstance(d, str):
        name = _ALIASES.get(d, d)
        if name in DType._registry:
            return DType._registry[name]
        # fall through to numpy parsing for e.g. "f4"
    npd = jnp.dtype(d)
    name = npd.name
    if name in DType._registry:
        return DType._registry[name]
    raise TypeError(f"unsupported dtype: {d!r}")


def to_jax_dtype(d):
    """DType | str | np dtype -> jnp dtype usable in jax calls."""
    return convert_dtype(d).np_dtype


# what `paddle.get_default_dtype` controls
_default_dtype = float32


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if not d.is_floating_point:
        raise TypeError(f"default dtype must be floating point, got {d}")
    _default_dtype = d


def get_default_dtype() -> DType:
    return _default_dtype


dtype = DType  # paddle exposes `paddle.dtype` as the type itself
