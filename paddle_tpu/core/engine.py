"""Tape-based autograd engine.

TPU-native equivalent of the reference's eager autograd
(reference: paddle/fluid/eager/backward.cc:105 ``RunBackward`` — in-degree
map over the GradNode graph, ready-queue topological execution,
``GradTensorHolder`` accumulation; grad_node_info.h for GradNode/edges).

Design: every differentiable eager op records one ``GradNode`` holding a
``jax.vjp`` closure (JAX computes the VJP — we never hand-write per-op
gradients) plus edges to the producers of its differentiable inputs. The
engine mirrors RunBackward's semantics: in-degree counting, topological
ready queue, per-slot grad accumulation, leaf ``.grad`` accumulation with
hooks. The closures are pure functions of immutable jax arrays, so
``retain_graph`` re-execution is always safe.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "GradNode", "run_backward", "no_grad", "enable_grad", "set_grad_enabled",
    "is_grad_enabled", "register_backward_final_hook",
]

# callbacks fired after every run_backward sweep completes (the moment the
# reference's EagerReducer finalizes bucketed allreduce — reducer.cc)
_backward_final_hooks: Dict[int, Callable] = {}
_bf_hook_id = [0]


def register_backward_final_hook(fn: Callable):
    _bf_hook_id[0] += 1
    hid = _bf_hook_id[0]
    _backward_final_hooks[hid] = fn

    class _H:
        def remove(self):
            _backward_final_hooks.pop(hid, None)

    return _H()


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_STATE = _GradState()


def is_grad_enabled() -> bool:
    return _STATE.enabled


@contextlib.contextmanager
def _grad_enabled_ctx(mode: bool):
    prev = _STATE.enabled
    _STATE.enabled = bool(mode)
    try:
        yield
    finally:
        _STATE.enabled = prev


def set_grad_enabled(mode: bool):
    return _grad_enabled_ctx(mode)


def no_grad(func=None):
    """Context manager *and* decorator, like ``paddle.no_grad``."""
    if func is None:
        return _grad_enabled_ctx(False)
    if callable(func):
        import functools

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            with _grad_enabled_ctx(False):
                return func(*args, **kwargs)

        return wrapper
    raise TypeError("no_grad used incorrectly")


def enable_grad():
    return _grad_enabled_ctx(True)


class GradNode:
    """One recorded op on the tape.

    ``vjp_fn`` maps output cotangents -> input cotangents (for the
    differentiable inputs only). ``edges[i]`` says where the i-th input
    cotangent flows: ``("node", producer, slot)`` into a producer node's
    accumulation buffer, or ``("leaf", tensor)`` into a leaf's ``.grad``.
    ``retain_map`` lets intermediate tensors observe their fully-accumulated
    grad the moment this node executes (Tensor.retain_grads / paddle.grad
    on intermediates).
    """

    __slots__ = (
        "name", "vjp_fn", "edges", "out_avals", "grad_buffer",
        "retain_map", "post_hooks", "second",
    )

    def __init__(self, name: str, vjp_fn: Callable, edges: List[Tuple],
                 out_avals: List[Tuple]):
        self.name = name
        self.vjp_fn = vjp_fn
        self.edges = edges
        self.out_avals = out_avals  # [(shape, dtype), ...] per output slot
        # (raw_fn, static_kwargs, tensor_inputs, diff_idx) when the op
        # supports create_graph: the backward can then be re-expressed as
        # a differentiable function of primals AND cotangents (the vjp
        # closure alone bakes primals in as constants, which would make
        # d(grad)/d(primal) unreachable)
        self.second: Optional[Tuple] = None
        self.grad_buffer: List[Optional[Any]] = [None] * len(out_avals)
        # slot -> list of observers: Tensor (retain_grads) or
        # ("capture", key) entries added temporarily by paddle.grad
        self.retain_map: Dict[int, List[Any]] = {}
        self.post_hooks: List[Callable] = []

    def add_retain(self, slot: int, target) -> None:
        self.retain_map.setdefault(slot, []).append(target)

    def accumulate(self, slot: int, grad) -> None:
        cur = self.grad_buffer[slot]
        self.grad_buffer[slot] = grad if cur is None else _gadd(cur, grad)

    def assembled_cotangents(self):
        import numpy as _np

        import jax as _jax

        cots = []
        for slot, (shape, dt) in enumerate(self.out_avals):
            g = _graw(self.grad_buffer[slot]) \
                if self.grad_buffer[slot] is not None else None
            if g is None:
                if jnp.issubdtype(dt, jnp.inexact):
                    g = jnp.zeros(shape, dt)
                else:
                    # integer/bool outputs carry float0 cotangents in JAX
                    g = _np.zeros(shape, _jax.dtypes.float0)
            elif jnp.issubdtype(dt, jnp.inexact) and g.dtype != dt:
                # mixed-precision graphs (AMP O1): a consumer may return a
                # cotangent in its own compute dtype; vjp demands the
                # producer's output dtype
                g = g.astype(dt)
            cots.append(g)
        return tuple(cots)

    def release(self):
        self.vjp_fn = None
        self.second = None
        self.grad_buffer = [None] * len(self.out_avals)

    def __repr__(self):
        return f"<GradNode {self.name} outs={len(self.out_avals)}>"


def _wrap(array):
    from .tensor import Tensor

    if isinstance(array, Tensor):
        return array  # create_graph grads stay graph-connected
    return Tensor(array, stop_gradient=True)


def _gadd(a, b):
    """Accumulate two grads; Tensor operands (create_graph mode) go
    through dispatched ops so the sum itself is differentiable."""
    from .tensor import Tensor

    if isinstance(a, Tensor) or isinstance(b, Tensor):
        ta = a if isinstance(a, Tensor) else Tensor(a)
        tb = b if isinstance(b, Tensor) else Tensor(b)
        return ta + tb
    return a + b


def _graw(g):
    """Raw array view of a grad that may be a Tensor."""
    return g._data if hasattr(g, "_data") else g


def _accumulate_leaf(tensor, grad) -> None:
    # tensor-level hooks fire as the grad finalizes
    # (reference: egr hooks, reducer marks vars ready here)
    from .tensor import Tensor

    for hook in list(tensor._grad_hooks.values()):
        out = hook(_wrap(grad))
        if out is not None:
            grad = out._data if hasattr(out, "_data") else out
    if tensor.grad is None:
        tensor.grad = _wrap(grad)
    elif isinstance(grad, Tensor):
        tensor.grad = tensor.grad + grad  # keep graph (create_graph)
    else:
        tensor.grad = _wrap(tensor.grad._data + grad)


def _assemble_cot_tensors(node: "GradNode"):
    """Cotangents as Tensors (create_graph mode): missing slots are
    graph-free zeros; existing Tensor grads keep their graph."""
    from .tensor import Tensor

    cots = []
    for slot, (shape, dt) in enumerate(node.out_avals):
        g = node.grad_buffer[slot]
        if g is None:
            g = Tensor(jnp.zeros(shape, dt))
        elif not isinstance(g, Tensor):
            g = Tensor(g)
        if jnp.issubdtype(dt, jnp.inexact) and g._data.dtype != dt:
            g = g.astype(str(jnp.dtype(dt)))
        cots.append(g)
    return cots


def _apply_node(node: "GradNode", create_graph: bool):
    """Run one node's backward. With create_graph and recorded primal
    info, the backward runs as a dispatched op over (primals,
    cotangents) — its outputs get their own GradNodes, so a second
    backward can differentiate through it (double grad; reference:
    generated higher-order GradNodes / prim composite VJPs)."""
    if not create_graph:
        return node.vjp_fn(node.assembled_cotangents())
    if node.second is None:
        # Severing the graph here would return silently WRONG second
        # derivatives, so refuse loudly — naming the actual cause.
        from .flags import flag as _flag

        if not _flag("record_double_grad"):
            raise NotImplementedError(
                f"create_graph=True through `{node.name}`: no primal "
                "recipe was recorded. If this is a built-in dispatched "
                "op, recording was disabled — re-enable via "
                "paddle.set_flags({'record_double_grad': True}) BEFORE "
                "the forward pass; PyLayer/to_static nodes never record "
                "one and don't support double grad regardless")
        raise NotImplementedError(
            f"create_graph=True through `{node.name}`: this node records "
            "no primal recipe (PyLayer/to_static graphs don't support "
            "double grad yet); restructure the model so the "
            "differentiated path uses built-in ops")
    from ..ops.dispatch import _interleave, eager_apply

    recipe_fn, in_tensors, diff_idx = node.second
    cot_tensors = _assemble_cot_tensors(node)
    diff_tensors = [in_tensors[i] for i in diff_idx]
    const = {i: in_tensors[i]._data for i in range(len(in_tensors))
             if i not in set(diff_idx)}
    k = len(diff_idx)
    n_in = len(in_tensors)
    out_avals = node.out_avals

    def second_raw(*arrs):
        prim, cots_ = arrs[:k], arrs[k:]

        def f(*diff_arrays):
            return recipe_fn(*_interleave(const, n_in, diff_arrays))

        _, vjp = jax.vjp(f, *prim)
        fixed = []
        for c, (shape, dt) in zip(cots_, out_avals):
            if not jnp.issubdtype(dt, jnp.inexact):
                import numpy as _np

                c = _np.zeros(shape, jax.dtypes.float0)
            fixed.append(c)
        return vjp(tuple(fixed))

    res = eager_apply(node.name + "_grad", second_raw,
                      list(diff_tensors) + cot_tensors,
                      n_outputs=len(diff_idx))
    return res if isinstance(res, tuple) else (res,)


def run_backward(
    tensors: Sequence,
    grad_tensors: Optional[Sequence] = None,
    retain_graph: bool = False,
    inputs: Optional[Sequence] = None,
    allow_unused: bool = False,
    create_graph: bool = False,
) -> Optional[List[Optional[Any]]]:
    """Reverse-mode sweep from ``tensors``.

    Mirrors ``egr::RunBackward`` (backward.cc:105). With ``inputs`` set,
    captures and returns raw grads of those tensors without touching any
    ``.grad`` (``paddle.grad`` semantics); intermediates are captured via a
    temporary entry in their producer's ``retain_map``.

    Telemetry: each sweep runs under an ``autograd::backward`` span when
    a profiler window is recording, and bumps the ``autograd.sweeps`` /
    ``autograd.nodes`` counters (profiler.stats) so per-step backward
    graph size is visible without a trace.
    """
    from ..profiler import stats as _stats
    from ..profiler.profiler import RecordEvent as _RecordEvent

    _stats.inc("autograd.sweeps")
    with _RecordEvent("autograd::backward"):
        return _run_backward_impl(tensors, grad_tensors, retain_graph,
                                  inputs, allow_unused, create_graph,
                                  _stats)


def _run_backward_impl(tensors, grad_tensors, retain_graph, inputs,
                       allow_unused, create_graph, _stats):
    roots: List[GradNode] = []
    for t, g in zip(tensors, grad_tensors or [None] * len(tensors)):
        node = t._grad_node
        if g is None:
            g_arr = jnp.ones(t._data.shape, t._data.dtype)
        elif create_graph and hasattr(g, "_data"):
            # keep the cotangent's own graph: d(grad)/d(grad_outputs)
            # must stay reachable through the seeded Tensor
            g_arr = g
        else:
            g_arr = g._data if hasattr(g, "_data") else jnp.asarray(g)
        if node is None:
            if not t.stop_gradient:
                _accumulate_leaf(t, g_arr)
            continue
        node.accumulate(t._out_idx, g_arr)
        if node not in roots:
            roots.append(node)

    # capture bookkeeping for paddle.grad-style calls
    captured: Dict[int, Any] = {}
    capture_leaf_ids: Dict[int, Any] = {}
    temp_retains: List[Tuple[GradNode, int]] = []
    if inputs is not None:
        for t in inputs:
            if t._grad_node is None:
                capture_leaf_ids[id(t)] = t
            else:
                node, slot = t._grad_node, t._out_idx
                entry = ("capture", id(t))
                node.add_retain(slot, entry)
                temp_retains.append((node, slot, entry))
        # a root tensor listed in inputs: its grad is the seeded cotangent
        for t, g in zip(tensors, grad_tensors or [None] * len(tensors)):
            if id(t) in {id(i) for i in inputs} and t._grad_node is None:
                pass  # handled as leaf below if reachable

    # ---- in-degree map over reachable nodes (getInDegreeMap, backward.cc:23)
    indeg: Dict[int, int] = {}
    stack = list(roots)
    seen = set()
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        indeg.setdefault(id(n), 0)
        for edge in n.edges:
            if edge[0] == "node":
                p = edge[1]
                indeg[id(p)] = indeg.get(id(p), 0) + 1
                stack.append(p)

    ready: List[GradNode] = [n for n in roots if indeg[id(n)] == 0]
    queued = {id(n) for n in ready}

    def _observe_retained(node: GradNode):
        """Before the node consumes its buffer, surface retained slot grads."""
        for slot, targets in list(node.retain_map.items()):
            g = node.grad_buffer[slot]
            if g is None:
                continue
            for target in targets:
                if isinstance(target, tuple) and target[0] == "capture":
                    k = target[1]
                    captured[k] = g if k not in captured \
                        else _gadd(captured[k], g)
                elif inputs is None:
                    # a Tensor with retain_grads(); paddle.grad passes must
                    # not touch .grad of anything
                    _accumulate_leaf(target, g)

    keep_graph = retain_graph or create_graph
    nodes_run = 0
    while ready:
        node = ready.pop()
        nodes_run += 1
        if node.vjp_fn is None:
            raise RuntimeError(
                f"the grad graph through {node.name} has been freed; use "
                "backward(retain_graph=True) to backward through it twice")
        _observe_retained(node)
        in_grads = _apply_node(node, create_graph)
        for hook in node.post_hooks:
            hook()
        if not keep_graph:
            node.release()
        else:
            node.grad_buffer = [None] * len(node.out_avals)
        for edge, g in zip(node.edges, in_grads):
            if edge[0] == "leaf":
                if g is None:
                    continue
                t = edge[1]
                if inputs is not None:
                    if id(t) in capture_leaf_ids:
                        k = id(t)
                        captured[k] = g if k not in captured \
                            else _gadd(captured[k], g)
                    # paddle.grad never pollutes other leaves' .grad
                else:
                    _accumulate_leaf(t, g)
            else:
                # a None grad still consumes the dependency edge — the
                # producer must run once every consumer has reported
                _, p, slot = edge
                if g is not None:
                    p.accumulate(slot, g)
                indeg[id(p)] -= 1
                if indeg[id(p)] == 0 and id(p) not in queued:
                    ready.append(p)
                    queued.add(id(p))

    _stats.inc("autograd.nodes", nodes_run)

    for node, slot, entry in temp_retains:
        targets = node.retain_map.get(slot)
        if targets is not None:
            # identity comparison: targets mixes tuples and Tensors, and
            # Tensor.__eq__ is elementwise
            node.retain_map[slot] = [t for t in targets if t is not entry]
            if not node.retain_map[slot]:
                node.retain_map.pop(slot, None)

    if inputs is not None:
        out = []
        for t in inputs:
            g = captured.get(id(t))
            if g is None and not allow_unused:
                raise RuntimeError(
                    "one of the input tensors receives no gradient; pass "
                    "allow_unused=True to get None for it")
            out.append(g)
        return out

    for hook in list(_backward_final_hooks.values()):
        hook()
    return None
