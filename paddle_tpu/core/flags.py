"""Global flags registry.

TPU-native equivalent of the reference's gflags-compatible flag system
(reference: paddle/phi/core/flags.cc — 120 PHI_DEFINE_EXPORTED_* flags,
macro at flags.h:145, settable by env ``FLAGS_*`` or ``paddle.set_flags``).

We keep the same surface: flags declared once with a default + doc, env
``FLAGS_<name>`` overrides the default at first read, and ``set_flags`` /
``get_flags`` mutate/inspect at runtime.

Every flag is ALSO settable via ``PADDLE_TPU_<NAME>`` (upper-cased) —
the deployment convention the PR 5 compile-cache flag established,
generalized to the whole registry. ``FLAGS_<name>`` wins when both are
set (reference parity). The README flags table lists both forms per
flag; ``tools/tpu_lint.py`` (flags pass) asserts the table stays
complete.
"""
from __future__ import annotations

import os
from typing import Any, Dict

__all__ = ["define_flag", "set_flags", "get_flags", "flag", "env_var_for"]

_FLAGS: Dict[str, dict] = {}


def _coerce(value, proto):
    if isinstance(proto, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(proto, int):
        return int(value)
    if isinstance(proto, float):
        return float(value)
    return value


def env_var_for(name: str) -> str:
    """The deployment-convention env override for a flag name."""
    return "PADDLE_TPU_" + name.upper()


def define_flag(name: str, default: Any, doc: str = "") -> None:
    if name in _FLAGS:
        return
    env = os.environ.get(f"FLAGS_{name}")
    if env is None:
        env = os.environ.get(env_var_for(name))
    value = _coerce(env, default) if env is not None else default
    _FLAGS[name] = {"default": default, "value": value, "doc": doc}


def set_flags(flags: Dict[str, Any]) -> None:
    """Mirror of ``paddle.set_flags`` (python/paddle/base/framework.py:64)."""
    for name, value in flags.items():
        key = name[len("FLAGS_"):] if name.startswith("FLAGS_") else name
        if key not in _FLAGS:
            raise ValueError(f"unknown flag {name!r}")
        _FLAGS[key]["value"] = _coerce(value, _FLAGS[key]["default"])


def get_flags(flags) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        key = name[len("FLAGS_"):] if name.startswith("FLAGS_") else name
        if key not in _FLAGS:
            raise ValueError(f"unknown flag {name!r}")
        out[name] = _FLAGS[key]["value"]
    return out


def flag(name: str):
    """Fast internal read."""
    return _FLAGS[name]["value"]


# ---- core flags (subset of reference's paddle/phi/core/flags.cc) ----
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf in eager mode")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; >0: report stats only")
define_flag("record_double_grad", True,
            "record primal recipes on the tape for paddle.grad(create_graph=True); disable to save memory in first-order-only runs")
define_flag("benchmark", False, "synchronize after each op for timing")
define_flag("paged_attention_backend", "auto",
            "decode paged-attention backend: auto (pool-streaming "
            "Pallas kernel on TPU, XLA gather elsewhere — see "
            "nn/functional/paged_attention.py) | stream | xla | fused "
            "(r4 per-sequence page-DMA Pallas kernel, opt-in) | pallas "
            "(stock jax kernel via a layout transpose)")
define_flag("attn_varlen_backend", "auto",
            "flash_attn_unpadded varlen flash-attention backend "
            "(nn/functional/flash_varlen.py): auto (segment-aware "
            "block-skipping Pallas kernel on TPU, the math-identical "
            "tiled XLA walk elsewhere) | pallas | interpret (the "
            "Pallas kernel through the interpreter — debug) | xla | "
            "dense (the legacy O(T^2) masked-dense path, reference "
            "only)")
define_flag("prefill_attention_backend", "auto",
            "chunked-prefill / speculative-verify attention over the "
            "paged pool (nn/functional/flash_varlen.py "
            "paged_prefill_attention): auto (block-table-indexed "
            "varlen kernel on TPU reading pages in place, tiled XLA "
            "walk elsewhere) | varlen (force the tiled walk family) | "
            "gather (legacy dense gather_kv_pages copy per chunk — "
            "also the forced path for int8-quantized pools)")
define_flag("decode_linear", "auto",
            "UNGROUPED decode matmul path (used when decode_grouped "
            "is off): auto (stream for int8 weights, XLA dots over "
            "loop-sliced stacked weights for bf16 — the r5 "
            "measurement) | xla | stream (force the Pallas "
            "weight-streaming kernel, nn/functional/stream_linear.py)")
define_flag("decode_grouped", "auto",
            "grouped decode weight streaming (fused O+LN2+FFN layer "
            "tail + QKV, <=2 streamed matmul calls per layer — "
            "nn/functional/stream_linear.py stream_layer_tail): auto "
            "(grouped for bf16/f32/weight-only-int8 stacks; A8W8 "
            "keeps the ungrouped int8 x int8 act-quant kernel) | on | "
            "off")
define_flag("moe_grouped_backend", "auto",
            "no-drop MoE ragged grouped-GEMM backend "
            "(nn/functional/grouped_gemm.py): auto (Pallas kernel on "
            "TPU, the math-identical tiled XLA walk elsewhere) | "
            "pallas | interpret (the kernel through the Pallas "
            "interpreter — debug/parity) | xla")
define_flag("decode_prefetch", True,
            "cross-layer prefetch inside the grouped decode tail: "
            "layer l+1's LN1+QKV projection runs as the tail kernel's "
            "final grid phase, overlapping its weight DMA with layer "
            "l's FFN compute; off = a separate streamed QKV call per "
            "layer (2 streamed calls/layer instead of 1)")
define_flag("compile_cache_dir", "",
            "persistent XLA compilation-cache directory (also settable "
            "via env PADDLE_TPU_COMPILE_CACHE_DIR): applied to "
            "jax_compilation_cache_dir at import by "
            "device.setup_compile_cache(), so recompiles of unchanged "
            "programs (e.g. the 25-min s2048 flash-attention backward) "
            "are served from disk across processes")
define_flag("check_donation", False,
            "use-after-donate poison mode (paddle_tpu.analysis.donation): "
            "buffers donated by the compiled-forward fast path are "
            "registered as poisoned after dispatch, and every subsequent "
            "dispatch / Tensor.numpy() asserts none of its inputs is one "
            "— CPU runs then fail exactly where TPU donation would read "
            "freed HBM, instead of silently passing (CPU jaxlib ignores "
            "donation)")
define_flag("serve_journal", True,
            "request-lifecycle flight recorder for the serving "
            "frontend (serving/journal.py): every lifecycle "
            "transition (submit/queued/admitted/prefill_chunk/"
            "first_token/decode/preempt/requeue/stall/evict_trigger/"
            "finish/error) lands in a bounded in-memory ring, dumped "
            "as a JSONL artifact on any run() exception; off = the "
            "scheduler holds no recorder and every hook is a single "
            "attribute test (zero journal allocations)")
define_flag("serve_journal_events", 4096,
            "flight-recorder ring capacity in events; older events "
            "are overwritten once the ring wraps (the journal.dropped "
            "gauge counts them)")
define_flag("serve_journal_dir", "",
            "directory for serving crash-dump artifacts "
            "(serve_crash_rank<r>_pid<pid>.jsonl, written by "
            "ServingEngine.run() on any raise; read back with "
            "tools/serve_top.py); empty = the system temp dir")
define_flag("serve_step_retries", 2,
            "crash-isolated stepping (serving/scheduler.py): retries "
            "granted to one request's prefill chunk / one decode "
            "chunk after an exception, each with capped exponential "
            "backoff, before the OFFENDING request alone errors out "
            "(state='error') while the serve loop keeps going")
define_flag("serve_retry_backoff_ms", 5.0,
            "base backoff between crash-isolated step retries; "
            "attempt k sleeps min(base * 2^(k-1), "
            "serve_retry_backoff_cap_ms) through the injectable "
            "serving clock (serving/faults.py — a ManualClock makes "
            "backoff a pure time-warp in tests)")
define_flag("serve_retry_backoff_cap_ms", 500.0,
            "cap on the exponential step-retry backoff")
define_flag("serve_watchdog_steps", 256,
            "progress watchdog: a request whose token progress "
            "(prefill position / generated count) hasn't moved for "
            "this many scheduler steps is preempted/requeued once, "
            "then failed on a second trip — the serve loop never "
            "hangs behind a wedged slot; 0 disables")
define_flag("serve_inbox_limit", 4096,
            "hard bound on the ServingEngine submit inbox; a full "
            "inbox rejects submit() with the typed ServerOverloaded "
            "(backpressure to the producer thread); 0 = unbounded")
define_flag("serve_shed_queue_depth", 0,
            "overload shedding: queue depth (inbox + waiting) at "
            "which admission rejects with ServerOverloaded and "
            "_drain_inbox sheds the sorted queue's overflow tail "
            "(lowest priority, newest first) into the 'shed' "
            "terminal state; 0 disables")
define_flag("serve_shed_burn_rate", 0.0,
            "overload shedding on service health: reject submits "
            "with ServerOverloaded while the rolling SLO burn-rate "
            "gauge (serving/slo.py) exceeds this; 0 disables")
define_flag("spec_k", 4,
            "speculative decoding window (inference/speculative.py): "
            "draft tokens proposed per verify round when the engines "
            "run with speculative= and no explicit spec_k; the verify "
            "pass scores k+1 tokens in ONE streamed program, so the "
            "weight stack is read once per accepted window instead of "
            "once per token")
define_flag("spec_drafter", "self",
            "default drafter for speculative=True: self (Medusa-style "
            "training-free self-drafting heads off the target's "
            "hidden state — zero extra weights to stream) | draft "
            "(requires an explicit FusedCausalLM draft model / "
            "DraftModelDrafter passed as speculative=, which keeps "
            "its own tiny non-paged KV state)")
define_flag("fleet_heartbeat_ms", 50.0,
            "fleet replica heartbeat interval (serving/router.py): "
            "each replica's serve loop stamps a beat through the "
            "injectable serving clock once per iteration; the "
            "router's health checker measures missed beats against "
            "this interval to walk a silent replica through the "
            "suspect -> dead state machine")
define_flag("fleet_suspect_beats", 3,
            "missed heartbeats before a fleet replica is marked "
            "SUSPECT (its queued-but-unadmitted requests hedge to a "
            "healthy peer); twice this many marks it DEAD and every "
            "in-flight request fails over via the recompute resume "
            "path")
define_flag("fleet_breaker_threshold", 3,
            "per-replica circuit breaker (serving/router.py): "
            "consecutive dispatch errors against one replica before "
            "its breaker opens and the router stops routing to it; a "
            "half-open probe re-admits it after the cooldown")
define_flag("fleet_dispatch_queue", 4096,
            "router-tier overload bound: fleet-wide queued-but-not-"
            "yet-admitted requests (every replica's inbox + waiting "
            "list) past this shed new submits with the typed "
            "FleetOverloaded BEFORE any replica admits; 0 = unbounded")
define_flag("tp_overlap", "psum",
            "row-parallel TP reduction schedule "
            "(nn/functional/stream_linear.py reduce_axis= seam, "
            "distributed/tp.py reduce_over_axis): psum (one blocking "
            "all-reduce per projection pair — the bitwise/census "
            "reference) | ring (the partial splits into mp column "
            "chunks and each chunk all-reduces via mp-1 ppermute "
            "steps pipelined under the next chunk's GEMM — "
            "mp*(mp-1) collective-permutes per reduction, none "
            "blocking the weight stream)")
define_flag("ep_overlap", False,
            "double-buffer the MoE expert-parallel exchange "
            "(nn/functional/grouped_gemm.py moe_ffn_ep): the "
            "dispatched capacity splits into two half buffers so "
            "expert compute on buffer 0 overlaps buffer 1's dispatch "
            "all_to_all and buffer 0's combine overlaps buffer 1's "
            "compute — census becomes 4 all_to_alls + 1 all_gather "
            "per MoE layer (off = the serialized "
            "dispatch/compute/combine triple, the census reference)")
define_flag("migrate_async", False,
            "asynchronous KV-page migration on a fleet drain "
            "(serving/router.py): COMPLETE pages stream to the "
            "destination in page-granular batches while BOTH "
            "endpoints keep taking decode steps (append-only pool "
            "writes never touch a completed page), and only the "
            "tail pages + slot metadata copy under the step locks "
            "at re-home; off = the whole export/import runs under "
            "the locks (the zero-loss reference path)")
define_flag("kv_host_tier_bytes", 0,
            "host-DRAM KV tier capacity per engine in bytes "
            "(serving/host_tier.py): cold PrefixCache chains and "
            "preempted-slot pages spill to host buffers instead of "
            "being evicted/recomputed, and re-admissions restore "
            "them back into free pool pages (int8-KV pools spill "
            "quantized rows + scale columns, so traffic roughly "
            "halves); 0 disables the tier and eviction releases "
            "pages outright")
define_flag("kv_restore_gbps", 10.0,
            "assumed host->HBM restore bandwidth (GB/s) for the "
            "router prefix-directory cost model "
            "(serving/router.py): a host-tier directory entry is "
            "worth PULLING when pages*page_bytes/bandwidth beats "
            "re-prefilling the covered tokens at "
            "FLAGS_disagg_prefill_tflops")
define_flag("disagg_prefill_tflops", 100.0,
            "assumed chunk-prefill throughput (TFLOP/s) for the "
            "directory cost model's re-prefill arm; lower it on "
            "hosts where prefill is slow (CPU rungs) so long "
            "host-tier prefixes pull instead of recompute")
define_flag("disagg", "",
            "fleet role split (serving/router.py FleetRouter): "
            "'' = symmetric replicas; 'auto' = half the fleet "
            "(>=1) becomes prefill-heavy and the rest decode-heavy; "
            "'P:D' pins the split explicitly. Prefill replicas take "
            "new admissions with prefill-weighted SLO interleave "
            "and hand finished-prefill slots to decode replicas "
            "over the export/import migration path (async when "
            "FLAGS_migrate_async), so decode TPOT never pays "
            "prefill stalls")
define_flag("lora_delta_backend", "auto",
            "batched multi-LoRA ragged delta-GEMM backend "
            "(nn/functional/lora.py lora_delta): auto (Pallas kernel "
            "on TPU, the math-identical tiled XLA walk elsewhere) | "
            "pallas | interpret (the kernel through the Pallas "
            "interpreter — debug/parity) | xla")
define_flag("tenant_quota_rps", 0.0,
            "router-tier per-tenant request rate limit "
            "(serving/router.py): submits from one tenant past this "
            "many requests per second (measured over "
            "FLAGS_tenant_quota_window_s on the injectable serving "
            "clock) shed with the typed TenantQuotaExceeded before "
            "any replica admits; 0 disables")
define_flag("tenant_quota_tokens", 0,
            "router-tier per-tenant token quota (serving/router.py): "
            "tokens billed to one tenant by the usage ledger "
            "(prefill + decode, FLAGS_usage_ledger must be on) "
            "within the rolling FLAGS_tenant_quota_window_s window "
            "past this shed the tenant's new submits with "
            "TenantQuotaExceeded; 0 disables")
define_flag("tenant_quota_window_s", 1.0,
            "rolling window (serving-clock seconds) both tenant "
            "quota legs measure against: the rate limiter keeps a "
            "per-tenant arrival deque pruned to this window and the "
            "token quota re-baselines each tenant's ledger token "
            "count once the window elapses")
define_flag("usage_ledger", False,
            "per-request -> per-tenant usage metering "
            "(serving/accounting.py UsageLedger): partitions every "
            "serve.step work phase across the requests it served and "
            "integrates KV page-seconds per request; off = the "
            "engine holds usage=None and every hook is one attribute "
            "test (zero per-step allocations)")
define_flag("usage_tenants_max", 64,
            "cardinality bound on per-tenant SLO goodput windows "
            "(serving/slo.py): tenants past this roll into the "
            "__other__ window instead of growing state unboundedly")
define_flag("usage_top_k", 4,
            "tenant gauges exported per telemetry tick "
            "(tenant.top<i>.device_ms, index-keyed): the bounded "
            "top-K slice of the ledger's per-tenant device time")
define_flag("telemetry_interval_ms", 0.0,
            "continuous time-series sampler "
            "(profiler/timeseries.py): default background sampling "
            "interval for TimeSeriesSampler.start() — every interval "
            "the sampler folds the stats registry (counters -> delta "
            "rates, gauges -> levels, histograms -> count/total) into "
            "bounded per-metric ring windows; 0 disables the default "
            "sampler (explicit tick() still works in tests)")
define_flag("telemetry_window", 512,
            "time-series retention: points kept per metric ring "
            "(profiler/timeseries.py) — fixed memory however long the "
            "serve runs; window aggregates (min/mean/max/p99) and "
            "serve_top --history sparklines read this window")
define_flag("telemetry_port", 0,
            "Prometheus text-format scrape endpoint "
            "(profiler/timeseries.py start_http_server): a stdlib "
            "http.server thread serves the stats registry as "
            "/metrics (counters *_total, histogram cumulative "
            "*_bucket) on this port; FleetRouter.start_telemetry "
            "serves the fleet-aggregated per-replica series (sum "
            "counters, max gauges) the same way; 0 = no exporter")
define_flag("serve_chunk_shrink", True,
            "graceful degradation under pool pressure: before a "
            "prefill chunk stalls/requeues for pages, shrink it "
            "(halving, page/bucket-aligned) until its tail pages fit "
            "the squeezed pool — tokens keep flowing at reduced "
            "chunk size instead of the request parking")
define_flag("use_bf16_matmul", True, "prefer bfloat16 matmul accumulation on the MXU")
define_flag("eager_fwd_cache", True,
            "no-grad eager dispatch through the signature-keyed "
            "compiled-forward cache (ops/dispatch.py); disable to force "
            "primitive-by-primitive eager execution")
define_flag("optimizer_donate_grads", False,
            "donate gradient buffers to the optimizer's fused update; "
            "grads are consumed by step() (p.grad is cleared), halving "
            "the step's transient gradient footprint")
define_flag("eager_jit_ops", True, "dispatch eager ops through cached jit computations")
define_flag("stop_check_timeout", 900, "bound (seconds) on distributed store waits")
define_flag("allocator_strategy", "auto_growth", "kept for API parity; PJRT owns memory")
define_flag("cudnn_deterministic", False, "kept for API parity; XLA is deterministic")
