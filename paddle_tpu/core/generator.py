"""Stateful RNG on top of JAX's functional keys.

TPU-native equivalent of the reference's per-device stateful ``Generator``
(reference: paddle/phi/core/generator.h) and the TP-aware
``RNGStatesTracker`` (reference:
python/paddle/distributed/fleet/layers/mpu/random.py:34), which keeps
separate named RNG streams so dropout stays deterministic across
tensor-parallel ranks.

Design: a Generator owns a jax PRNG key and splits it on every draw —
stateful shell over the functional core. ``rng_state(name)`` context
switches the default generator to a named tracked stream.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional

import jax

__all__ = [
    "Generator", "default_generator", "seed", "get_rng_state", "set_rng_state",
    "RNGStatesTracker", "get_rng_tracker", "rng_state",
]


class Generator:
    """Stateful PRNG: every ``next_key()`` splits the internal key."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int) -> "Generator":
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            # key creation is lazy: materializing it would initialize the
            # XLA backend, which must not happen at import time (it would
            # break a later jax.distributed.initialize in
            # init_parallel_env — the reference's import-then-init order)
            self._key = None
            self._offset = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def _materialize(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)

    def next_key(self):
        with self._lock:
            self._materialize()
            self._key, sub = jax.random.split(self._key)
            self._offset += 1
            return sub

    def get_state(self):
        return {"seed": self._seed, "offset": self._offset}

    def set_state(self, state) -> None:
        self.manual_seed(state["seed"])
        # replay the split chain to the recorded offset
        for _ in range(state["offset"]):
            self.next_key()
        self._offset = state["offset"]

    def spawn_key(self, data: int):
        """Deterministic fold-in (no state mutation) — for per-step keys."""
        with self._lock:
            self._materialize()
            return jax.random.fold_in(self._key, data)


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _CURRENT.gen if _CURRENT.gen is not None else _default_generator


def seed(value: int) -> Generator:
    """Mirror of ``paddle.seed``: reseed the default generator (and tracker)."""
    _default_generator.manual_seed(value)
    get_rng_tracker().reset(value)
    return _default_generator


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


class _Current(threading.local):
    def __init__(self):
        self.gen: Optional[Generator] = None
        # jit tracing: (traced base key, draw counter). While active, draws
        # come from fold_in(traced_key, n) so a compiled program gets fresh
        # randomness from its key operand each call instead of baking a
        # trace-time constant mask.
        self.trace_key = None
        self.trace_count = 0


_CURRENT = _Current()


@contextlib.contextmanager
def use_trace_key(key):
    prev = (_CURRENT.trace_key, _CURRENT.trace_count)
    _CURRENT.trace_key = key
    _CURRENT.trace_count = 0
    try:
        yield
    finally:
        _CURRENT.trace_key, _CURRENT.trace_count = prev


def next_rng_key():
    """Next key for an op needing randomness — trace-aware."""
    if _CURRENT.trace_key is not None:
        _CURRENT.trace_count += 1
        return jax.random.fold_in(_CURRENT.trace_key, _CURRENT.trace_count)
    return default_generator().next_key()


class RNGStatesTracker:
    """Named RNG streams for TP determinism (mpu/random.py:34 equivalent).

    ``add("local_seed", s)`` registers a stream; ``rng_state("local_seed")``
    makes draws inside the context come from that stream. Model-parallel
    layers use a rank-offset stream for dropout on sharded activations and
    the global stream for replicated ones.
    """

    def __init__(self):
        self.states_: Dict[str, Generator] = {}

    def reset(self, base_seed: Optional[int] = None):
        import zlib

        if base_seed is None:
            self.states_.clear()
        else:
            for name, gen in self.states_.items():
                # stable digest: python hash() is per-process randomized,
                # which would desync dropout masks across TP ranks
                gen.manual_seed(base_seed ^ zlib.crc32(name.encode()))

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise ValueError(f"rng state {name!r} already exists")
        self.states_[name] = Generator(seed)

    def get_states_tracker(self):
        return {k: g.get_state() for k, g in self.states_.items()}

    def set_states_tracker(self, states):
        for k, s in states.items():
            if k not in self.states_:
                self.states_[k] = Generator(0)
            self.states_[k].set_state(s)

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        if name == "global_seed":
            yield
            return
        if name not in self.states_:
            raise ValueError(f"rng state {name!r} was never added")
        prev = _CURRENT.gen
        _CURRENT.gen = self.states_[name]
        try:
            yield
        finally:
            _CURRENT.gen = prev


_tracker = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _tracker


def rng_state(name: str = "global_seed"):
    return _tracker.rng_state(name)
