"""Native runtime components (C++), bound via ctypes.

The reference keeps its rendezvous store in C++
(paddle/phi/core/distributed/store/tcp_store.h:121); so do we:
``tcp_store.cc`` compiles on first use into a cached shared library
(g++, no pybind11 dependency — plain C ABI + ctypes per the
environment's binding guidance).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import List, Optional

__all__ = ["TCPStore", "lib"]

_LIB = None
_LIB_LOCK = threading.Lock()


def _build_lib() -> str:
    src = os.path.join(os.path.dirname(__file__), "tcp_store.cc")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.environ.get(
        "PADDLE_TPU_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu"))
    os.makedirs(cache_dir, exist_ok=True)
    out = os.path.join(cache_dir, f"libpts_{digest}.so")
    if not os.path.exists(out):
        tmp = out + f".tmp{os.getpid()}"
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
             src, "-o", tmp],
            check=True, capture_output=True)
        os.replace(tmp, out)  # atomic: concurrent builders race safely
    return out


def lib() -> ctypes.CDLL:
    global _LIB
    with _LIB_LOCK:
        if _LIB is None:
            path = _build_lib()
            L = ctypes.CDLL(path)
            L.pts_server_start.restype = ctypes.c_void_p
            L.pts_server_start.argtypes = [ctypes.c_int]
            L.pts_server_port.restype = ctypes.c_int
            L.pts_server_port.argtypes = [ctypes.c_void_p]
            L.pts_server_stop.argtypes = [ctypes.c_void_p]
            L.pts_client_connect.restype = ctypes.c_void_p
            L.pts_client_connect.argtypes = [ctypes.c_char_p,
                                             ctypes.c_int, ctypes.c_int]
            L.pts_client_close.argtypes = [ctypes.c_void_p]
            L.pts_set.restype = ctypes.c_int
            L.pts_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p, ctypes.c_uint32]
            L.pts_add.restype = ctypes.c_longlong
            L.pts_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_longlong]
            L.pts_get.restype = ctypes.c_int
            L.pts_get.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_uint32)]
            L.pts_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
            L.pts_wait.restype = ctypes.c_int
            L.pts_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
            L.pts_check.restype = ctypes.c_int
            L.pts_check.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            L.pts_delete.restype = ctypes.c_int
            L.pts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            _LIB = L
        return _LIB


class TCPStore:
    """KV rendezvous store over the native server (reference:
    paddle.distributed.TCPStore / tcp_store.h:121 API: set/get/add/
    wait/delete_key; is_master hosts the map)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 120.0):
        self._lib = lib()
        self._server = None
        self.host = host
        self.port = port
        self.timeout_ms = int(timeout * 1000)
        if is_master:
            # port 0 → kernel picks; read it back (no TOCTOU rebind race)
            self._server = self._lib.pts_server_start(self.port)
            if not self._server:
                raise RuntimeError(f"TCPStore: bind failed on port "
                                   f"{self.port}")
            self.port = self._lib.pts_server_port(self._server)
            if self.port < 0:
                raise RuntimeError(
                    "TCPStore: could not read back the bound port")
        self._client = self._lib.pts_client_connect(
            self.host.encode(), self.port, self.timeout_ms)
        if not self._client:
            raise RuntimeError(
                f"TCPStore: cannot reach {self.host}:{self.port}")

    def set(self, key: str, value) -> None:
        data = value.encode() if isinstance(value, str) else bytes(value)
        rc = self._lib.pts_set(self._client, key.encode(), data,
                               len(data))
        if rc != 0:
            raise RuntimeError(f"TCPStore.set({key!r}) failed")

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        """Blocks until the key exists (reference TCPStore::get)."""
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint32()
        t = self.timeout_ms if timeout is None else int(timeout * 1000)
        rc = self._lib.pts_get(self._client, key.encode(), t,
                               ctypes.byref(out), ctypes.byref(out_len))
        if rc == 1:
            raise TimeoutError(f"TCPStore.get({key!r}) timed out")
        if rc != 0:
            raise RuntimeError(f"TCPStore.get({key!r}) failed")
        data = ctypes.string_at(out, out_len.value)
        self._lib.pts_free(out)
        return data

    def add(self, key: str, amount: int = 1) -> int:
        v = self._lib.pts_add(self._client, key.encode(), amount)
        if v == -0x7FFFFFFFFFFFFFFF:
            raise RuntimeError(f"TCPStore.add({key!r}) failed")
        return int(v)

    def wait(self, keys: List[str], timeout: Optional[float] = None):
        t = self.timeout_ms if timeout is None else int(timeout * 1000)
        rc = self._lib.pts_wait(self._client,
                                "\n".join(keys).encode(), t)
        if rc == 1:
            raise TimeoutError(f"TCPStore.wait({keys}) timed out")
        if rc != 0:
            raise RuntimeError("TCPStore.wait failed")

    def check(self, key: str) -> bool:
        rc = self._lib.pts_check(self._client, key.encode())
        if rc < 0:
            raise RuntimeError("TCPStore.check failed")
        return bool(rc)

    def delete_key(self, key: str) -> None:
        if self._lib.pts_delete(self._client, key.encode()) != 0:
            raise RuntimeError(f"TCPStore.delete_key({key!r}) failed")

    def __del__(self):
        try:
            if getattr(self, "_client", None):
                self._lib.pts_client_close(self._client)
                self._client = None
            if getattr(self, "_server", None):
                self._lib.pts_server_stop(self._server)
                self._server = None
        except Exception:
            pass
