// TCPStore — native key-value rendezvous store.
//
// TPU-native equivalent of the reference's C++ TCPStore
// (reference: paddle/phi/core/distributed/store/tcp_store.h:121 +
// socket.cpp): rank 0 hosts an in-memory map over TCP; clients
// set/get/add/wait/check/delete. get/wait BLOCK server-side on a
// condition variable until the key exists (the rendezvous primitive the
// reference brokers ncclUniqueId through; here it brokers launcher
// rendezvous, elastic membership, and eager p2p payloads).
//
// Wire protocol (all little-endian):
//   request:  u8 op | u32 klen | key | u32 vlen | value
//     op: 'S' set, 'G' get(blocking), 'A' add(i64 in value),
//         'W' wait(keys joined by '\n'), 'C' check, 'D' delete
//     timeout for G/W rides in vlen==4 payload (ms) when op=='G'/'W'.
//   response: u8 status (0 ok, 1 timeout/missing) | u32 len | payload
//
// Built as a shared library; Python binds via ctypes
// (paddle_tpu/core/native/__init__.py) — the pybind11-free binding path.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<uint8_t>> data;
};

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_resp(int fd, uint8_t status, const uint8_t* payload,
               uint32_t len) {
  if (!write_full(fd, &status, 1)) return false;
  if (!write_full(fd, &len, 4)) return false;
  if (len && !write_full(fd, payload, len)) return false;
  return true;
}

struct Server {
  Store store;
  int listen_fd = -1;
  std::atomic<bool> stopping{false};
  std::thread accept_thread;
  std::mutex workers_mu;
  std::vector<std::thread> workers;
  std::vector<int> conn_fds;  // guarded by workers_mu

  void handle(int fd) {
    for (;;) {
      uint8_t op;
      uint32_t klen, vlen;
      if (!read_full(fd, &op, 1) || !read_full(fd, &klen, 4)) break;
      std::string key(klen, '\0');
      if (klen && !read_full(fd, key.data(), klen)) break;
      if (!read_full(fd, &vlen, 4)) break;
      std::vector<uint8_t> val(vlen);
      if (vlen && !read_full(fd, val.data(), vlen)) break;

      if (op == 'S') {
        {
          std::lock_guard<std::mutex> lk(store.mu);
          store.data[key] = std::move(val);
        }
        store.cv.notify_all();
        if (!send_resp(fd, 0, nullptr, 0)) break;
      } else if (op == 'A') {
        int64_t amount = 0;
        if (vlen == 8) std::memcpy(&amount, val.data(), 8);
        int64_t now;
        {
          std::lock_guard<std::mutex> lk(store.mu);
          auto& slot = store.data[key];
          int64_t cur = 0;
          if (slot.size() == 8) std::memcpy(&cur, slot.data(), 8);
          now = cur + amount;
          slot.resize(8);
          std::memcpy(slot.data(), &now, 8);
        }
        store.cv.notify_all();
        if (!send_resp(fd, 0, reinterpret_cast<uint8_t*>(&now), 8)) break;
      } else if (op == 'G' || op == 'W') {
        int32_t timeout_ms = 120000;
        // key carries "key" (G) or "k1\nk2" (W); val carries timeout
        if (vlen == 4) std::memcpy(&timeout_ms, val.data(), 4);
        std::vector<std::string> keys;
        size_t pos = 0;
        while (pos <= key.size()) {
          size_t nl = key.find('\n', pos);
          if (nl == std::string::npos) {
            keys.push_back(key.substr(pos));
            break;
          }
          keys.push_back(key.substr(pos, nl - pos));
          pos = nl + 1;
        }
        std::unique_lock<std::mutex> lk(store.mu);
        auto have_all = [&] {
          for (auto& k : keys)
            if (store.data.find(k) == store.data.end()) return false;
          return true;
        };
        bool ok = store.cv.wait_for(
            lk, std::chrono::milliseconds(timeout_ms),
            [&] { return have_all() || stopping.load(); });
        if (!ok || stopping.load()) {
          lk.unlock();
          if (!send_resp(fd, 1, nullptr, 0)) break;
          continue;
        }
        if (op == 'G') {
          auto payload = store.data[keys[0]];  // copy under lock
          lk.unlock();
          if (!send_resp(fd, 0, payload.data(),
                         static_cast<uint32_t>(payload.size())))
            break;
        } else {
          lk.unlock();
          if (!send_resp(fd, 0, nullptr, 0)) break;
        }
      } else if (op == 'C') {
        uint8_t exists;
        {
          std::lock_guard<std::mutex> lk(store.mu);
          exists = store.data.count(key) ? 1 : 0;
        }
        if (!send_resp(fd, 0, &exists, 1)) break;
      } else if (op == 'D') {
        {
          std::lock_guard<std::mutex> lk(store.mu);
          store.data.erase(key);
        }
        if (!send_resp(fd, 0, nullptr, 0)) break;
      } else {
        break;  // unknown op: drop connection
      }
    }
    {
      // deregister before close so stop() never shuts down a reused fd
      std::lock_guard<std::mutex> lk(workers_mu);
      conn_fds.erase(std::remove(conn_fds.begin(), conn_fds.end(), fd),
                     conn_fds.end());
    }
    ::close(fd);
  }

  void accept_loop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stopping.load()) return;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(workers_mu);
      if (stopping.load()) {
        ::close(fd);
        return;
      }
      conn_fds.push_back(fd);
      workers.emplace_back([this, fd] { handle(fd); });
    }
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;  // one outstanding request per client
};

}  // namespace

extern "C" {

void* pts_server_start(int port) {
  auto* s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) < 0 ||
      ::listen(s->listen_fd, 128) < 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return s;
}

int pts_server_port(void* h) {
  // actual bound port (port=0 requests let the kernel choose — no
  // probe-then-rebind TOCTOU race)
  auto* s = static_cast<Server*>(h);
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
                    &len) != 0)
    return -1;
  return ntohs(addr.sin_port);
}

void pts_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  s->stopping.store(true);
  s->store.cv.notify_all();
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  // Wake every handler: shut down its connection fd so blocked recv()
  // returns, then JOIN (never detach — a detached handler could touch
  // the Store after delete).
  {
    std::lock_guard<std::mutex> lk(s->workers_mu);
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : s->workers)
    if (t.joinable()) t.join();
  delete s;
}

void* pts_client_connect(const char* host, int port, int timeout_ms) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  std::snprintf(portstr, sizeof(portstr), "%d", port);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  // retry until the server is up (rank-0 races are normal at bootstrap)
  for (;;) {
    if (::getaddrinfo(host, portstr, &hints, &res) == 0) {
      int fd = ::socket(res->ai_family, res->ai_socktype,
                        res->ai_protocol);
      if (fd >= 0 &&
          ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
        ::freeaddrinfo(res);
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        auto* c = new Client();
        c->fd = fd;
        return c;
      }
      if (fd >= 0) ::close(fd);
      ::freeaddrinfo(res);
      res = nullptr;
    }
    if (std::chrono::steady_clock::now() > deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void pts_client_close(void* h) {
  auto* c = static_cast<Client*>(h);
  ::close(c->fd);
  delete c;
}

static int request(Client* c, uint8_t op, const char* key,
                   const uint8_t* val, uint32_t vlen, uint8_t* status,
                   std::vector<uint8_t>* payload) {
  std::lock_guard<std::mutex> lk(c->mu);
  uint32_t klen = static_cast<uint32_t>(std::strlen(key));
  if (!write_full(c->fd, &op, 1) || !write_full(c->fd, &klen, 4) ||
      !write_full(c->fd, key, klen) || !write_full(c->fd, &vlen, 4) ||
      (vlen && !write_full(c->fd, val, vlen)))
    return -1;
  uint32_t rlen;
  if (!read_full(c->fd, status, 1) || !read_full(c->fd, &rlen, 4))
    return -1;
  payload->resize(rlen);
  if (rlen && !read_full(c->fd, payload->data(), rlen)) return -1;
  return 0;
}

int pts_set(void* h, const char* key, const uint8_t* val, uint32_t len) {
  uint8_t status;
  std::vector<uint8_t> payload;
  if (request(static_cast<Client*>(h), 'S', key, val, len, &status,
              &payload) != 0)
    return -1;
  return status;
}

long long pts_add(void* h, const char* key, long long amount) {
  uint8_t status;
  std::vector<uint8_t> payload;
  int64_t amt = amount;
  if (request(static_cast<Client*>(h), 'A', key,
              reinterpret_cast<uint8_t*>(&amt), 8, &status,
              &payload) != 0 ||
      status != 0 || payload.size() != 8)
    return -0x7FFFFFFFFFFFFFFFLL;
  int64_t v;
  std::memcpy(&v, payload.data(), 8);
  return v;
}

int pts_get(void* h, const char* key, int timeout_ms, uint8_t** out,
            uint32_t* out_len) {
  uint8_t status;
  std::vector<uint8_t> payload;
  int32_t t = timeout_ms;
  if (request(static_cast<Client*>(h), 'G', key,
              reinterpret_cast<uint8_t*>(&t), 4, &status, &payload) != 0)
    return -1;
  if (status != 0) return 1;  // timeout
  *out_len = static_cast<uint32_t>(payload.size());
  *out = static_cast<uint8_t*>(std::malloc(payload.size()));
  std::memcpy(*out, payload.data(), payload.size());
  return 0;
}

void pts_free(uint8_t* p) { std::free(p); }

int pts_wait(void* h, const char* keys_nl, int timeout_ms) {
  uint8_t status;
  std::vector<uint8_t> payload;
  int32_t t = timeout_ms;
  if (request(static_cast<Client*>(h), 'W', keys_nl,
              reinterpret_cast<uint8_t*>(&t), 4, &status, &payload) != 0)
    return -1;
  return status;  // 0 ok, 1 timeout
}

int pts_check(void* h, const char* key) {
  uint8_t status;
  std::vector<uint8_t> payload;
  if (request(static_cast<Client*>(h), 'C', key, nullptr, 0, &status,
              &payload) != 0 ||
      payload.size() != 1)
    return -1;
  return payload[0];
}

int pts_delete(void* h, const char* key) {
  uint8_t status;
  std::vector<uint8_t> payload;
  if (request(static_cast<Client*>(h), 'D', key, nullptr, 0, &status,
              &payload) != 0)
    return -1;
  return status;
}

}  // extern "C"
