"""Device places.

TPU-native equivalent of the reference's ``phi::Place`` / ``CUDAPlace``
(reference: paddle/phi/common/place.h). A Place names a logical device; the
backing object is a ``jax.Device``. ``TPUPlace`` replaces ``CUDAPlace``;
``CPUPlace`` is kept for host tensors and for the virtual-device test mesh.
"""
from __future__ import annotations

import functools

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "set_device", "get_device",
    "device_count", "current_place", "is_compiled_with_tpu",
]


@functools.lru_cache(maxsize=None)
def _devices_for(platform: str):
    try:
        return tuple(jax.devices(platform))
    except RuntimeError:
        return ()


def _accelerator_platform() -> str | None:
    """The non-CPU platform jax was initialized with, if any."""
    backend = jax.default_backend()
    return None if backend == "cpu" else backend


class Place:
    """Base place: (device_kind, device_id)."""

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = int(device_id)

    def jax_device(self) -> jax.Device:
        if self.device_type == "cpu":
            devs = _devices_for("cpu")
        else:
            # 'tpu' place maps onto whatever accelerator platform is live
            # (real TPU, or the tunneled 'axon' platform, or CPU fallback in
            # the virtual-device test harness).
            plat = _accelerator_platform()
            devs = _devices_for(plat) if plat else _devices_for("cpu")
        if not devs:
            raise RuntimeError(f"no devices for place {self}")
        return devs[self.device_id % len(devs)]

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type == "tpu"

    # paddle compat: CUDAPlace queries map to the accelerator
    def is_gpu_place(self):
        return self.is_tpu_place()


class CPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("cpu", device_id)


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


_current_place: Place | None = None


def _default_place() -> Place:
    if _accelerator_platform() is not None:
        return TPUPlace(0)
    return CPUPlace(0)


def current_place() -> Place:
    return _current_place if _current_place is not None else _default_place()


def set_device(device: str) -> Place:
    """``set_device("tpu:0")`` / ``"cpu"`` — mirrors ``paddle.set_device``."""
    global _current_place
    if ":" in device:
        kind, _, idx = device.partition(":")
        idx = int(idx)
    else:
        kind, idx = device, 0
    if kind in ("tpu", "gpu", "cuda", "xpu"):  # accept gpu spelling for compat
        _current_place = TPUPlace(idx)
    elif kind == "cpu":
        _current_place = CPUPlace(idx)
    else:
        raise ValueError(f"unknown device {device!r}")
    return _current_place


def get_device() -> str:
    p = current_place()
    return f"{p.device_type}:{p.device_id}"


def device_count() -> int:
    plat = _accelerator_platform()
    return len(_devices_for(plat) if plat else _devices_for("cpu"))


def is_compiled_with_tpu() -> bool:
    return _accelerator_platform() is not None
