"""Eager Tensor.

TPU-native equivalent of the reference's eager ``paddle::Tensor``
(reference: paddle/phi/api/include/tensor.h:82 and the pybind eager tensor
in paddle/fluid/pybind/eager.cc). The backing store is an immutable
``jax.Array`` (PJRT buffer); "in-place" ops rebind ``_data`` and bump a
version counter, which is exactly the functional-rewrite the XLA
programming model wants while preserving Paddle's mutable-tensor API.

Autograd state lives on the tensor: ``stop_gradient`` (Paddle defaults new
tensors to True; ``Parameter`` flips it), ``grad``, and the producing
``GradNode`` + output slot (reference: AutogradMeta in
paddle/fluid/eager/autograd_meta.h).

Op methods (``t.matmul``, ``t.__add__`` …) are attached by the ops modules
at import time via ``Tensor._attach_method`` — the tensor-method surface is
generated from the op registry, mirroring how the reference generates
method bindings from ops.yaml.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import engine
from .dtype import DType, convert_dtype
from .place import Place, current_place

__all__ = ["Tensor", "Parameter", "to_tensor"]

_name_counter = itertools.count()
_hook_counter = itertools.count()


class Tensor:
    __slots__ = (
        "_data", "stop_gradient", "grad", "_grad_node", "_out_idx",
        "name", "persistable", "_grad_hooks", "_version", "__weakref__",
        "_dist_attr", "_static_program",
    )

    def __init__(self, data, stop_gradient: bool = True, name: str = None):
        if isinstance(data, Tensor):
            data = data._data
        elif not isinstance(data, jax.Array):
            import numpy as _np

            host = _np.asarray(data)
            if _np.issubdtype(host.dtype, _np.complexfloating):
                # the TPU backend has no complex support — complex
                # tensors live on the host CPU device from creation
                # (a TPU-resident complex buffer can't even be read back)
                data = jax.device_put(host, jax.devices("cpu")[0])
            else:
                data = jnp.asarray(host)  # single conversion
        self._data: jax.Array = data
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self._grad_node: Optional[engine.GradNode] = None
        self._out_idx: int = 0
        self.name = name or f"generated_tensor_{next(_name_counter)}"
        self.persistable = False
        self._grad_hooks: Dict[int, Callable] = {}
        self._version = 0
        self._dist_attr = None  # (ProcessMesh, placements) when distributed

    # ---------------- basic properties ----------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self) -> DType:
        return convert_dtype(self._data.dtype)

    @property
    def place(self) -> Place:
        return current_place()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def T(self):
        return self.transpose(list(range(self.ndim))[::-1])

    def dim(self):
        return self.ndim

    def numel(self):
        return self.size

    # ---------------- conversion ----------------
    def numpy(self) -> np.ndarray:
        from .flags import flag

        if flag("check_donation"):
            from ..analysis import donation as _don

            _don.assert_not_poisoned([self._data], "Tensor.numpy()")
        return np.asarray(self._data)

    def item(self):
        return self._data.item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    # ---------------- autograd ----------------
    def backward(self, grad_tensor: "Tensor" = None, retain_graph: bool = False):
        engine.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._data))
        else:
            self.grad = None

    def zero_grad(self):
        self.clear_grad()

    def retain_grads(self):
        """Ask a non-leaf to keep its grad after backward (Paddle API)."""
        if self._grad_node is not None:
            targets = self._grad_node.retain_map.get(self._out_idx, [])
            if not any(t is self for t in targets):
                self._grad_node.add_retain(self._out_idx, self)

    def register_hook(self, hook: Callable):
        hid = next(_hook_counter)
        self._grad_hooks[hid] = hook

        class _Handle:
            def remove(_self):
                self._grad_hooks.pop(hid, None)

        return _Handle()

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        # participates in autograd like an identity op
        from ..ops.dispatch import eager_apply

        return eager_apply("clone", lambda x: x + 0, [self], {})

    # ---------------- mutation (functional rebind) ----------------
    def _rebind(self, new_array, node: engine.GradNode = None, out_idx: int = 0):
        """In-place update: swap the buffer, bump version (inplace version
        check parity with reference tensor_wrapper.h)."""
        self._data = new_array
        self._version += 1
        if node is not None:
            self._grad_node = node
            self._out_idx = out_idx

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        self._rebind(jnp.asarray(value, self._data.dtype).reshape(self._data.shape))

    def copy_(self, other, blocking: bool = True):
        self.set_value(other)
        return self

    @property
    def inplace_version(self):
        return self._version

    # ---------------- misc ----------------
    def __repr__(self):
        grad_part = f", stop_gradient={self.stop_gradient}"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_part},\n       {np.asarray(self._data)})")

    def __hash__(self):
        return id(self)

    # method attachment point used by ops modules
    @classmethod
    def _attach_method(cls, name: str, fn: Callable):
        setattr(cls, name, fn)

    # block jnp from consuming Tensor via operators and returning jax arrays
    __jax_array__ = None


# remove the placeholder so jnp.asarray(Tensor) raises rather than silently
# treating it as an opaque object
del Tensor.__jax_array__


class Parameter(Tensor):
    """Trainable tensor: ``stop_gradient=False``, ``persistable=True``
    (reference: python/paddle/base/framework.py Parameter)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip")

    def __init__(self, data, name: str = None, trainable: bool = True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """``paddle.to_tensor`` equivalent."""
    if isinstance(data, Tensor):
        arr = data._data
    elif isinstance(data, jax.Array):
        arr = data
    else:
        arr = np.asarray(data)
        # paddle keeps python float defaulting to the default float dtype
        if arr.dtype == np.float64 and not isinstance(data, np.ndarray) and dtype is None:
            from .dtype import get_default_dtype

            arr = arr.astype(get_default_dtype().np_dtype)
        if np.issubdtype(arr.dtype, np.complexfloating):
            # TPU has no complex support — keep complex on the host CPU
            arr = jax.device_put(arr, jax.devices("cpu")[0])
        else:
            arr = jnp.asarray(arr)
    if dtype is not None:
        np_dtype = convert_dtype(dtype).np_dtype
        if np.issubdtype(np_dtype, np.complexfloating) and \
                getattr(arr, "device", None) is not None and \
                getattr(arr.device, "platform", "cpu") != "cpu":
            # casting TO complex must also leave the TPU device
            arr = jax.device_put(np.asarray(arr).astype(np_dtype),
                                 jax.devices("cpu")[0])
        else:
            arr = arr.astype(np_dtype)
    if place is not None and isinstance(place, Place):
        arr = jax.device_put(arr, place.jax_device())
    return Tensor(arr, stop_gradient=stop_gradient)
