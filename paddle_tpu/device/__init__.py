"""paddle_tpu.device — device control + memory observability.

TPU-native equivalent of the reference's device API (reference:
python/paddle/device — set_device/get_device/synchronize — and the memory
stats surface paddle/fluid/memory/stats.h + paddle.device.cuda.
max_memory_allocated). PJRT owns device memory on TPU; the stats facade
reads the runtime's per-device counters instead of keeping its own
allocator bookkeeping.
"""
from __future__ import annotations

import jax

from ..core.place import (  # noqa: F401
    CPUPlace, Place, TPUPlace, current_place, device_count, get_device,
    is_compiled_with_tpu, set_device,
)
from . import vmem  # noqa: F401  (per-generation VMEM budget table)
from .vmem import (  # noqa: F401
    KERNEL_VMEM_LIMIT_BYTES, VMEM_BUDGET_BYTES, vmem_budget_bytes,
)

__all__ = [
    "set_device", "get_device", "device_count", "current_place",
    "synchronize", "memory_stats", "memory_allocated",
    "max_memory_allocated", "memory_reserved", "max_memory_reserved",
    "reset_peak_memory_stats", "empty_cache", "setup_compile_cache",
    "Place", "CPUPlace", "TPUPlace", "is_compiled_with_tpu",
    "is_compiled_with_cuda", "is_compiled_with_xpu", "cuda", "tpu",
    "vmem", "VMEM_BUDGET_BYTES", "KERNEL_VMEM_LIMIT_BYTES",
    "vmem_budget_bytes",
]


def setup_compile_cache(path=None):
    """Wire the persistent XLA compilation cache.

    ``path`` (or ``FLAGS_compile_cache_dir`` / env
    ``PADDLE_TPU_COMPILE_CACHE_DIR`` when omitted) becomes jax's
    ``jax_compilation_cache_dir``: compiled executables are written to
    disk and re-loaded by later processes, so a warm run skips the
    multi-minute XLA compiles the cold run paid (the s2048 rung's
    flash-attention backward alone measured ~25 min cold, r5).
    Called automatically at ``import paddle_tpu``; call again after
    ``set_flags({"FLAGS_compile_cache_dir": ...})`` to re-point it.
    Returns the applied path, or None when no path is configured.
    The ``compile.persistent_cache`` gauge records whether a cache dir
    is active, so bench telemetry shows which regime — cold or
    cache-warm — a compile-seconds histogram was measured under."""
    from ..core.flags import flag
    from ..profiler import stats as _stats

    path = path or flag("compile_cache_dir")
    if not path:
        _stats.set_gauge("compile.persistent_cache", 0)
        return None
    jax.config.update("jax_compilation_cache_dir", str(path))
    # cache even fast-compiling programs: the decode/prefill serving
    # programs are individually cheap but numerous, and CI correctness
    # runs recompile them every process
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    except AttributeError:  # older jax: flag absent — defaults apply
        pass
    _stats.set_gauge("compile.persistent_cache", 1)
    return str(path)


def _resolve(device=None) -> jax.Device:
    if device is None:
        return current_place().jax_device()
    if isinstance(device, Place):
        return device.jax_device()
    if isinstance(device, jax.Device):
        return device
    if isinstance(device, int):
        return jax.devices()[device]
    return Place(*_split(str(device))).jax_device()


def _split(spec: str):
    if ":" in spec:
        kind, idx = spec.split(":")
        return kind, int(idx)
    return spec, 0


def synchronize(device=None) -> None:
    """Block until all queued work on the device is complete (reference:
    paddle.device.synchronize / cudaDeviceSynchronize). XLA execution is
    data-dependency-ordered, so the fence is: put a trivial computation on
    the device and block on its result — everything enqueued before it on
    the same device is complete when it returns."""
    import jax.numpy as jnp

    dev = _resolve(device)
    jax.device_put(jnp.zeros(()), dev).block_until_ready()


def memory_stats(device=None) -> dict:
    """Raw PJRT memory counters (reference: memory/stats.h Stat registry).
    Keys follow the PJRT allocator: bytes_in_use, peak_bytes_in_use,
    bytes_limit, ... Empty dict when the backend exposes none (CPU)."""
    dev = _resolve(device)
    try:
        return dict(dev.memory_stats() or {})
    except Exception:
        return {}


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the device (reference:
    paddle.device.cuda.memory_allocated)."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak allocated bytes (reference: memory/stats.h peak tracking,
    paddle.device.cuda.max_memory_allocated)."""
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Bytes reserved by the allocator pool; PJRT reports the pool limit
    region in bytes_reserved, falling back to bytes_in_use where the
    backend has no pool concept."""
    stats = memory_stats(device)
    return int(stats.get("bytes_reserved", stats.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    stats = memory_stats(device)
    return int(stats.get("peak_bytes_reserved",
                         stats.get("peak_bytes_in_use", 0)))


def reset_peak_memory_stats(device=None) -> None:
    """PJRT exposes no peak-reset; raise rather than silently no-op
    (the reference resets its own Stat registry — ours is the runtime's)."""
    raise NotImplementedError(
        "PJRT does not expose a peak-counter reset; snapshot "
        "max_memory_allocated() and diff instead")


def empty_cache() -> None:
    """Best-effort release of framework-held caches (reference:
    paddle.device.cuda.empty_cache). XLA's allocator manages its own
    pool; we clear jit caches so dead executables release buffers."""
    jax.clear_caches()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


class _DeviceNamespace:
    """paddle.device.cuda-compatible namespace (maps onto the TPU/PJRT
    counters so reference code reading .cuda keeps working)."""

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return max_memory_reserved(device)

    @staticmethod
    def synchronize(device=None):
        return synchronize(device)

    @staticmethod
    def empty_cache():
        return empty_cache()

    @staticmethod
    def device_count():
        return device_count()


cuda = _DeviceNamespace()
tpu = _DeviceNamespace()
