"""Per-generation TPU VMEM budgets — the single source of truth for
kernel VMEM limits.

Every hand-tuned Pallas kernel in the repo caps its scoped-VMEM use via
``compiler_params(vmem_limit_bytes=...)``. Those caps used to be magic
``100 * 1024 * 1024`` literals scattered across the kernel modules; the
geometry pass of ``paddle_tpu.analysis`` flags any such literal
(rule ``G-MAGIC``) and this module is where the number actually comes
from: the physical VMEM of the target generation minus a fixed reserve
for Mosaic's own scratch (spills, semaphores, pipelining bookkeeping).

Physical VMEM per TensorCore by generation (v2-v4 from the public TPU
system architecture docs; v5e confirmed empirically by the r5 kernel
bring-up — the repo's streaming kernels run with a 100MB cap on v5e):

    v2 / v3 : 16 MiB
    v4+     : 128 MiB (v4, v5e, v5p, v6e)

Off-TPU (CPU interpret mode) the budget is irrelevant to execution but
the analyzer still validates against the DEFAULT serving generation so
CI catches geometry that would not fit the chip.
"""
from __future__ import annotations

__all__ = [
    "MiB", "GiB", "VMEM_BUDGET_BYTES", "VMEM_RESERVE_BYTES",
    "DEFAULT_GENERATION", "KERNEL_VMEM_LIMIT_BYTES",
    "MOSAIC_DEFAULT_VMEM_LIMIT_BYTES", "vmem_budget_bytes",
    "HBM_BUDGET_BYTES", "HBM_RESERVE_BYTES", "hbm_budget_bytes",
    "detect_generation",
]

MiB = 1 << 20
GiB = 1 << 30

#: physical VMEM bytes per TensorCore, by TPU generation
VMEM_BUDGET_BYTES = {
    "v2": 16 * MiB,
    "v3": 16 * MiB,
    "v4": 128 * MiB,
    "v5e": 128 * MiB,
    "v5p": 128 * MiB,
    "v6e": 128 * MiB,
}

#: physical HBM bytes per chip, by TPU generation (public TPU system
#: architecture docs; the MEMORY pass of ``paddle_tpu.analysis`` checks
#: a program's static peak-live-bytes bound against this table, so
#: "this 13B config OOMs on v5e" is a CPU-side lint finding instead of
#: a burned chip session)
HBM_BUDGET_BYTES = {
    "v2": 8 * GiB,
    "v3": 16 * GiB,
    "v4": 32 * GiB,
    "v5e": 16 * GiB,
    "v5p": 95 * GiB,
    "v6e": 32 * GiB,
}

#: HBM held back from the analyzer's budget: the XLA runtime's own
#: allocations (executables, infeed/outfeed, framework scratch) that a
#: program's buffer liveness never sees
HBM_RESERVE_BYTES = 1 * GiB

#: headroom left to the Mosaic compiler for its own scratch — register
#: spills, DMA semaphores, pipelining bookkeeping — on top of what the
#: kernel's declared blocks/scratch consume
VMEM_RESERVE_BYTES = 28 * MiB

#: the serving generation the hand-tuned kernel geometry targets (the
#: chip every BENCH_r* number was measured on)
DEFAULT_GENERATION = "v5e"

#: the vmem_limit_bytes every repo Pallas kernel declares: generation
#: budget minus the Mosaic reserve (= the historical 100 MiB cap, now
#: derived instead of hard-coded)
KERNEL_VMEM_LIMIT_BYTES = (
    VMEM_BUDGET_BYTES[DEFAULT_GENERATION] - VMEM_RESERVE_BYTES)

#: what a pallas_call gets when it declares NO vmem_limit_bytes — the
#: conservative scoped-VMEM default of the XLA:TPU compiler
#: (xla_tpu_scoped_vmem_limit_kib = 16384)
MOSAIC_DEFAULT_VMEM_LIMIT_BYTES = 16 * MiB

#: jax device_kind strings -> generation keys (prefix match, checked
#: longest-first so "v5 lite" beats "v5")
_DEVICE_KIND_MAP = (
    ("tpu v6 lite", "v6e"),
    ("tpu v6e", "v6e"),
    ("tpu v5 lite", "v5e"),
    ("tpu v5e", "v5e"),
    ("tpu v5p", "v5p"),
    ("tpu v5", "v5p"),
    ("tpu v4", "v4"),
    ("tpu v3", "v3"),
    ("tpu v2", "v2"),
)


def detect_generation(default: str = DEFAULT_GENERATION) -> str:
    """TPU generation of the attached accelerator, or ``default`` when
    running off-TPU (CPU CI analyses against the serving target)."""
    try:
        import jax

        if jax.default_backend() != "tpu":
            return default
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return default
    for prefix, gen in _DEVICE_KIND_MAP:
        if kind.startswith(prefix):
            return gen
    return default


def vmem_budget_bytes(generation: str | None = None) -> int:
    """Physical VMEM budget for ``generation`` (auto-detected when
    None). Unknown generations fall back to the conservative 16 MiB."""
    gen = generation or detect_generation()
    return VMEM_BUDGET_BYTES.get(gen, 16 * MiB)


def hbm_budget_bytes(generation: str | None = None) -> int:
    """Usable HBM for ``generation`` (auto-detected when None): the
    physical capacity minus the runtime reserve. Unknown generations
    fall back to the conservative v5e 16 GiB."""
    gen = generation or detect_generation()
    return (HBM_BUDGET_BYTES.get(gen, HBM_BUDGET_BYTES["v5e"])
            - HBM_RESERVE_BYTES)
