"""paddle_tpu.distributed — mirrors python/paddle/distributed.

Built out incrementally; env/rank plumbing first, then collectives, mesh
sharding, fleet, and parallel wrappers (SURVEY.md §2.3 inventory).
"""
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401

__all__ = ["ParallelEnv", "get_rank", "get_world_size"]
