"""paddle_tpu.distributed — mirrors python/paddle/distributed.

SPMD core: ProcessMesh + placements + shard_tensor/reshard over
jax.sharding (GSPMD inserts the collectives, they ride ICI). The
imperative collective API compiles per-call; fleet layers hybrid
parallelism on top (SURVEY.md §2.3).
"""
from .auto_parallel import (  # noqa: F401
    Partial, Placement, ProcessMesh, Replicate, Shard, dtensor_from_fn,
    dtensor_from_local, get_mesh, reshard, set_mesh, shard_layer,
    shard_tensor, unshard_dtensor,
)
from .communication import (  # noqa: F401
    Group, P2POp, ReduceOp, all_gather, all_gather_object, all_reduce,
    all_to_all, all_to_all_single, barrier, batch_isend_irecv, broadcast,
    broadcast_object_list, destroy_process_group, gather, get_backend,
    get_group, irecv, isend, new_group, recv, reduce, reduce_scatter,
    scatter, scatter_object_list, send, wait,
)
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401
from . import fleet  # noqa: F401
from .parallel import DataParallel, init_parallel_env, is_initialized  # noqa: F401
from ..core.native import TCPStore  # noqa: F401  (native C++ store)
from .check import CommWatchdog, watchdog  # noqa: F401
from . import tp  # noqa: F401  (tensor-parallel serving mesh helpers)
from .tp import TPContext, serving_mesh, split_kv_heads  # noqa: F401

__all__ = [
    "ProcessMesh", "Placement", "Replicate", "Shard", "Partial",
    "shard_tensor", "reshard", "shard_layer", "dtensor_from_fn",
    "dtensor_from_local", "unshard_dtensor", "get_mesh", "set_mesh",
    "Group", "new_group", "get_group", "ReduceOp", "all_reduce",
    "all_gather", "all_gather_object", "all_to_all", "all_to_all_single",
    "reduce", "reduce_scatter", "broadcast", "broadcast_object_list",
    "scatter", "scatter_object_list", "send", "recv", "isend", "irecv",
    "P2POp", "batch_isend_irecv", "gather", "barrier", "wait",
    "get_backend", "destroy_process_group", "ParallelEnv", "get_rank",
    "get_world_size", "DataParallel", "init_parallel_env", "is_initialized",
    "TPContext", "serving_mesh", "split_kv_heads",
]
from . import ps  # noqa: F401  (raise-stub surface, SURVEY §7.3)
from . import rpc  # noqa: F401
