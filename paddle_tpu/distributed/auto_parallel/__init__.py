from .api import (  # noqa: F401
    dtensor_from_fn, dtensor_from_local, reshard, shard_layer, shard_tensor,
    unshard_dtensor,
)
from .placement import (  # noqa: F401
    Partial, Placement, ProcessMesh, Replicate, Shard, get_mesh, set_mesh,
)
from .engine import Engine  # noqa: F401
