"""Semi-auto parallel API: shard_tensor / reshard / shard_layer.

TPU-native equivalent of the reference's dygraph semi-auto API
(reference: python/paddle/distributed/auto_parallel/api.py —
shard_tensor:118, reshard:282, shard_layer:381; reshard function pairs in
paddle/phi/core/distributed/auto_parallel/reshard/). Where the reference
implements 9 reshard function pairs {r,s,p}×{r,s,p} + cross-mesh in C++,
here GSPMD does the work: a reshard is ``jax.device_put`` to the target
``NamedSharding`` (XLA inserts all-gather/all-to-all/slice), and
Partial→{Replicate,Shard} is a compiled psum over the mesh axis.
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Parameter, Tensor
from .placement import Partial, Placement, ProcessMesh, Replicate, Shard

__all__ = ["shard_tensor", "reshard", "shard_layer", "dtensor_from_fn",
           "unshard_dtensor", "dtensor_from_local"]


def _normalize_placements(mesh: ProcessMesh, placements):
    if placements is None:
        return [Replicate()] * mesh.ndim
    out = list(placements)
    while len(out) < mesh.ndim:
        out.append(Replicate())
    return out


def shard_tensor(data, mesh: ProcessMesh, placements,
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Distribute a tensor over the mesh (api.py:118 parity).

    The result's ``_data`` is a global jax.Array laid out by GSPMD; Partial
    placements keep the local values (pending reduction) like the
    reference's DistTensor.
    """
    t = data if isinstance(data, Tensor) else Tensor(jnp.asarray(data))
    placements = _normalize_placements(mesh, placements)
    has_partial = any(p.is_partial() for p in placements)
    sharding = mesh.sharding_for(placements, t._data.ndim)
    if has_partial:
        # keep per-shard values; logical value = reduction over partial axes.
        # We store the local array replicated and record partial state.
        arr = jax.device_put(t._data, sharding)
    else:
        arr = jax.device_put(t._data, sharding)
    out_cls = Parameter if isinstance(t, Parameter) else Tensor
    if out_cls is Parameter:
        out = Parameter(arr, trainable=not t.stop_gradient)
    else:
        out = Tensor(arr, stop_gradient=t.stop_gradient
                     if stop_gradient is None else stop_gradient)
    out._dist_attr = (mesh, placements)
    return out


def dtensor_from_local(local_tensor, mesh: ProcessMesh, placements) -> Tensor:
    """Assemble a global dist tensor from this process's local shard
    (reference: dtensor_from_local). Multi-host path uses
    make_array_from_single_device_arrays; single-process treats the local
    tensor as the global value."""
    t = local_tensor if isinstance(local_tensor, Tensor) else Tensor(local_tensor)
    placements = _normalize_placements(mesh, placements)
    if jax.process_count() == 1:
        return shard_tensor(t, mesh, placements)
    sharding = mesh.sharding_for(placements, t._data.ndim)
    global_shape = list(t._data.shape)
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            global_shape[pl.dim] *= mesh.shape[mesh_dim]
    arr = jax.make_array_from_process_local_data(
        sharding, np.asarray(t._data), tuple(global_shape))
    out = Tensor(arr, stop_gradient=t.stop_gradient)
    out._dist_attr = (mesh, placements)
    return out


def _reduce_partial(arr, mesh: ProcessMesh, placements, target_placements):
    """Resolve Partial → concrete via a compiled psum over partial axes."""
    from jax import shard_map

    partial_axes = [mesh.dim_names[i] for i, p in enumerate(placements)
                    if p.is_partial()]
    if not partial_axes:
        return arr
    in_spec = _pspec_of(mesh, placements, arr.ndim)
    out_spec = _pspec_of(mesh, target_placements, arr.ndim)

    def body(x):
        return jax.lax.psum(x, tuple(partial_axes))

    fn = shard_map(body, mesh=mesh.jax_mesh(), in_specs=(in_spec,),
                   out_specs=out_spec)
    return jax.jit(fn)(arr)


def _pspec_of(mesh: ProcessMesh, placements, ndim) -> PartitionSpec:
    spec: List = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            d = pl.dim
            name = mesh.dim_names[mesh_dim]
            if spec[d] is None:
                spec[d] = name
            elif isinstance(spec[d], tuple):
                spec[d] += (name,)
            else:
                spec[d] = (spec[d], name)
    return PartitionSpec(*spec)


def reshard(dist_tensor: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    """Change placements (api.py:282). GSPMD emits the collective:
    s→r = all-gather, r→s = slice, s→s' = all-to-all, p→r = all-reduce,
    p→s = reduce-scatter — exactly the reference's reshard function table
    (reshard_function_registry.h) compiled instead of hand-written."""
    placements = _normalize_placements(mesh, placements)
    src_mesh, src_placements = dist_tensor._dist_attr or (mesh, None)
    arr = dist_tensor._data

    if src_placements is not None and any(
            p.is_partial() for p in src_placements):
        arr = _reduce_partial(arr, src_mesh, src_placements, placements)
        src_placements = [Replicate() if p.is_partial() else p
                          for p in src_placements]

    target = mesh.sharding_for(placements, arr.ndim)
    if any(p.is_partial() for p in placements):
        raise NotImplementedError("resharding TO Partial is not supported "
                                  "(matches reference: partial is produced "
                                  "by ops, not requested)")
    arr = jax.device_put(arr, target)
    out = Tensor(arr, stop_gradient=dist_tensor.stop_gradient)
    out._dist_attr = (mesh, placements)
    out.name = dist_tensor.name
    return out


def shard_layer(layer, process_mesh: ProcessMesh,
                shard_fn: Optional[Callable] = None,
                input_fn: Optional[Callable] = None,
                output_fn: Optional[Callable] = None):
    """Distribute a Layer's params over the mesh (api.py:381)."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for pname, p in list(sublayer._parameters.items()):
                if p is None or p._dist_attr is not None:
                    continue
                sublayer._parameters[pname] = shard_tensor(
                    p, mesh, [Replicate()] * mesh.ndim)

    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inp, out: output_fn(out, process_mesh))
    return layer


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    """Gather to a fully-replicated dense tensor (api.py unshard_dtensor)."""
    attr = dist_tensor._dist_attr
    if attr is None:
        return dist_tensor
    mesh, placements = attr
    full = reshard(dist_tensor, mesh, [Replicate()] * mesh.ndim)
    out = Tensor(full._data, stop_gradient=dist_tensor.stop_gradient)
    return out
