"""Auto-parallel static Engine — fit/evaluate/predict over a mesh.

TPU-native equivalent of the reference's auto-parallel static Engine
(reference: python/paddle/distributed/auto_parallel/static/engine.py:59 —
``Engine(model, loss, optimizer, metrics, strategy)``; fit:911,
evaluate, predict, prepare:1475). The reference pipeline is completion →
partition → reshard → parallel executor; here the same outcome comes
from GSPMD: ``prepare`` shards inputs/labels over the mesh's ``dp`` axis
(and leaves parameter shardings to shard_tensor annotations already on
the model), and the whole train step compiles to one XLA program
(jit.TrainStep) whose collectives XLA inserts from the shardings.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...core.tensor import Tensor

__all__ = ["Engine"]


class Engine:
    """reference: auto_parallel/static/engine.py:59."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics if isinstance(metrics, (list, tuple)) else (
            [metrics] if metrics is not None else [])
        self.strategy = strategy
        self._mesh = None
        self._train_step = None
        self._prepared_mode: Optional[str] = None

    # ---- mesh / sharding plumbing ----
    def _ensure_mesh(self):
        if self._mesh is not None:
            return self._mesh
        from ..auto_parallel import get_mesh

        mesh = get_mesh()
        if mesh is None:
            # default: 1-D dp mesh over all devices (reference default
            # parallelization when no annotations are given)
            import jax

            from ..auto_parallel import ProcessMesh

            n = len(jax.devices())
            mesh = ProcessMesh(np.arange(n).reshape(n), dim_names=["dp"])
        self._mesh = mesh
        return mesh

    def _dp_shard(self, t: Tensor) -> Tensor:
        from ..auto_parallel import Replicate, Shard, shard_tensor

        mesh = self._ensure_mesh()
        if "dp" not in mesh.dim_names:
            return t
        placements = [Replicate()] * mesh.ndim
        placements[mesh.dim_names.index("dp")] = Shard(0)
        return shard_tensor(t, mesh, placements)

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        """Build the compiled step for ``mode`` (reference: engine.py
        prepare:1475 — completion/partition/reshard happen here; ours is
        the TrainStep jit construction, shardings resolved by GSPMD)."""
        self._prepared_mode = mode
        if mode == "train":
            if self.model is None or self.loss is None \
                    or self.optimizer is None:
                raise ValueError("train mode needs model, loss, optimizer")
            from ...jit.train_step import TrainStep

            self._train_step = TrainStep(self.model, self._loss_fn,
                                         self.optimizer)
        return self

    def _loss_fn(self, logits, *labels):
        out = self.loss(logits, *labels)
        return out

    # ---- data plumbing ----
    def _batches(self, data, batch_size, drop_last):
        """Accepts an io.Dataset / list of (input, label) pairs / a
        DataLoader; yields (inputs, labels) Tensor lists. drop_last=True
        for training (stable shapes → one compiled step); False for
        eval/predict (every sample counts)."""
        from ...io import DataLoader, Dataset

        if isinstance(data, DataLoader):
            for batch in data:
                yield self._split_batch(batch)
            return
        if isinstance(data, Dataset) or hasattr(data, "__getitem__"):
            loader = DataLoader(data, batch_size=batch_size or 1,
                                shuffle=False, drop_last=drop_last)
            for batch in loader:
                yield self._split_batch(batch)
            return
        raise TypeError(f"unsupported data {type(data)}")

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            *ins, lab = batch
            return list(ins), [lab]
        return [batch], []

    # ---- public API (engine.py fit:911 / evaluate / predict) ----
    def fit(self, train_data, epochs: int = 1, batch_size: Optional[int]
            = None, steps_per_epoch: Optional[int] = None, verbose: int = 0,
            log_freq: int = 10):
        if self._prepared_mode != "train":
            self.prepare(mode="train")
        history = {"loss": []}
        for epoch in range(epochs):
            for step, (ins, labs) in enumerate(
                    self._batches(train_data, batch_size, drop_last=True)):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                ins = [self._dp_shard(t) for t in ins]
                labs = [self._dp_shard(t) for t in labs]
                loss = self._train_step(ins, labs)
                history["loss"].append(float(loss.numpy()))
                if verbose and step % log_freq == 0:
                    print(f"[Engine] epoch {epoch} step {step} "
                          f"loss {history['loss'][-1]:.4f}")
        return history

    def evaluate(self, valid_data, batch_size: Optional[int] = None,
                 steps: Optional[int] = None):
        from ...core import engine as grad_engine

        self.model.eval()
        losses, n = [], 0
        for m in self.metrics:
            m.reset()
        with grad_engine.no_grad():
            for step, (ins, labs) in enumerate(
                    self._batches(valid_data, batch_size, drop_last=False)):
                if steps is not None and step >= steps:
                    break
                logits = self.model(*ins)
                if self.loss is not None:
                    losses.append(float(
                        self.loss(logits, *labs).numpy()))
                for m in self.metrics:
                    m.update(m.compute(logits, *labs))
                n += 1
        self.model.train()
        out = {"loss": float(np.mean(losses)) if losses else None}
        for m in self.metrics:
            out[m.name()] = m.accumulate()
        return out

    def predict(self, test_data, batch_size: Optional[int] = None,
                steps: Optional[int] = None) -> List[np.ndarray]:
        from ...core import engine as grad_engine

        self.model.eval()
        outs = []
        with grad_engine.no_grad():
            for step, (ins, _) in enumerate(
                    self._batches(test_data, batch_size, drop_last=False)):
                if steps is not None and step >= steps:
                    break
                outs.append(self.model(*ins).numpy())
        self.model.train()
        return outs

    def save(self, path: str):
        from ...framework.io import save as fsave

        fsave(self.model.state_dict(), path + ".pdparams")
        if self.optimizer is not None:
            fsave(self.optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str):
        from ...framework.io import load as fload

        self.model.set_state_dict(fload(path + ".pdparams"))
        if self.optimizer is not None:
            import os

            if os.path.exists(path + ".pdopt"):
                self.optimizer.set_state_dict(fload(path + ".pdopt"))

    def cost(self, *a, **k):
        raise NotImplementedError(
            "cost modeling is replaced by XLA's compile-time estimates; "
            "profile a compiled step instead (paddle_tpu.profiler)")
