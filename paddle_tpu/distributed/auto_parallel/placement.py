"""Placements + ProcessMesh.

TPU-native equivalent of the reference's auto-parallel metadata
(reference: paddle/phi/core/distributed/auto_parallel/placement_types.h —
Replicate/Shard/Partial; process_mesh.h; python
distributed/auto_parallel/process_mesh.py:71). A ProcessMesh wraps
``jax.sharding.Mesh`` over the real device grid; placements translate to
``PartitionSpec`` dims, with Partial tracked as pending-reduction state
(GSPMD's partial-sum) resolved at reshard time.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["Placement", "Replicate", "Shard", "Partial", "ProcessMesh",
           "get_mesh", "set_mesh"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = int(dim)

    def get_dim(self):
        return self.dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Partial(Placement):
    """Pending reduction over the mesh dim (reference: REDUCE_TYPE sum/avg/
    max/min in placement_types.h)."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and \
            other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))


class ProcessMesh:
    """N-D logical mesh over the device grid.

    ``ProcessMesh([[0,1,2,3],[4,5,6,7]], dim_names=["dp","mp"])`` — the
    reference's semantics (process ids in an ndarray) carried onto a
    ``jax.sharding.Mesh`` whose axis names are the dim names.
    """

    def __init__(self, mesh=None, dim_names: Optional[Sequence[str]] = None,
                 shape=None, process_ids=None):
        if mesh is None and shape is not None:
            mesh = np.asarray(process_ids if process_ids is not None
                              else np.arange(int(np.prod(shape)))).reshape(shape)
        arr = np.asarray(mesh)
        self._mesh_arr = arr
        self._shape = tuple(arr.shape)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = tuple(dim_names)
        self._jax_mesh = None

    # ---- reference API ----
    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._mesh_arr.flatten().tolist()

    @property
    def mesh(self):
        return self._mesh_arr

    def get_dim_size(self, dim_name: str) -> int:
        return self._shape[self._dim_names.index(dim_name)]

    def get_rank_by_dim_and_process_id(self, dim_name, process_id):
        axis = self._dim_names.index(dim_name)
        loc = np.argwhere(self._mesh_arr == process_id)
        return int(loc[0][axis]) if len(loc) else -1

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            np.array_equal(self._mesh_arr, other._mesh_arr) and \
            self._dim_names == other._dim_names

    def __hash__(self):
        return hash((self._mesh_arr.tobytes(), self._dim_names))

    def __repr__(self):
        return (f"ProcessMesh(shape={list(self._shape)}, "
                f"dim_names={list(self._dim_names)})")

    # ---- jax bridge ----
    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devices = np.asarray(jax.devices())
            if devices.size < self._mesh_arr.size:
                raise RuntimeError(
                    f"mesh wants {self._mesh_arr.size} devices, only "
                    f"{devices.size} available")
            dev_grid = devices[self._mesh_arr.flatten()].reshape(self._shape)
            self._jax_mesh = Mesh(dev_grid, self._dim_names)
        return self._jax_mesh

    def sharding_for(self, placements: Sequence[Placement], ndim: int
                     ) -> NamedSharding:
        """placements (one per mesh dim) → NamedSharding for an ndim array."""
        spec: List = [None] * ndim
        for mesh_dim, pl in enumerate(placements):
            if isinstance(pl, Shard):
                d = pl.dim
                if spec[d] is None:
                    spec[d] = self._dim_names[mesh_dim]
                elif isinstance(spec[d], tuple):
                    spec[d] = spec[d] + (self._dim_names[mesh_dim],)
                else:
                    spec[d] = (spec[d], self._dim_names[mesh_dim])
        return NamedSharding(self.jax_mesh(), PartitionSpec(*spec))


_global_mesh: Optional[ProcessMesh] = None


def set_mesh(mesh: ProcessMesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh
