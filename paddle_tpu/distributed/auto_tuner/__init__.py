"""distributed.auto_tuner (reference: python/paddle/distributed/auto_tuner)."""
from .tuner import AutoTuner  # noqa: F401
from .search import GridSearch  # noqa: F401
from .cost_model import estimate_step_cost, estimate_memory_bytes  # noqa: F401

__all__ = ["AutoTuner", "GridSearch", "estimate_step_cost",
           "estimate_memory_bytes"]
