"""Analytic cost + memory models for parallel-config search.

TPU-native equivalent of the reference's tuner cost models (reference:
python/paddle/distributed/auto_tuner/cost_model.py,
memory_cost_model.py). The arithmetic mirrors the standard hybrid-
parallel accounting (scaling-book recipe): per-chip FLOPs from the dense
param count, comm terms for TP allreduce (2 per layer over ICI), PP
bubble fraction (p-1)/(m+p-1), DP gradient allreduce overlap.
"""
from __future__ import annotations

__all__ = ["estimate_step_cost", "estimate_memory_bytes"]

# v5e-ish constants; relative ranking is what matters for pruning
_CHIP_FLOPS = 197e12          # bf16 peak FLOP/s
_ICI_BW = 4.5e10              # bytes/s per link direction
_MFU = 0.4


def estimate_memory_bytes(cfg: dict) -> float:
    """Per-chip bytes for params+grads+optimizer states+activations under
    (dp, mp, pp, sharding) (reference: memory_cost_model.py
    get_model_memory_usage)."""
    n_params = cfg.get("n_params")
    if n_params is None:
        raise ValueError("cost model needs cfg['n_params']")
    mp = cfg.get("mp_degree", 1)
    pp = cfg.get("pp_degree", 1)
    sharding = cfg.get("sharding_degree", 1)
    micro_bs = cfg.get("micro_batch_size", 1)
    seq = cfg.get("seq_length", 2048)
    hidden = cfg.get("hidden_size", 1024)
    layers = cfg.get("num_layers", 24)

    local_params = n_params / (mp * pp)
    # bf16 params + bf16 grads (2+2) and fp32 master+moments sharded (12)
    state_bytes = local_params * (4 + 12 / sharding)
    # activation bytes per microbatch per local layer (recompute halves)
    act = micro_bs * seq * hidden * (layers / pp) * 16 / mp
    if cfg.get("recompute", True):
        act *= 0.3
    return state_bytes + act


def estimate_step_cost(cfg: dict) -> float:
    """Relative step time for one global batch (reference:
    cost_model.py). Lower is better."""
    n_params = cfg.get("n_params")
    if n_params is None:
        raise ValueError("cost model needs cfg['n_params']")
    dp = cfg.get("dp_degree", 1)
    mp = cfg.get("mp_degree", 1)
    pp = cfg.get("pp_degree", 1)
    global_bs = cfg.get("global_batch_size", 32)
    micro_bs = cfg.get("micro_batch_size", 1)
    seq = cfg.get("seq_length", 2048)
    hidden = cfg.get("hidden_size", 1024)
    layers = cfg.get("num_layers", 24)

    tokens = global_bs * seq
    flops = 6 * n_params * tokens                       # fwd+bwd
    compute_t = flops / (dp * mp * pp * _CHIP_FLOPS * _MFU)

    # TP: 2 allreduces of activations per layer per microbatch (fwd+bwd
    # doubles it) over the mp group
    micro_steps = max(global_bs // (dp * micro_bs), 1)
    act_bytes = micro_bs * seq * hidden * 2
    tp_t = 0.0
    if mp > 1:
        vol = 2 * (mp - 1) / mp * act_bytes
        tp_t = 4 * layers * micro_steps * vol / _ICI_BW

    # PP bubble: (p-1)/(m+p-1) of compute
    bubble = (pp - 1) / max(micro_steps + pp - 1, 1)
    pp_t = compute_t * bubble

    # DP gradient allreduce (overlapped: count half)
    dp_t = 0.0
    if dp > 1:
        grad_bytes = 2 * n_params / (mp * pp)
        dp_t = 0.5 * 2 * (dp - 1) / dp * grad_bytes / _ICI_BW

    return compute_t + tp_t + pp_t + dp_t
