"""Candidate generation + pruned grid search.

TPU-native equivalent of the reference's search algorithms (reference:
python/paddle/distributed/auto_tuner/search.py GridSearch;
prune.py divisibility/memory pruning; utils.py default_candidates).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from .cost_model import estimate_memory_bytes, estimate_step_cost

__all__ = ["GridSearch", "default_candidates", "prune_config"]


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def default_candidates(tuner_cfg: Dict) -> Dict[str, List[int]]:
    """Per-axis candidate lists (reference: utils.py
    default_candidates)."""
    n = int(tuner_cfg.get("num_devices", 8))

    def pick(key, default):
        v = tuner_cfg.get(key)
        return default if v is None else v  # falsy scalars are pins

    cands = {
        "dp_degree": pick("dp_degree", _divisors(n)),
        "mp_degree": pick("mp_degree", _divisors(n)),
        "pp_degree": pick("pp_degree", _divisors(n)),
        "sharding_degree": pick("sharding_degree", _divisors(n)),
        "micro_batch_size": pick("micro_batch_size", [1, 2, 4, 8]),
        "recompute": pick("recompute", [True, False]),
    }
    return {k: (v if isinstance(v, list) else [v]) for k, v in cands.items()}


def prune_config(cfg: Dict, tuner_cfg: Dict) -> Optional[str]:
    """Return a reason string if cfg is invalid/hopeless, else None
    (reference: prune.py prune_by_* registry)."""
    n = int(tuner_cfg.get("num_devices", 8))
    dp, mp, pp = cfg["dp_degree"], cfg["mp_degree"], cfg["pp_degree"]
    sh = cfg["sharding_degree"]
    if dp * mp * pp != n:
        return f"dp*mp*pp={dp * mp * pp} != num_devices={n}"
    if sh > dp:
        return f"sharding_degree={sh} > dp_degree={dp}"
    gbs = int(tuner_cfg.get("global_batch_size", 32))
    if gbs % (dp * cfg["micro_batch_size"]):
        return "global_batch_size not divisible by dp*micro_bs"
    layers = int(tuner_cfg.get("num_layers", 24))
    if layers % pp:
        return f"num_layers={layers} not divisible by pp={pp}"
    heads = int(tuner_cfg.get("num_attention_heads", 16))
    if heads % mp:
        return f"num_attention_heads={heads} not divisible by mp={mp}"
    mem_cap = float(tuner_cfg.get("memory_limit_bytes", 0))
    if mem_cap:
        full = dict(tuner_cfg)
        full.update(cfg)
        if estimate_memory_bytes(full) > mem_cap:
            return "estimated memory exceeds limit"
    return None


class GridSearch:
    """Pruned cartesian grid, cheapest analytic cost first (reference:
    search.py GridSearch.search_once)."""

    def __init__(self, tuner_cfg: Dict):
        self.tuner_cfg = tuner_cfg
        # user candidates overlay the defaults axis-by-axis, so a
        # partial dict pins some axes without dropping the rest
        cands = dict(default_candidates(tuner_cfg))
        for k, v in (tuner_cfg.get("candidates") or {}).items():
            cands[k] = v if isinstance(v, list) else [v]
        keys = list(cands)
        configs = []
        self.pruned: List[Dict] = []
        for combo in itertools.product(*(cands[k] for k in keys)):
            cfg = dict(zip(keys, combo))
            reason = prune_config(cfg, tuner_cfg)
            if reason is None:
                configs.append(cfg)
            else:
                self.pruned.append({**cfg, "pruned": reason})
        full = dict(tuner_cfg)
        configs.sort(key=lambda c: estimate_step_cost({**full, **c}))
        self._queue = configs
        self._idx = 0

    def search_once(self) -> Optional[Dict]:
        if self._idx >= len(self._queue):
            return None
        cfg = self._queue[self._idx]
        self._idx += 1
        return dict(cfg)

    @property
    def all_tasks(self) -> List[Dict]:
        return [dict(c) for c in self._queue]
