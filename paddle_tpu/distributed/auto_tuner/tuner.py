"""AutoTuner driver: propose → trial → record → best.

TPU-native equivalent of the reference's tuner (reference:
python/paddle/distributed/auto_tuner/tuner.py AutoTuner:21 — the launch
CLI runs short trials per candidate and records history; recorder.py
keeps (cfg, metric) rows and sorts). Trials here are run by a
user-supplied ``runner(cfg) -> metric`` callback (the launcher wiring the
reference has lives in its CLI layer); with no runner, candidates are
ranked by the analytic cost model alone.
"""
from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional

from .cost_model import estimate_step_cost
from .search import GridSearch

__all__ = ["AutoTuner"]


class AutoTuner:
    """reference: auto_tuner/tuner.py:21."""

    def __init__(self, tuner_cfg: Dict):
        if "n_params" not in tuner_cfg:
            raise ValueError(
                "tuner_cfg needs 'n_params' (total model parameters) — "
                "the cost/memory models rank candidates by it")
        self.tuner_cfg = dict(tuner_cfg)
        self.task_limit = int(tuner_cfg.get("task_limit", 100))
        algo = tuner_cfg.get("search_algo", {"name": "grid"})
        if isinstance(algo, dict):
            algo = algo.get("name", "grid")
        if algo != "grid":
            raise NotImplementedError(f"search_algo {algo!r}; grid only")
        self.algo = GridSearch(self.tuner_cfg)
        self.history: List[Dict] = []
        self.cur_task_id = 0

    def search_once(self) -> Optional[Dict]:
        """Next candidate config, or None when exhausted/limit reached."""
        if self.cur_task_id >= self.task_limit:
            return None
        cfg = self.algo.search_once()
        if cfg is not None:
            self.cur_task_id += 1
        return cfg

    def add_cfg(self, cfg: Dict, metric: Optional[float],
                error: Optional[str] = None) -> None:
        """Record a trial result (reference: recorder.py add_cfg);
        metric convention: higher is better (tokens/s); None = failed."""
        self.history.append({"cfg": dict(cfg), "metric": metric,
                             "error": error})

    def get_best(self) -> Optional[Dict]:
        ok = [h for h in self.history if h["metric"] is not None]
        if not ok:
            return None
        return max(ok, key=lambda h: h["metric"])

    def tune(self, runner: Optional[Callable[[Dict], float]] = None,
             max_trials: Optional[int] = None) -> Dict:
        """Drive the whole loop. ``runner(cfg)`` returns the measured
        metric (higher better) or raises on OOM/failure. Returns the best
        record. Without a runner, returns the analytically-cheapest
        candidate (cost-model-only mode)."""
        if runner is None:
            cands = self.algo.all_tasks
            if not cands:
                raise RuntimeError("no valid candidate configs")
            full = dict(self.tuner_cfg)
            best = min(cands,
                       key=lambda c: estimate_step_cost({**full, **c}))
            return {"cfg": best, "metric": None, "error": None}
        trials = 0
        while True:
            if max_trials is not None and trials >= max_trials:
                break
            cfg = self.search_once()
            if cfg is None:
                break
            trials += 1
            try:
                metric = float(runner(cfg))
                self.add_cfg(cfg, metric)
            except Exception as e:  # OOM/compile failure → recorded skip
                self.add_cfg(cfg, None, error=str(e))
        best = self.get_best()
        if best is None:
            raise RuntimeError(
                "auto-tune: every trial failed; history: "
                + json.dumps(self.history, default=str)[:2000])
        return best
