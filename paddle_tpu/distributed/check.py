"""Collective sanity checks + communication watchdog.

TPU-native equivalent of the reference's communication safety layer:
- static checks (reference: paddle/phi/core/distributed/check/
  static_check.cc — same-place/shape/dtype validation of collective
  inputs; check/nccl_dynamic_check.h — cross-rank metadata agreement
  via a broadcast before the real collective);
- hang watchdog (reference: paddle/phi/core/distributed/
  comm_task_manager.h:37 CommTaskManager + nccl_comm_task.cc — tracks
  in-flight collectives and surfaces stuck ranks on timeout).

Dynamic checks are flag-gated (`FLAGS_check_collective`, mirroring
FLAGS_enable_nccl_dynamic_check) because the metadata exchange costs a
store round-trip per collective.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..core.flags import define_flag, flag

__all__ = ["check_tensor_list", "dynamic_check", "CommWatchdog",
           "watchdog"]

define_flag("check_collective", False,
            "cross-rank shape/dtype agreement check before each "
            "multi-process collective (nccl_dynamic_check equivalent)")
# Must be BELOW the 120s store blocking-get timeout (_P2P_TIMEOUT_MS):
# the watchdog's stuck-rank report has to fire while the op is still in
# flight, before the raw coordination-service timeout kills it.
define_flag("comm_timeout_sec", 60,
            "watchdog timeout for in-flight eager collectives")


def check_tensor_list(tensor_list, tensor=None, op_name: str = "") -> None:
    """Local static checks (static_check.cc CheckShape/CheckDataType):
    every tensor in a scatter/gather list must agree in shape+dtype."""
    if not tensor_list:
        return
    datas = [t._data if hasattr(t, "_data") else t for t in tensor_list]
    shape0, dtype0 = datas[0].shape, datas[0].dtype
    for i, d in enumerate(datas[1:], 1):
        if d.shape != shape0 or d.dtype != dtype0:
            raise ValueError(
                f"{op_name}: tensor_list[{i}] has shape {d.shape}/"
                f"{d.dtype}, expected {shape0}/{dtype0} "
                "(collective inputs must agree across slots)")
    if tensor is not None:
        td = tensor._data if hasattr(tensor, "_data") else tensor
        if td.dtype != dtype0:
            raise ValueError(
                f"{op_name}: output dtype {td.dtype} != input {dtype0}")


def dynamic_check(tensor, op_name: str, group=None) -> None:
    """Cross-rank agreement check (nccl_dynamic_check.h equivalent):
    every participating process posts (shape, dtype) to the coordination
    store and verifies all match before the data-plane collective runs.
    Flag-gated; call sites are the multi-process collectives."""
    if not flag("check_collective"):
        return
    import jax

    if jax.process_count() <= 1:
        return
    from .communication.collectives import _store_gather_group
    from .communication.group import _get_default_group
    import numpy as np

    g = group or _get_default_group()
    if getattr(g, "_ranks", None) and \
            g.get_group_rank(jax.process_index()) < 0:
        return  # non-members must not join the group's store barrier
    meta = np.frombuffer(
        (str(tuple(tensor._data.shape)) + "|"
         + str(tensor._data.dtype)).encode().ljust(128), dtype=np.uint8)
    metas = _store_gather_group(meta, g)
    mine = bytes(meta).rstrip()
    for r, m in zip(g._ranks, metas):
        if bytes(m).rstrip() != mine:
            raise RuntimeError(
                f"{op_name}: rank {r} metadata "
                f"{bytes(m).rstrip().decode()} != local "
                f"{mine.decode()} — collective would corrupt data "
                "(nccl_dynamic_check parity)")


class CommWatchdog:
    """In-flight collective tracker (comm_task_manager.h:37).

    ``with watchdog.track(op, group):`` registers the op; a daemon
    thread scans for entries older than FLAGS_comm_timeout_sec and
    invokes ``on_timeout`` (default: print a stuck-rank report, once per
    offender). XLA has no stream to cancel — surfacing WHERE training is
    stuck is the actionable part (matches the reference, which also only
    surfaces + optionally aborts)."""

    def __init__(self, on_timeout: Optional[Callable] = None,
                 scan_interval: float = 5.0):
        self._inflight: Dict[int, dict] = {}
        self._lock = threading.Lock()
        self._next = 0
        self._reported: set = set()
        self._on_timeout = on_timeout or self._default_report
        self._scan_interval = scan_interval
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.timeouts: List[dict] = []  # observability for tests/tools

    def _default_report(self, entry: dict) -> None:
        import sys

        print(f"[comm watchdog] collective `{entry['op']}` in flight for "
              f"{time.time() - entry['start']:.0f}s "
              f"(group ranks {entry['ranks']}) — a peer is likely stuck "
              "or dead; check the launcher's per-rank logs",
              file=sys.stderr)

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._scan_loop,
                                            daemon=True)
            self._thread.start()

    def _scan_loop(self):
        while not self._stop.wait(self._scan_interval):
            timeout = float(flag("comm_timeout_sec"))
            now = time.time()
            with self._lock:
                entries = list(self._inflight.items())
            for token, e in entries:
                if now - e["start"] > timeout and token not in \
                        self._reported:
                    self._reported.add(token)
                    self.timeouts.append(dict(e))
                    self._on_timeout(e)

    class _Span:
        def __init__(self, wd, op, ranks):
            self._wd = wd
            self._op = op
            self._ranks = ranks
            self._token = None

        def __enter__(self):
            wd = self._wd
            with wd._lock:
                wd._next += 1
                self._token = wd._next
                wd._inflight[self._token] = {
                    "op": self._op, "ranks": self._ranks,
                    "start": time.time()}
            wd._ensure_thread()
            return self

        def __exit__(self, *exc):
            with self._wd._lock:
                self._wd._inflight.pop(self._token, None)
            return False

    def track(self, op: str, group=None) -> "_Span":
        ranks = list(getattr(group, "_ranks", []) or [])
        return self._Span(self, op, ranks)

    def stop(self):
        self._stop.set()


watchdog = CommWatchdog()
