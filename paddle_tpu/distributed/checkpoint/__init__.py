from .save_state_dict import save_state_dict  # noqa: F401
from .load_state_dict import load_state_dict  # noqa: F401

__all__ = ["save_state_dict", "load_state_dict"]
