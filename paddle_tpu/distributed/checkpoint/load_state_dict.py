"""Distributed checkpoint load with load-time resharding.

TPU-native equivalent of the reference's
``load_state_dict`` (reference:
python/paddle/distributed/checkpoint/load_state_dict.py:365): build a
read plan from the saved shard metadata, read only the slices each
device needs, and assemble them directly into the CURRENT tensor's
sharding — so a checkpoint written on mesh [8] loads onto [2,4], [4],
or a single replicated host unchanged (elastic resume across parallel
configs).

The jax twist: the per-device assembly is a
``jax.make_array_from_callback`` whose callback slices the saved shards
— each device materializes only its own piece.
"""
from __future__ import annotations

import json
import os
from typing import Dict

import numpy as np

import jax

from ...core.tensor import Tensor
from .save_state_dict import _safe

__all__ = ["load_state_dict"]


class _ShardReader:
    """Assembles arbitrary global slices from saved shard files."""

    def __init__(self, path: str, entry: dict):
        self.path = path
        self.entry = entry
        self.shape = tuple(entry["shape"])
        self.dtype = np.dtype(entry["dtype"])
        self._cache: Dict[str, np.ndarray] = {}

    def _shard(self, fn: str) -> np.ndarray:
        if fn not in self._cache:
            self._cache[fn] = np.load(os.path.join(self.path, fn))
        return self._cache[fn]

    def read(self, index) -> np.ndarray:
        """index: tuple of slices (global coords) → assembled ndarray."""
        bounds = []
        for dim, sl in enumerate(index):
            start = 0 if sl.start is None else int(sl.start)
            stop = self.shape[dim] if sl.stop is None else int(sl.stop)
            bounds.append((start, stop))
        out = np.empty([b - a for a, b in bounds], self.dtype)
        filled = np.zeros(out.shape, bool) if self.entry["shards"] else None
        for sh in self.entry["shards"]:
            s_idx = sh["index"]
            # intersection of the request with this shard
            inter = []
            ok = True
            for (ra, rb), (sa, sb) in zip(bounds, s_idx):
                a, b = max(ra, sa), min(rb, sb)
                if a >= b:
                    ok = False
                    break
                inter.append((a, b))
            if not ok:
                continue
            data = self._shard(sh["file"])
            src = tuple(slice(a - sa, b - sa) for (a, b), (sa, _sb)
                        in zip(inter, s_idx))
            dst = tuple(slice(a - ra, b - ra) for (a, b), (ra, _rb)
                        in zip(inter, bounds))
            out[dst] = data[src]
            filled[dst] = True
        if filled is not None and not filled.all():
            raise ValueError(
                f"checkpoint shards do not cover requested slice "
                f"{bounds} of shape {self.shape}")
        return out


def load_state_dict(state_dict: Dict[str, Tensor], path: str,
                    process_group=None, coordinator_rank: int = 0) -> None:
    """Fill ``state_dict``'s Tensors in place from ``path``, resharding
    each saved tensor to the target Tensor's CURRENT sharding
    (load_state_dict.py:365 parity)."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)

    missing = [n for n in state_dict if n not in meta["tensors"]]
    if missing:
        raise KeyError(
            f"checkpoint at {path!r} lacks tensors: {missing[:8]}")

    for name, target in state_dict.items():
        entry = meta["tensors"][name]
        reader = _ShardReader(path, entry)
        saved_shape = tuple(entry["shape"])
        if isinstance(target, Tensor):
            tgt_arr = target._data
            if tuple(int(s) for s in tgt_arr.shape) != saved_shape:
                raise ValueError(
                    f"{name}: saved shape {saved_shape} != target "
                    f"{tuple(tgt_arr.shape)}")
            sharding = tgt_arr.sharding
            new = jax.make_array_from_callback(
                saved_shape, sharding,
                lambda idx, r=reader: r.read(idx).astype(r.dtype))
            new = new.astype(tgt_arr.dtype)
            target._rebind(new)
        else:
            raise TypeError(f"{name}: load target must be a Tensor")
