"""Distributed checkpoint save.

TPU-native equivalent of the reference's distributed checkpoint
(reference: python/paddle/distributed/checkpoint/save_state_dict.py:104):
each rank writes the shards it owns as separate files plus one global
metadata file describing every shard's slice of the global tensor, with
replicated shards deduplicated. The jax twist: shard ownership comes
from ``jax.Array.addressable_shards`` (device-local views of the
mesh-sharded array), so the same code covers single-process multi-device
and multi-host.

Layout:
  <path>/metadata.json                 — global shapes/dtypes + shard map
  <path>/<tensor>.<i>.npy              — one file per unique shard
"""
from __future__ import annotations

import json
import os
import re
from typing import Dict

import numpy as np

import jax

from ...core.tensor import Tensor

__all__ = ["save_state_dict"]


def _safe(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", name)


def _index_of(shard, shape):
    """Normalized [(start, stop), ...] for a shard's global slice."""
    out = []
    for dim, sl in enumerate(shard.index):
        start = 0 if sl.start is None else int(sl.start)
        stop = shape[dim] if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_state_dict(state_dict: Dict[str, Tensor], path: str,
                    process_group=None, coordinator_rank: int = 0) -> None:
    """Write ``state_dict`` (possibly mesh-sharded Tensors) under
    ``path`` with per-shard files + global metadata
    (save_state_dict.py:104 parity)."""
    os.makedirs(path, exist_ok=True)
    my_rank = jax.process_index()
    meta = {"tensors": {}, "format": "paddle_tpu_dist_ckpt_v1"}

    for name, value in state_dict.items():
        arr = value._data if isinstance(value, Tensor) else jax.numpy.asarray(
            value)
        shape = tuple(int(s) for s in arr.shape)
        entry = {"shape": list(shape), "dtype": str(arr.dtype),
                 "shards": []}
        seen = set()
        fname_base = _safe(name)
        if hasattr(arr, "addressable_shards") and arr.addressable_shards:
            shards = arr.addressable_shards
        else:
            shards = None
        if shards is None:
            fn = f"{fname_base}.0.npy"
            if my_rank == coordinator_rank:
                np.save(os.path.join(path, fn), np.asarray(arr))
            entry["shards"].append({"file": fn,
                                    "index": [[0, s] for s in shape]})
        else:
            i = 0
            for sh in shards:
                idx = _index_of(sh, shape)
                key = tuple(map(tuple, idx))
                if key in seen:
                    continue  # replicated copy — dedup
                seen.add(key)
                fn = f"{fname_base}.{i}.npy"
                np.save(os.path.join(path, fn), np.asarray(sh.data))
                entry["shards"].append({"file": fn, "index": idx})
                i += 1
        meta["tensors"][name] = entry

    # multi-host: every process wrote its own (deduped) local shards; the
    # coordinator merges metadata. Single-process: just write it.
    if jax.process_count() > 1:
        from ..communication.collectives import all_gather_object

        metas = []
        all_gather_object(metas, meta)
        if my_rank == coordinator_rank:
            merged = {"tensors": {}, "format": meta["format"]}
            for m in metas:
                for n, e in m["tensors"].items():
                    cur = merged["tensors"].setdefault(
                        n, {"shape": e["shape"], "dtype": e["dtype"],
                            "shards": []})
                    known = {tuple(map(tuple, s["index"]))
                             for s in cur["shards"]}
                    for s in e["shards"]:
                        if tuple(map(tuple, s["index"])) not in known:
                            cur["shards"].append(s)
            meta = merged
    if my_rank == coordinator_rank:
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=1)
