from .collectives import (  # noqa: F401
    P2POp, ReduceOp, all_gather, all_gather_object, all_reduce, all_to_all,
    all_to_all_single, batch_isend_irecv, broadcast, broadcast_object_list,
    gather, irecv, isend, recv, reduce, reduce_scatter, scatter,
    scatter_object_list, send,
)
from .group import (  # noqa: F401
    Group, barrier, destroy_process_group, get_backend, get_group,
    is_initialized, new_group, wait,
)
