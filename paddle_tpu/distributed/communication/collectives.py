"""Imperative collective API.

TPU-native equivalent of the reference's communication ops (reference:
python/paddle/distributed/communication/{all_reduce,all_gather,...}.py over
ProcessGroupNCCL). Semantics by tensor kind:

- dist tensors (mesh-sharded ``jax.Array``): the collective is a compiled
  XLA collective (psum/all_gather/...) over the group's mesh axis — the
  SPMD path that rides ICI.
- plain tensors, world_size == 1: exact degenerate semantics (identity /
  copy), matching the reference on a single rank.
- plain tensors, multi-process: host-level collectives via
  jax.experimental.multihost_utils (DCN path).

Ordering: XLA programs are data-dependent; the Task/stream model degrades
to completed futures (SURVEY.md §5.8 "no-op-with-tokens").
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from .group import Group, _get_default_group

__all__ = ["ReduceOp", "all_reduce", "all_gather", "all_gather_object",
           "reduce", "reduce_scatter", "broadcast", "broadcast_object_list",
           "scatter", "scatter_object_list", "all_to_all",
           "all_to_all_single", "send", "recv", "isend", "irecv",
           "batch_isend_irecv", "P2POp", "gather"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_REDUCE_FNS = {
    ReduceOp.SUM: jnp.add,
    ReduceOp.MAX: jnp.maximum,
    ReduceOp.MIN: jnp.minimum,
    ReduceOp.PROD: jnp.multiply,
}


class _CompletedTask:
    """Task/Wait parity (reference process_group.h Task): XLA ordering is
    data-dependency based so every returned task is already complete."""

    def __init__(self, tensor=None):
        self._tensor = tensor

    def wait(self):
        if self._tensor is not None and hasattr(self._tensor, "_data"):
            self._tensor._data.block_until_ready()
        return True

    def is_completed(self):
        return True


def _world(group: Optional[Group]) -> int:
    g = group or _get_default_group()
    return max(g.nranks, 1)


def _is_dist(t: Tensor) -> bool:
    return isinstance(t, Tensor) and t._dist_attr is not None


def _multihost() -> bool:
    return jax.process_count() > 1


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group: Group = None,
               sync_op: bool = True):
    if _is_dist(tensor):
        from ..auto_parallel.api import reshard
        from ..auto_parallel.placement import Replicate

        mesh, placements = tensor._dist_attr
        if any(p.is_partial() for p in placements):
            out = reshard(tensor, mesh,
                          [Replicate() if p.is_partial() else p
                           for p in placements])
            tensor._rebind(out._data)
            tensor._dist_attr = out._dist_attr
        return _CompletedTask(tensor)
    if _world(group) == 1 and not _multihost():
        return _CompletedTask(tensor)
    if _multihost():
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(np.asarray(tensor._data))
        fn = {ReduceOp.SUM: np.sum, ReduceOp.MAX: np.max,
              ReduceOp.MIN: np.min, ReduceOp.PROD: np.prod,
              ReduceOp.AVG: np.mean}[op]
        tensor._rebind(jnp.asarray(fn(gathered, axis=0)))
        return _CompletedTask(tensor)
    raise RuntimeError("all_reduce: no distributed context")


def all_gather(tensor_list: List, tensor: Tensor, group: Group = None,
               sync_op: bool = True):
    n = _world(group)
    if _is_dist(tensor):
        # gather the per-rank shards along the group's axis
        from ..auto_parallel.api import unshard_dtensor

        mesh, placements = tensor._dist_attr
        full = unshard_dtensor(tensor)
        shard_dims = [p.dim for p in placements if p.is_shard()]
        if shard_dims:
            parts = jnp.split(full._data, n, axis=shard_dims[0])
            tensor_list.extend(Tensor(p) for p in parts)
        else:
            tensor_list.extend(Tensor(full._data) for _ in range(n))
        return _CompletedTask()
    if n == 1 and not _multihost():
        tensor_list.append(Tensor(tensor._data))
        return _CompletedTask()
    if _multihost():
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(np.asarray(tensor._data))
        tensor_list.extend(Tensor(jnp.asarray(g)) for g in gathered)
        return _CompletedTask()
    raise RuntimeError("all_gather: no distributed context")


def all_gather_object(object_list: List, obj, group: Group = None):
    n = _world(group)
    if n == 1 and not _multihost():
        object_list.append(obj)
        return
    if _multihost():
        import pickle

        from jax.experimental import multihost_utils

        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        # pad to a common max length via a first length exchange
        ln = multihost_utils.process_allgather(np.asarray([payload.size]))
        max_len = int(ln.max())
        padded = np.zeros(max_len, np.uint8)
        padded[: payload.size] = payload
        datas = multihost_utils.process_allgather(padded)
        for d, l in zip(datas, ln.ravel()):
            object_list.append(pickle.loads(bytes(d[: int(l)])))
        return
    raise RuntimeError("all_gather_object: no distributed context")


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM, group: Group = None,
           sync_op: bool = True):
    """Reduce to rank ``dst``; non-dst ranks keep their input unchanged
    (reference: communication/reduce.py semantics — only dst receives the
    reduced value). For mesh-sharded dist tensors the SPMD program is the
    same on every device, so reduce degenerates to all_reduce (every shard
    holds the reduced value — a superset of the dst-only guarantee)."""
    if _is_dist(tensor):
        return all_reduce(tensor, op=op, group=group, sync_op=sync_op)
    n = _world(group)
    if n == 1 and not _multihost():
        return _CompletedTask(tensor)
    if _multihost():
        before = tensor._data
        all_reduce(tensor, op=op, group=group, sync_op=sync_op)
        # dst is a group-relative rank: translate to the global process id
        g = group or _get_default_group()
        dst_global = g._ranks[dst] if getattr(g, "_ranks", None) and \
            dst < len(g._ranks) else dst
        if jax.process_index() != dst_global:
            tensor._rebind(before)
        return _CompletedTask(tensor)
    raise RuntimeError("reduce: no distributed context")


def reduce_scatter(tensor: Tensor, tensor_list: List[Tensor],
                   op=ReduceOp.SUM, group: Group = None, sync_op: bool = True):
    n = _world(group)
    if n == 1 and not _multihost():
        t = tensor_list[0]
        tensor._rebind(t._data if isinstance(t, Tensor) else jnp.asarray(t))
        return _CompletedTask(tensor)
    if _multihost():
        # reduce all, keep own slice
        reduced = Tensor(jnp.stack([t._data for t in tensor_list]))
        all_reduce(reduced, op=op, group=group)
        tensor._rebind(reduced._data[jax.process_index()])
        return _CompletedTask(tensor)
    raise RuntimeError("reduce_scatter: no distributed context")


def broadcast(tensor: Tensor, src: int = 0, group: Group = None,
              sync_op: bool = True):
    n = _world(group)
    if n == 1 and not _multihost():
        return _CompletedTask(tensor)
    if _multihost():
        from jax.experimental import multihost_utils

        val = multihost_utils.broadcast_one_to_all(
            np.asarray(tensor._data),
            is_source=jax.process_index() == src)
        tensor._rebind(jnp.asarray(val))
        return _CompletedTask(tensor)
    raise RuntimeError("broadcast: no distributed context")


def broadcast_object_list(object_list: List, src: int = 0,
                          group: Group = None):
    if _world(group) == 1 and not _multihost():
        return
    if _multihost():
        import pickle

        from jax.experimental import multihost_utils

        is_src = jax.process_index() == src
        payload = np.frombuffer(pickle.dumps(object_list), np.uint8) \
            if is_src else np.zeros(0, np.uint8)
        ln = multihost_utils.broadcast_one_to_all(
            np.asarray([payload.size]), is_source=is_src)
        buf = np.zeros(int(ln[0]), np.uint8)
        if is_src:
            buf[:] = payload
        data = multihost_utils.broadcast_one_to_all(buf, is_source=is_src)
        object_list[:] = pickle.loads(bytes(data))
        return
    raise RuntimeError("broadcast_object_list: no distributed context")


def scatter(tensor: Tensor, tensor_list: List[Tensor] = None, src: int = 0,
            group: Group = None, sync_op: bool = True):
    n = _world(group)
    if n == 1 and not _multihost():
        if tensor_list:
            tensor._rebind(tensor_list[0]._data)
        return _CompletedTask(tensor)
    if _multihost():
        from jax.experimental import multihost_utils

        stacked = np.stack([np.asarray(t._data) for t in tensor_list]) \
            if jax.process_index() == src and tensor_list else None
        shape = (n,) + tuple(tensor._data.shape)
        data = multihost_utils.broadcast_one_to_all(
            stacked if stacked is not None else np.zeros(shape,
                                                         tensor.numpy().dtype),
            is_source=jax.process_index() == src)
        tensor._rebind(jnp.asarray(data[jax.process_index()]))
        return _CompletedTask(tensor)
    raise RuntimeError("scatter: no distributed context")


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    objs = list(in_object_list or [])
    broadcast_object_list(objs, src=src, group=group)
    n = _world(group)
    if n == 1:
        out_object_list[:] = objs[:1]
    else:
        idx = jax.process_index() if _multihost() else 0
        out_object_list[:] = [objs[idx]]


def all_to_all(out_tensor_list: List, in_tensor_list: List[Tensor],
               group: Group = None, sync_op: bool = True):
    n = _world(group)
    if n == 1 and not _multihost():
        out_tensor_list.extend(Tensor(t._data) for t in in_tensor_list)
        return _CompletedTask()
    if _multihost():
        from jax.experimental import multihost_utils

        stacked = np.stack([np.asarray(t._data) for t in in_tensor_list])
        gathered = multihost_utils.process_allgather(stacked)  # [P, P, ...]
        me = jax.process_index()
        out_tensor_list.extend(
            Tensor(jnp.asarray(gathered[p][me])) for p in range(n))
        return _CompletedTask()
    raise RuntimeError("all_to_all: no distributed context")


def all_to_all_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True):
    n = _world(group)
    if n == 1 and not _multihost():
        out_tensor._rebind(in_tensor._data)
        return _CompletedTask(out_tensor)
    parts = jnp.split(in_tensor._data, n, axis=0)
    outs: List[Tensor] = []
    all_to_all(outs, [Tensor(p) for p in parts], group=group)
    out_tensor._rebind(jnp.concatenate([o._data for o in outs], axis=0))
    return _CompletedTask(out_tensor)


def send(tensor: Tensor, dst: int = 0, group: Group = None,
         sync_op: bool = True):
    if _world(group) == 1 and not _multihost():
        _P2P_BUF.setdefault(dst, []).append(jnp.asarray(tensor._data))
        return _CompletedTask(tensor)
    raise NotImplementedError(
        "eager p2p send across processes: use the compiled pipeline "
        "schedules (fleet.meta_parallel) whose ppermute rides ICI")


_P2P_BUF = {}


def recv(tensor: Tensor, src: int = 0, group: Group = None,
         sync_op: bool = True):
    if _world(group) == 1 and not _multihost():
        buf = _P2P_BUF.get(src or 0)
        if buf:
            tensor._rebind(buf.pop(0))
        return _CompletedTask(tensor)
    raise NotImplementedError(
        "eager p2p recv across processes: use the compiled pipeline "
        "schedules (fleet.meta_parallel)")


isend = send
irecv = recv


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list: List[P2POp]):
    tasks = []
    for op in p2p_op_list:
        tasks.append(op.op(op.tensor, op.peer, op.group))
    return tasks


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    gather_list = gather_list if gather_list is not None else []
    return all_gather(gather_list, tensor, group=group)
