"""Imperative collective API.

TPU-native equivalent of the reference's communication ops (reference:
python/paddle/distributed/communication/{all_reduce,all_gather,...}.py over
ProcessGroupNCCL). Semantics by tensor kind:

- dist tensors (mesh-sharded ``jax.Array``): the collective is a compiled
  XLA collective (psum/all_gather/...) over the group's mesh axis — the
  SPMD path that rides ICI.
- plain tensors, world_size == 1: exact degenerate semantics (identity /
  copy), matching the reference on a single rank.
- plain tensors, multi-process: host-level collectives via
  jax.experimental.multihost_utils (DCN path).

Ordering: XLA programs are data-dependent; the Task/stream model degrades
to completed futures (SURVEY.md §5.8 "no-op-with-tokens").
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...profiler import stats as _stats
from ..check import check_tensor_list, dynamic_check, watchdog
from .group import Group, _get_default_group

__all__ = ["ReduceOp", "all_reduce", "all_gather", "all_gather_object",
           "reduce", "reduce_scatter", "broadcast", "broadcast_object_list",
           "scatter", "scatter_object_list", "all_to_all",
           "all_to_all_single", "send", "recv", "isend", "irecv",
           "batch_isend_irecv", "P2POp", "gather"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_REDUCE_FNS = {
    ReduceOp.SUM: jnp.add,
    ReduceOp.MAX: jnp.maximum,
    ReduceOp.MIN: jnp.minimum,
    ReduceOp.PROD: jnp.multiply,
}


def _coll_stats(op_name: str, *tensors) -> None:
    """Telemetry for the primitive data movers: per-op call counters and
    local payload bytes (``dist.<op>.{calls,bytes}`` in profiler.stats)
    — the reference reports the same per-collective volume through its
    comm op stats. Counted at the public entry, whatever path (compiled
    ICI, store-brokered, degenerate single-rank) serves the call."""
    if not _stats.is_enabled():
        return
    _stats.inc(f"dist.{op_name}.calls")
    nbytes = 0
    for t in tensors:
        d = getattr(t, "_data", t)
        nbytes += int(getattr(d, "nbytes", 0) or 0)
    if nbytes:
        _stats.inc(f"dist.{op_name}.bytes", nbytes)


class _CompletedTask:
    """Task/Wait parity (reference process_group.h Task): XLA ordering is
    data-dependency based so every returned task is already complete."""

    def __init__(self, tensor=None):
        self._tensor = tensor

    def wait(self):
        if self._tensor is not None and hasattr(self._tensor, "_data"):
            self._tensor._data.block_until_ready()
        return True

    def is_completed(self):
        return True


def _world(group: Optional[Group]) -> int:
    g = group or _get_default_group()
    return max(g.nranks, 1)


def _is_dist(t: Tensor) -> bool:
    return isinstance(t, Tensor) and t._dist_attr is not None


def _multihost() -> bool:
    return jax.process_count() > 1


def _full_world(group: Optional[Group]) -> bool:
    """True only for the identity-ordered whole-world group — the
    compiled paths index src/dst by GLOBAL rank, so a permuted or subset
    group must take the group-aware store path instead."""
    g = group or _get_default_group()
    if g is None or g.nranks == 0:
        return True
    return list(g._ranks) == list(range(jax.process_count()))


_STORE_SEQ = {}


def _store_gather_group(arr, g: Group):
    """Members-only allgather through the coordination-service KV store
    (reference: TCPStore-brokered group ops). Only the group's processes
    participate — world-wide barriers would deadlock non-members. All
    keys (data + ack counter) are deleted by the last reader, so the
    store stays bounded."""
    import pickle

    host = np.asarray(arr)
    if host.nbytes > _STORE_PATH_WARN_BYTES:
        import warnings

        warnings.warn(
            f"subset-group collective is moving a {host.nbytes >> 20}MB "
            f"tensor through the coordination KV store (control-plane "
            f"path, ~100x slower than compiled ICI collectives). For "
            f"bulk traffic use full-world collectives or a mesh-axis "
            f"sharding so the exchange compiles to XLA collectives.",
            RuntimeWarning, stacklevel=3)
    client = _coord_client()
    me = jax.process_index()
    gid = g.id if g.id is not None else 0
    seq = _STORE_SEQ[gid] = _STORE_SEQ.get(gid, 0) + 1
    base = f"paddle_tpu/coll/{gid}/{seq}"
    client.key_value_set_bytes(f"{base}/{me}",
                               pickle.dumps(host, protocol=4))
    out = []
    with watchdog.track("store_allgather", g):
        for r in g._ranks:
            blob = client.blocking_key_value_get_bytes(f"{base}/{r}",
                                                       _P2P_TIMEOUT_MS)
            out.append(pickle.loads(blob))
    # ack barrier: the member whose increment completes the count cleans
    # up (everyone has read every data key before acking)
    done = client.key_value_increment(f"{base}/ack", 1)
    if done == g.nranks:
        for r in g._ranks:
            client.key_value_delete(f"{base}/{r}")
        client.key_value_delete(f"{base}/ack")
    return out


def _store_broadcast(arr, g: Group, src_group_rank: int):
    """One-to-group broadcast through the store: only src uploads; the
    others block on that single key (no n-fold gather). Cleanup via the
    same ack-counter pattern as _store_gather_group."""
    import pickle

    client = _coord_client()
    gid = g.id if g.id is not None else 0
    seq_key = ("bcast", gid)
    seq = _STORE_SEQ[seq_key] = _STORE_SEQ.get(seq_key, 0) + 1
    base = f"paddle_tpu/bcast/{gid}/{seq}"
    me_gr = g.get_group_rank(jax.process_index())
    if me_gr == src_group_rank:
        client.key_value_set_bytes(base,
                                   pickle.dumps(np.asarray(arr),
                                                protocol=4))
    with watchdog.track("store_broadcast", g):
        blob = client.blocking_key_value_get_bytes(base, _P2P_TIMEOUT_MS)
    val = pickle.loads(blob)
    done = client.key_value_increment(f"{base}/ack", 1)
    if done == g.nranks:
        client.key_value_delete(base)
        client.key_value_delete(f"{base}/ack")
    return val


def _my_group_rank(g: Optional[Group]) -> int:
    """Group rank of this process, -1 for non-members (non-members must
    no-op: they neither post store keys nor join ack barriers)."""
    g = g or _get_default_group()
    if g is None or not getattr(g, "_ranks", None):
        return jax.process_index()
    return g.get_group_rank(jax.process_index())


# ---- compiled cross-process data plane --------------------------------
# One device per process forms a global 1-D mesh; collectives are jitted
# XLA programs over it, so multi-host traffic rides ICI/DCN through the
# runtime instead of numpy host gathers (reference: the NCCL data plane
# under ProcessGroupNCCL; SURVEY §5.8 TPU-equivalent mapping). The mesh
# and jitted programs are built once per (op, world) and cached —
# all_reduce is the per-step gradient hot path, so every call after the
# first must hit jit's function-identity cache.

_COLL_CACHE: dict = {}

_REDUCERS = None


def _reducers():
    global _REDUCERS
    if _REDUCERS is None:
        _REDUCERS = {ReduceOp.SUM: jnp.sum, ReduceOp.MAX: jnp.max,
                     ReduceOp.MIN: jnp.min, ReduceOp.PROD: jnp.prod,
                     ReduceOp.AVG: jnp.mean}
    return _REDUCERS


def _cached(key, builder):
    ck = (key, jax.process_count())
    if ck not in _COLL_CACHE:
        _COLL_CACHE[ck] = builder()
    return _COLL_CACHE[ck]


def _proc_mesh():
    def build():
        devs = [next(d for d in jax.devices() if d.process_index == p)
                for p in range(jax.process_count())]
        return jax.sharding.Mesh(np.array(devs), ("p",))

    return _cached("mesh", build)


def _my_mesh_device(mesh):
    return next(d for d in mesh.devices.flat
                if d.process_index == jax.process_index())


def _global_stack(local, mesh):
    """Each process contributes its local value as one slice of a global
    [P, ...] array sharded along the process axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = jax.process_count()
    sharding = NamedSharding(mesh, P("p"))
    shard = jax.device_put(local[None], _my_mesh_device(mesh))
    return jax.make_array_from_single_device_arrays(
        (n,) + tuple(local.shape), sharding, [shard])


def _local_value(garr):
    return jnp.asarray(garr.addressable_shards[0].data)


def _compiled_allreduce(local, op):
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _proc_mesh()
    red = _reducers()[op]
    fn = _cached(("allreduce", op), lambda: jax.jit(
        lambda x: red(x, axis=0),
        out_shardings=NamedSharding(mesh, P())))
    return _local_value(fn(_global_stack(local, mesh)))


def _compiled_allgather(local):
    """Returns the [P, ...] stack, fully replicated on every process."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _proc_mesh()
    fn = _cached("allgather", lambda: jax.jit(
        lambda x: x, out_shardings=NamedSharding(mesh, P())))
    return _local_value(fn(_global_stack(local, mesh)))


def _compiled_broadcast(local, src):
    """One-to-all: only the src shard travels (XLA lowers the sharded
    x[src] + replicated output to a broadcast from src's device, not a
    P-fold allgather)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _proc_mesh()
    fn = _cached(("broadcast", src), lambda: jax.jit(
        lambda x: x[src], out_shardings=NamedSharding(mesh, P())))
    return _local_value(fn(_global_stack(local, mesh)))


def _compiled_reducescatter(stacked, op):
    """stacked: local [P, ...] contributions; returns this process's
    reduced slice (XLA reduce-scatter over the process mesh)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _proc_mesh()
    n = jax.process_count()
    shard = jax.device_put(stacked[None], _my_mesh_device(mesh))
    garr = jax.make_array_from_single_device_arrays(
        (n,) + tuple(stacked.shape),
        jax.sharding.NamedSharding(mesh, P("p")), [shard])
    red = _reducers()[op]
    fn = _cached(("reducescatter", op), lambda: jax.jit(
        lambda x: red(x, axis=0),
        out_shardings=NamedSharding(mesh, P("p"))))
    return _local_value(fn(garr))[0]


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group: Group = None,
               sync_op: bool = True):
    _coll_stats("all_reduce", tensor)
    if _is_dist(tensor):
        from ..auto_parallel.api import reshard
        from ..auto_parallel.placement import Replicate

        mesh, placements = tensor._dist_attr
        if any(p.is_partial() for p in placements):
            out = reshard(tensor, mesh,
                          [Replicate() if p.is_partial() else p
                           for p in placements])
            tensor._rebind(out._data)
            tensor._dist_attr = out._dist_attr
        return _CompletedTask(tensor)
    if _world(group) == 1 and not _multihost():
        return _CompletedTask(tensor)
    if _multihost():
        dynamic_check(tensor, "all_reduce", group)
        if _full_world(group):
            tensor._rebind(_compiled_allreduce(tensor._data, op))
            return _CompletedTask(tensor)
        # subset/permuted group: members-only store-brokered path
        g = group or _get_default_group()
        if _my_group_rank(g) < 0:
            return _CompletedTask(tensor)  # non-member no-op
        parts = _store_gather_group(tensor._data, g)
        fn = {ReduceOp.SUM: np.sum, ReduceOp.MAX: np.max,
              ReduceOp.MIN: np.min, ReduceOp.PROD: np.prod,
              ReduceOp.AVG: np.mean}[op]
        tensor._rebind(jnp.asarray(fn(np.stack(parts), axis=0)))
        return _CompletedTask(tensor)
    raise RuntimeError("all_reduce: no distributed context")


def all_gather(tensor_list: List, tensor: Tensor, group: Group = None,
               sync_op: bool = True):
    _coll_stats("all_gather", tensor)
    n = _world(group)
    if _is_dist(tensor):
        # gather the per-rank shards along the group's axis
        from ..auto_parallel.api import unshard_dtensor

        mesh, placements = tensor._dist_attr
        full = unshard_dtensor(tensor)
        shard_dims = [p.dim for p in placements if p.is_shard()]
        if shard_dims:
            parts = jnp.split(full._data, n, axis=shard_dims[0])
            tensor_list.extend(Tensor(p) for p in parts)
        else:
            tensor_list.extend(Tensor(full._data) for _ in range(n))
        return _CompletedTask()
    if n == 1 and not _multihost():
        tensor_list.append(Tensor(tensor._data))
        return _CompletedTask()
    if _multihost():
        dynamic_check(tensor, "all_gather", group)
        if _full_world(group):
            stack = _compiled_allgather(tensor._data)
            tensor_list.extend(Tensor(stack[i])
                               for i in range(stack.shape[0]))
            return _CompletedTask()
        # subset/permuted group: members-only store-brokered path
        g = group or _get_default_group()
        if _my_group_rank(g) < 0:
            return _CompletedTask()  # non-member no-op
        parts = _store_gather_group(tensor._data, g)
        tensor_list.extend(Tensor(jnp.asarray(p)) for p in parts)
        return _CompletedTask()
    raise RuntimeError("all_gather: no distributed context")


def all_gather_object(object_list: List, obj, group: Group = None):
    n = _world(group)
    if n == 1 and not _multihost():
        object_list.append(obj)
        return
    if _multihost():
        import pickle

        from jax.experimental import multihost_utils

        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        # pad to a common max length via a first length exchange
        ln = multihost_utils.process_allgather(np.asarray([payload.size]))
        max_len = int(ln.max())
        padded = np.zeros(max_len, np.uint8)
        padded[: payload.size] = payload
        datas = multihost_utils.process_allgather(padded)
        for d, l in zip(datas, ln.ravel()):
            object_list.append(pickle.loads(bytes(d[: int(l)])))
        return
    raise RuntimeError("all_gather_object: no distributed context")


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM, group: Group = None,
           sync_op: bool = True):
    """Reduce to rank ``dst``; non-dst ranks keep their input unchanged
    (reference: communication/reduce.py semantics — only dst receives the
    reduced value). For mesh-sharded dist tensors the SPMD program is the
    same on every device, so reduce degenerates to all_reduce (every shard
    holds the reduced value — a superset of the dst-only guarantee)."""
    if _is_dist(tensor):
        return all_reduce(tensor, op=op, group=group, sync_op=sync_op)
    n = _world(group)
    if n == 1 and not _multihost():
        return _CompletedTask(tensor)
    if _multihost():
        before = tensor._data
        all_reduce(tensor, op=op, group=group, sync_op=sync_op)
        # dst is a group-relative rank: translate to the global process id
        g = group or _get_default_group()
        dst_global = g._ranks[dst] if getattr(g, "_ranks", None) and \
            dst < len(g._ranks) else dst
        if jax.process_index() != dst_global:
            tensor._rebind(before)
        return _CompletedTask(tensor)
    raise RuntimeError("reduce: no distributed context")


def reduce_scatter(tensor: Tensor, tensor_list: List[Tensor],
                   op=ReduceOp.SUM, group: Group = None, sync_op: bool = True):
    _coll_stats("reduce_scatter", *tensor_list)
    check_tensor_list(tensor_list, tensor, "reduce_scatter")
    n = _world(group)
    if n == 1 and not _multihost():
        t = tensor_list[0]
        tensor._rebind(t._data if isinstance(t, Tensor) else jnp.asarray(t))
        return _CompletedTask(tensor)
    if _multihost():
        stacked = jnp.stack([t._data for t in tensor_list])
        if _full_world(group):
            tensor._rebind(_compiled_reducescatter(stacked, op))
            return _CompletedTask(tensor)
        # subset/permuted group: reduce within the group, keep own
        # group-rank slice (stacked has nranks chunks by group rank)
        g = group or _get_default_group()
        if _my_group_rank(g) < 0:
            return _CompletedTask(tensor)  # non-member no-op
        parts = _store_gather_group(stacked, g)
        red = {ReduceOp.SUM: np.sum, ReduceOp.MAX: np.max,
               ReduceOp.MIN: np.min, ReduceOp.PROD: np.prod,
               ReduceOp.AVG: np.mean}[op](np.stack(parts), axis=0)
        my_gr = g.get_group_rank(jax.process_index())
        if my_gr >= 0:
            tensor._rebind(jnp.asarray(red[my_gr]))
        return _CompletedTask(tensor)
    raise RuntimeError("reduce_scatter: no distributed context")


def broadcast(tensor: Tensor, src: int = 0, group: Group = None,
              sync_op: bool = True):
    _coll_stats("broadcast", tensor)
    n = _world(group)
    if n == 1 and not _multihost():
        return _CompletedTask(tensor)
    if _multihost():
        dynamic_check(tensor, "broadcast", group)
        if _full_world(group):
            tensor._rebind(_compiled_broadcast(tensor._data, src))
            return _CompletedTask(tensor)
        # subset/permuted group: translate global src to group rank
        # (matches the compiled path's global-rank convention)
        g = group or _get_default_group()
        if _my_group_rank(g) < 0:
            return _CompletedTask(tensor)  # non-member no-op
        src_gr = g.get_group_rank(src)
        if src_gr < 0:
            raise ValueError(f"broadcast src={src} is not in group "
                             f"{g._ranks}")
        tensor._rebind(jnp.asarray(
            _store_broadcast(tensor._data, g, src_gr)))
        return _CompletedTask(tensor)
    raise RuntimeError("broadcast: no distributed context")


def broadcast_object_list(object_list: List, src: int = 0,
                          group: Group = None):
    if _world(group) == 1 and not _multihost():
        return
    if _multihost():
        import pickle

        from jax.experimental import multihost_utils

        is_src = jax.process_index() == src
        payload = np.frombuffer(pickle.dumps(object_list), np.uint8) \
            if is_src else np.zeros(0, np.uint8)
        ln = multihost_utils.broadcast_one_to_all(
            np.asarray([payload.size]), is_source=is_src)
        buf = np.zeros(int(ln[0]), np.uint8)
        if is_src:
            buf[:] = payload
        data = multihost_utils.broadcast_one_to_all(buf, is_source=is_src)
        object_list[:] = pickle.loads(bytes(data))
        return
    raise RuntimeError("broadcast_object_list: no distributed context")


def scatter(tensor: Tensor, tensor_list: List[Tensor] = None, src: int = 0,
            group: Group = None, sync_op: bool = True):
    if tensor_list:
        check_tensor_list(tensor_list, tensor, "scatter")
    n = _world(group)
    if n == 1 and not _multihost():
        if tensor_list:
            tensor._rebind(tensor_list[0]._data)
        return _CompletedTask(tensor)
    if _multihost():
        from jax.experimental import multihost_utils

        stacked = np.stack([np.asarray(t._data) for t in tensor_list]) \
            if jax.process_index() == src and tensor_list else None
        shape = (n,) + tuple(tensor._data.shape)
        data = multihost_utils.broadcast_one_to_all(
            stacked if stacked is not None else np.zeros(shape,
                                                         tensor.numpy().dtype),
            is_source=jax.process_index() == src)
        tensor._rebind(jnp.asarray(data[jax.process_index()]))
        return _CompletedTask(tensor)
    raise RuntimeError("scatter: no distributed context")


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    objs = list(in_object_list or [])
    broadcast_object_list(objs, src=src, group=group)
    n = _world(group)
    if n == 1:
        out_object_list[:] = objs[:1]
    else:
        idx = jax.process_index() if _multihost() else 0
        out_object_list[:] = [objs[idx]]


def all_to_all(out_tensor_list: List, in_tensor_list: List[Tensor],
               group: Group = None, sync_op: bool = True):
    _coll_stats("all_to_all", *in_tensor_list)
    check_tensor_list(in_tensor_list, None, "all_to_all")
    n = _world(group)
    if n == 1 and not _multihost():
        out_tensor_list.extend(Tensor(t._data) for t in in_tensor_list)
        return _CompletedTask()
    if _multihost():
        stacked = jnp.stack([t._data for t in in_tensor_list])
        if _full_world(group):
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = _proc_mesh()
            shard = jax.device_put(stacked[None], _my_mesh_device(mesh))
            garr = jax.make_array_from_single_device_arrays(
                (n,) + tuple(stacked.shape),
                NamedSharding(mesh, P("p")), [shard])
            # [src, dst, ...] -> [dst, src, ...]; my row = my inbox
            out = jax.jit(lambda x: jnp.swapaxes(x, 0, 1),
                          out_shardings=NamedSharding(mesh, P("p")))(garr)
            inbox = _local_value(out)[0]
            out_tensor_list.extend(Tensor(inbox[p]) for p in range(n))
            return _CompletedTask()
        # subset/permuted group: rows/columns indexed by GROUP rank
        g = group or _get_default_group()
        if _my_group_rank(g) < 0:
            return _CompletedTask()  # non-member no-op
        parts = _store_gather_group(stacked, g)
        my_gr = g.get_group_rank(jax.process_index())
        if my_gr >= 0:
            out_tensor_list.extend(
                Tensor(jnp.asarray(p[my_gr])) for p in parts)
        return _CompletedTask()
    raise RuntimeError("all_to_all: no distributed context")


# warn when eager subset-group collectives move bulk data through the
# coordination KV (control-plane path; fine for metadata, wrong for
# gradient traffic — VERDICT r2 weak #7)
_STORE_PATH_WARN_BYTES = 1 << 20

_A2A_UNEVEN_SEQ = {}


def all_to_all_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True):
    """(reference: communication/all_to_all.py ``alltoall_single`` —
    honors uneven ``in_split_sizes``/``out_split_sizes``). The even path
    is the compiled exchange; uneven splits move piecewise through the
    coordination KV (sizes differ per (src,dst) pair, so there is no
    uniform-shape program; uneven a2a is a control-plane-scale op —
    MoE capacity exchange — in the reference too)."""
    _coll_stats("all_to_all_single", in_tensor)
    n = _world(group)
    uneven = out_split_sizes is not None or in_split_sizes is not None
    if not uneven:
        if n == 1 and not _multihost():
            out_tensor._rebind(in_tensor._data)
            return _CompletedTask(out_tensor)
        parts = jnp.split(in_tensor._data, n, axis=0)
        outs: List[Tensor] = []
        all_to_all(outs, [Tensor(p) for p in parts], group=group)
        out_tensor._rebind(jnp.concatenate([o._data for o in outs],
                                           axis=0))
        return _CompletedTask(out_tensor)

    in_sp = list(in_split_sizes) if in_split_sizes is not None else \
        [in_tensor.shape[0] // n] * n
    out_sp = list(out_split_sizes) if out_split_sizes is not None else None
    if len(in_sp) != n:
        raise ValueError(
            f"in_split_sizes must have world_size ({n}) entries, "
            f"got {len(in_sp)}")
    if out_sp is not None and len(out_sp) != n:
        raise ValueError(
            f"out_split_sizes must have world_size ({n}) entries, "
            f"got {len(out_sp)}")
    if sum(in_sp) != int(in_tensor.shape[0]):
        raise ValueError(
            f"in_split_sizes sum {sum(in_sp)} != input rows "
            f"{int(in_tensor.shape[0])}")
    if n == 1 and not _multihost():
        out_tensor._rebind(in_tensor._data)
        return _CompletedTask(out_tensor)

    import pickle

    g = group or _get_default_group()
    ranks = list(getattr(g, "ranks", range(n))) or list(range(n))
    me = jax.process_index()
    if me not in ranks:
        return _CompletedTask(out_tensor)
    my_gr = ranks.index(me)
    # per-group key namespace + per-group sequence: concurrent disjoint
    # groups (e.g. two EP groups) must not collide in the shared KV
    gid = g.id if getattr(g, "id", None) is not None else 0
    _A2A_UNEVEN_SEQ[gid] = _A2A_UNEVEN_SEQ.get(gid, 0) + 1
    seq = _A2A_UNEVEN_SEQ[gid]
    client = _coord_client()
    offs = np.cumsum([0] + in_sp)
    data = np.asarray(in_tensor._data)
    for j in range(n):
        piece = data[offs[j]: offs[j + 1]]
        client.key_value_set_bytes(
            f"paddle_tpu/a2a_uneven/{gid}/{seq}/{my_gr}->{j}",
            pickle.dumps(piece, protocol=4))
    pieces = []
    for j in range(n):
        key = f"paddle_tpu/a2a_uneven/{gid}/{seq}/{j}->{my_gr}"
        with watchdog.track("all_to_all_single(uneven)", group):
            blob = client.blocking_key_value_get_bytes(
                key, _P2P_TIMEOUT_MS)
        client.key_value_delete(key)
        piece = pickle.loads(blob)
        if out_sp is not None and piece.shape[0] != out_sp[j]:
            raise ValueError(
                f"rank {j} sent {piece.shape[0]} rows, out_split_sizes "
                f"expected {out_sp[j]}")
        pieces.append(piece)
    out_tensor._rebind(jnp.asarray(np.concatenate(pieces, axis=0)))
    return _CompletedTask(out_tensor)


_P2P_BUF = {}
_P2P_SEQ = {}
_P2P_TIMEOUT_MS = 120_000


def _coord_client():
    """The JAX coordination-service KV client — the control-plane
    TCPStore equivalent (reference: phi/core/distributed/store/
    tcp_store.h:121). Eager cross-process p2p is brokered through it;
    the data-plane p2p (pipeline stage handoff) is the compiled
    ppermute in fleet.meta_parallel, which rides ICI."""
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "p2p across processes needs jax.distributed to be "
            "initialized (call init_parallel_env first)")
    return client


def _p2p_seq(a: int, b: int) -> int:
    key = (a, b)
    _P2P_SEQ[key] = _P2P_SEQ.get(key, 0) + 1
    return _P2P_SEQ[key]


def send(tensor: Tensor, dst: int = 0, group: Group = None,
         sync_op: bool = True):
    """Point-to-point send (reference: communication/send.py over
    ProcessGroup::Send). Cross-process path serializes through the
    coordination service — matched send/recv pairs use a per-(src,dst)
    sequence number so repeated transfers don't collide."""
    _coll_stats("send", tensor)
    if _world(group) == 1 and not _multihost():
        _P2P_BUF.setdefault(dst, []).append(jnp.asarray(tensor._data))
        return _CompletedTask(tensor)
    import pickle

    me = jax.process_index()
    seq = _p2p_seq(me, dst)
    payload = pickle.dumps(np.asarray(tensor._data), protocol=4)
    _coord_client().key_value_set_bytes(
        f"paddle_tpu/p2p/{me}->{dst}/{seq}", payload)
    return _CompletedTask(tensor)


def recv(tensor: Tensor, src: int = 0, group: Group = None,
         sync_op: bool = True):
    """Point-to-point recv matching ``send`` (reference:
    communication/recv.py). Blocks up to 120s for the matching key."""
    _coll_stats("recv", tensor)
    if _world(group) == 1 and not _multihost():
        buf = _P2P_BUF.get(src or 0)
        if buf:
            tensor._rebind(buf.pop(0))
        return _CompletedTask(tensor)
    import pickle

    me = jax.process_index()
    seq = _p2p_seq(src, me)
    client = _coord_client()
    key = f"paddle_tpu/p2p/{src}->{me}/{seq}"
    with watchdog.track(f"recv(src={src})", group):
        blob = client.blocking_key_value_get_bytes(key, _P2P_TIMEOUT_MS)
    client.key_value_delete(key)  # keep the coordinator store bounded
    tensor._rebind(jnp.asarray(pickle.loads(blob)))
    return _CompletedTask(tensor)


isend = send
irecv = recv


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list: List[P2POp]):
    tasks = []
    for op in p2p_op_list:
        tasks.append(op.op(op.tensor, op.peer, op.group))
    return tasks


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """(reference: communication/gather.py — only ``dst`` receives the
    gathered list; other ranks' gather_list stays untouched)."""
    gather_list = gather_list if gather_list is not None else []
    tmp: List[Tensor] = []
    task = all_gather(tmp, tensor, group=group)
    me = jax.process_index() if _multihost() else 0
    if me == dst:
        gather_list.extend(tmp)
    return task
