"""Communication groups (reference:
python/paddle/distributed/communication/group.py — Group registry,
new_group). A Group is a named set of ranks; on TPU it corresponds to a
mesh axis (collectives over a group compile to ICI collectives along that
axis) rather than an NCCL communicator.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..env import get_rank, get_world_size

__all__ = ["Group", "new_group", "get_group", "destroy_process_group",
           "is_initialized", "_get_default_group", "_set_default_group",
           "wait", "barrier", "get_backend"]

_group_map: Dict[int, "Group"] = {}
_next_group_id = [0]
_default_group: Optional["Group"] = None


class Group:
    def __init__(self, rank_in_group: int, gid: int, ranks: List[int],
                 name: str = None, mesh_axis=None):
        self._rank = rank_in_group
        self._id = gid
        self._ranks = list(ranks)
        self._name = name or f"group_{gid}"
        # (ProcessMesh, axis_name) when this group maps onto a mesh axis
        self.mesh_axis = mesh_axis

    @property
    def rank(self):
        return self._rank

    @property
    def id(self):
        return self._id

    @property
    def ranks(self):
        return self._ranks

    @property
    def nranks(self):
        return len(self._ranks)

    world_size = nranks

    @property
    def name(self):
        return self._name

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank):
        return self._ranks.index(rank) if rank in self._ranks else -1

    def is_member(self):
        return get_rank() in self._ranks or self._rank >= 0

    def __repr__(self):
        return f"Group(id={self._id}, ranks={self._ranks})"


def _set_default_group(group: Group):
    global _default_group
    _default_group = group
    _group_map[0] = group


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        world = max(get_world_size(), 1)
        _default_group = Group(get_rank(), 0, list(range(world)), "default")
        _group_map[0] = _default_group
    return _default_group


def new_group(ranks: Optional[List[int]] = None, backend=None,
              timeout=None) -> Group:
    _next_group_id[0] += 1
    gid = _next_group_id[0]
    if ranks is None:
        ranks = list(range(max(get_world_size(), 1)))
    my = get_rank()
    rank_in_group = ranks.index(my) if my in ranks else -1
    g = Group(rank_in_group, gid, ranks)
    _group_map[gid] = g
    return g


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _get_default_group()
    return _group_map[gid]


def is_initialized() -> bool:
    from ..env import is_initialized as env_init

    return env_init() or _default_group is not None


def destroy_process_group(group: Optional[Group] = None):
    global _default_group
    if group is None:
        _group_map.clear()
        _default_group = None
    else:
        _group_map.pop(group.id, None)


def get_backend(group=None) -> str:
    return "xla"


def wait(tensor, group=None, use_calc_stream=True):
    """Stream-sync parity: XLA ordering is data-dependency based, so wait ≈
    block_until_ready (reference: communication/wait — stream event)."""
    if hasattr(tensor, "_data"):
        tensor._data.block_until_ready()
    return tensor


def barrier(group=None):
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("paddle_tpu_barrier")
