"""Distributed environment / rank bookkeeping.

TPU-native equivalent of the reference's env plumbing (reference:
python/paddle/distributed/parallel.py — ``ParallelEnv`` reads
``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM`` set by the launcher).
Under JAX multi-host, process_index/process_count are authoritative once
``jax.distributed`` is initialized; env vars seed the pre-init view.
"""
from __future__ import annotations

import os

__all__ = ["ParallelEnv", "get_rank", "get_world_size"]

_initialized = False


def _mark_initialized():
    global _initialized
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def get_rank(group=None) -> int:
    if group is not None:
        return group.rank
    try:
        import jax

        if _initialized:
            return jax.process_index()
    except Exception:
        pass
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def get_world_size(group=None) -> int:
    if group is not None:
        return group.world_size
    try:
        import jax

        if _initialized:
            return jax.process_count()
    except Exception:
        pass
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


class ParallelEnv:
    """reference: parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return int(os.environ.get("FLAGS_selected_tpus",
                                  os.environ.get("FLAGS_selected_gpus", "0")))

    @property
    def current_endpoint(self):
        eps = self.trainer_endpoints
        r = self.rank
        return eps[r] if r < len(eps) else ""

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []

    @property
    def nrings(self):
        return 1
