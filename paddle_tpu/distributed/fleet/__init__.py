"""paddle_tpu.distributed.fleet — mirrors python/paddle/distributed/fleet."""
from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from .fleet import (  # noqa: F401
    Fleet, distributed_model, distributed_optimizer, fleet,
    get_hybrid_communicate_group, init, is_first_worker, worker_index,
    worker_num,
)
from . import meta_parallel  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401
from . import utils  # noqa: F401
