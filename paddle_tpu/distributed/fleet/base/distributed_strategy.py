"""DistributedStrategy (reference:
python/paddle/distributed/fleet/base/distributed_strategy.py:175 over
protobuf distributed_strategy.proto:359). Plain-python config object with
the same field surface; hybrid_configs drives the topology."""
from __future__ import annotations

__all__ = ["DistributedStrategy"]


class _Bunch(dict):
    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel degrees (reference hybrid_configs)
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
            "mp_configs": _Bunch(),
            "pp_configs": _Bunch(
                micro_batch_size=1, accumulate_steps=1,
                schedule_mode="1F1B"),
        }
        # feature toggles (subset of distributed_strategy.proto)
        self.amp = False
        self.amp_configs = _Bunch(
            init_loss_scaling=32768.0, use_pure_fp16=False,
            custom_white_list=[], custom_black_list=[], use_bf16=True)
        self.recompute = False
        self.recompute_configs = _Bunch(checkpoints=[])
        self.sharding = False
        self.sharding_configs = _Bunch(stage=1, degree=8)
        self.gradient_merge = False
        self.gradient_merge_configs = _Bunch(k_steps=1, avg=True)
        self.pipeline = False
        self.pipeline_configs = _Bunch(accumulate_steps=1,
                                       micro_batch_size=1)
        self.tensor_parallel = False
        self.tensor_parallel_configs = _Bunch(tensor_parallel_degree=1)
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.find_unused_parameters = False
        self.heter_ccl_mode = False
        self.without_graph_optimization = True

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items()
                  if not k.startswith("_")}
        return f"DistributedStrategy({fields})"
