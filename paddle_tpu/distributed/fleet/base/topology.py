"""Hybrid parallel topology.

TPU-native equivalent of the reference's topology
(reference: python/paddle/distributed/fleet/base/topology.py —
CommunicateTopology:61, HybridCommunicateGroup:174; 5-D cartesian axis
order pp→mp→sep→sharding→dp, topology.py:299). Here the topology IS a
ProcessMesh: each axis becomes a named mesh dim, groups map onto mesh
axes, and collectives along a group compile to ICI collectives on that
axis.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...auto_parallel.placement import ProcessMesh
from ...communication.group import Group, new_group
from ...env import get_rank, get_world_size

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

_HYBRID_PARALLEL_ORDER = ["pp", "mp", "sep", "sharding", "dp"]


class CommunicateTopology:
    """Cartesian rank topology (topology.py:61)."""

    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names or
                                    _HYBRID_PARALLEL_ORDER)
        self._dims = list(dims or [1] * len(self._parallel_names))
        self.coordinate = list(itertools.product(
            *[range(d) for d in self._dims]))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **args):
        coord = tuple(args[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [self._coord2rank[c] for c in self.coordinate
                if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All groups along `axis_name`: list of rank lists."""
        axis = self._parallel_names.index(axis_name)
        other_axes = [i for i in range(len(self._dims)) if i != axis]
        groups = []
        for other in itertools.product(
                *[range(self._dims[i]) for i in other_axes]):
            ranks = []
            for v in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, v)
                ranks.append(self._coord2rank[tuple(coord)])
            groups.append(ranks)
        return groups

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord2rank[tuple(coord)]


class HybridCommunicateGroup:
    """Per-axis comm groups + the global ProcessMesh (topology.py:174).

    The TPU twist: build ONE ProcessMesh with axes in hybrid order; each
    axis group is (mesh, axis_name) so sharded ops compile to the right
    collective.
    """

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = get_rank()
        self.nranks = topology.world_size()

        self._dp_degree = topology.get_dim("dp")
        self._mp_degree = topology.get_dim("mp")
        self._pp_degree = topology.get_dim("pp")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") \
            if "sep" in topology.get_hybrid_group_names() else 1

        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(n) for n in names]
        self._mesh = ProcessMesh(
            np.arange(int(np.prod(dims))).reshape(dims), dim_names=names)

        # per-axis groups containing this rank
        self._groups: Dict[str, Group] = {}
        for name in names:
            ranks_lists = topology.get_comm_list(name)
            my = self.global_rank if self.global_rank < self.nranks else 0
            for ranks in ranks_lists:
                if my in ranks:
                    g = new_group(ranks)
                    g.mesh_axis = (self._mesh, name)
                    g._name = f"{name}_group"
                    self._groups[name] = g
                    break

    # ---- mesh access (TPU-native) ----
    @property
    def mesh(self) -> ProcessMesh:
        return self._mesh

    def axis_name(self, parallel: str) -> str:
        return parallel

    # ---- reference API ----
    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._mp_degree > 1:
            return "model"
        if self._sharding_degree > 1:
            return "sharding"
        return "data"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # dp
    def get_data_parallel_rank(self):
        return self._coord("dp")

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._groups.get("dp")

    def get_data_parallel_group_src_rank(self):
        g = self._groups.get("dp")
        return g.ranks[0] if g else 0

    # mp
    def get_model_parallel_rank(self):
        return self._coord("mp")

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._groups.get("mp")

    def get_model_parallel_group_src_rank(self):
        g = self._groups.get("mp")
        return g.ranks[0] if g else 0

    # pp
    def get_stage_id(self):
        return self._coord("pp")

    def get_pipe_parallel_rank(self):
        return self._coord("pp")

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._groups.get("pp")

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._coord("sharding")

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._groups.get("sharding")

    # sep
    def get_sep_parallel_rank(self):
        return self._coord("sep")

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._groups.get("sep")

    def _coord(self, name):
        if name not in self._topo.get_hybrid_group_names():
            return 0
        my = self.global_rank if self.global_rank < self.nranks else 0
        coord = self._topo.get_coord(my)
        return coord[self._topo.get_hybrid_group_names().index(name)]

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pp=stage_id, **kwargs)
