"""fleet.elastic (reference: python/paddle/distributed/fleet/elastic)."""
from .manager import (  # noqa: F401
    ELASTIC_AUTO_PARALLEL_EXIT_CODE, ELASTIC_EXIT_CODE, CoordinationStore,
    ElasticManager, ElasticStatus, LocalFileStore)

__all__ = ["ElasticManager", "ElasticStatus", "LocalFileStore",
           "CoordinationStore", "ELASTIC_EXIT_CODE",
           "ELASTIC_AUTO_PARALLEL_EXIT_CODE"]
