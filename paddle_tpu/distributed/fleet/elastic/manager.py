"""Elastic training manager: membership, heartbeats, relaunch decisions.

TPU-native equivalent of the reference's elastic manager (reference:
python/paddle/distributed/fleet/elastic/manager.py:126 ElasticManager —
etcd node registration with TTL, scale-event watching, fault-tolerance
levels, relaunch via exit codes ELASTIC_EXIT_CODE=101 / auto-parallel
102 at manager.py:32-33). The store is pluggable: the JAX
coordination-service KV (multi-host jobs) or a local file store
(single-host tests / the launcher's watch loop) — both give the same
registration/heartbeat/watch semantics etcd gives the reference.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["ElasticManager", "ElasticStatus", "LocalFileStore",
           "CoordinationStore", "ELASTIC_EXIT_CODE",
           "ELASTIC_AUTO_PARALLEL_EXIT_CODE"]

ELASTIC_EXIT_CODE = 101                 # manager.py:32
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 102   # manager.py:33
ELASTIC_TTL = 60                        # manager.py:39 default
ELASTIC_TIMEOUT = 120


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"       # waiting for np to recover
    RESTART = "restart"
    EXIT = "exit"


class LocalFileStore:
    """File-backed KV for single-host elastic tests (etcd stand-in)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key.replace("/", "__"))

    def set(self, key: str, value: str) -> None:
        # write-then-rename: readers never observe a truncated heartbeat
        path = self._path(key)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, path)

    def get(self, key: str) -> Optional[str]:
        try:
            with open(self._path(key)) as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def keys(self, prefix: str) -> List[str]:
        import re

        p = prefix.replace("/", "__")
        return [f.replace("__", "/") for f in os.listdir(self.root)
                if f.startswith(p)
                and not re.search(r"\.tmp\d+$", f)]  # our own tmp files


class CoordinationStore:
    """KV over the JAX coordination service (multi-host path)."""

    def __init__(self):
        from ...communication.collectives import _coord_client

        self._client = _coord_client()
        self._known: List[str] = []

    def set(self, key: str, value: str) -> None:
        self._client.key_value_set(key, value, allow_overwrite=True)
        if key not in self._known:
            self._known.append(key)

    def get(self, key: str) -> Optional[str]:
        try:
            return self._client.key_value_try_get(key)
        except Exception:
            return None

    def delete(self, key: str) -> None:
        self._client.key_value_delete(key)

    def keys(self, prefix: str) -> List[str]:
        try:
            return [k for k, _ in self._client.key_value_dir_get(prefix)]
        except Exception:
            return [k for k in self._known if k.startswith(prefix)]


class ElasticManager:
    """reference: elastic/manager.py:126.

    np spec "min" or "min:max" (PADDLE_ELASTIC_NP contract): the job
    holds while live hosts ∈ [min, max] differs from the launched world,
    restarts when membership changed but is still viable, exits when it
    can't recover within elastic_timeout.
    """

    def __init__(self, job_id: str = None, np: str = None,
                 host: str = None, store=None,
                 ttl: int = None, elastic_timeout: int = None):
        self.job_id = job_id or os.getenv("PADDLE_ELASTIC_JOB_ID",
                                          "default")
        np = np or os.getenv("PADDLE_ELASTIC_NP", "1")
        self.min_np, self.max_np = self._parse_np(np)
        self.host = host or os.getenv("POD_IP", f"host-{os.getpid()}")
        self.ttl = ttl or int(os.getenv("PADDLE_ELASTIC_TTL",
                                        str(ELASTIC_TTL)))
        self.elastic_timeout = elastic_timeout or int(
            os.getenv("PADDLE_ELASTIC_TIMEOUT", str(ELASTIC_TIMEOUT)))
        self.store = store if store is not None else LocalFileStore(
            os.path.join("/tmp", f"paddle_tpu_elastic_{self.job_id}"))
        self.enable = self.min_np > 0
        self._beat_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._launched_hosts: List[str] = []

    @staticmethod
    def _parse_np(np_spec: str):
        """"4" -> (4, 4); "2:8" -> (2, 8) (manager.py _parse_np)."""
        if ":" in str(np_spec):
            lo, hi = str(np_spec).split(":")
            return int(lo), int(hi)
        n = int(np_spec)
        return n, n

    # ---- registration + heartbeat (etcd lease equivalent) ----
    def _key(self, host: str) -> str:
        return f"elastic/{self.job_id}/nodes/{host}"

    def register(self) -> None:
        self._heartbeat()
        if self._beat_thread is None:
            self._beat_thread = threading.Thread(
                target=self._beat_loop, daemon=True)
            self._beat_thread.start()

    def _heartbeat(self) -> None:
        self.store.set(self._key(self.host),
                       json.dumps({"ts": time.time()}))

    def _beat_loop(self) -> None:
        while not self._stop.wait(max(self.ttl / 3, 0.05)):
            self._heartbeat()

    def deregister(self) -> None:
        self._stop.set()
        self.store.delete(self._key(self.host))

    # ---- membership ----
    def hosts(self) -> List[str]:
        """Hosts whose heartbeat is within TTL."""
        now = time.time()
        live = []
        for key in self.store.keys(f"elastic/{self.job_id}/nodes/"):
            raw = self.store.get(key)
            if raw is None:
                continue
            try:
                ts = json.loads(raw)["ts"]
            except Exception:
                continue
            if now - ts <= self.ttl:
                live.append(key.rsplit("/", 1)[-1])
        return sorted(live)

    def snapshot_launched(self) -> None:
        self._launched_hosts = self.hosts()

    # ---- decisions (manager.py watch loop) ----
    def need_scale(self) -> bool:
        return set(self.hosts()) != set(self._launched_hosts)

    def viable(self) -> bool:
        return self.min_np <= len(self.hosts()) <= self.max_np

    def watch_once(self) -> str:
        """One decision tick: HOLD (unchanged), RESTART (membership
        changed but viable), or HOLD-until-timeout→EXIT handled by
        wait_viable."""
        if not self.need_scale():
            return ElasticStatus.HOLD
        if self.viable():
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def wait_viable(self, poll: float = 0.1) -> bool:
        """Block until membership is viable or elastic_timeout passes
        (False → caller should exit with ELASTIC_EXIT_CODE)."""
        deadline = time.time() + self.elastic_timeout
        while time.time() < deadline:
            if self.viable():
                return True
            time.sleep(poll)
        return False
