"""Fleet facade.

TPU-native equivalent of the reference's fleet (reference:
python/paddle/distributed/fleet/fleet.py — Fleet:100, init:167,
distributed_model via fleet/model.py:32, distributed_optimizer:1306 →
HybridParallelOptimizer). ``fleet.init`` builds the hybrid topology as a
ProcessMesh; ``distributed_model`` wraps per parallel mode;
``distributed_optimizer`` adds TP-aware grad clip + sharding.
"""
from __future__ import annotations

from typing import Optional

from ..env import get_rank, get_world_size
from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["Fleet", "fleet", "init", "get_hybrid_communicate_group",
           "distributed_model", "distributed_optimizer", "worker_index",
           "worker_num", "is_first_worker"]

_hcg: Optional[HybridCommunicateGroup] = None
_strategy: Optional[DistributedStrategy] = None


class Fleet:
    def __init__(self):
        self._is_initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        global _hcg, _strategy
        strategy = strategy or DistributedStrategy()
        _strategy = strategy
        hc = strategy.hybrid_configs
        dims = [hc["pp_degree"], hc["mp_degree"], hc.get("sep_degree", 1),
                hc["sharding_degree"], hc["dp_degree"]]
        names = ["pp", "mp", "sep", "sharding", "dp"]
        topo = CommunicateTopology(names, dims)
        _hcg = HybridCommunicateGroup(topo)
        self._is_initialized = True
        return self

    @property
    def is_initialized(self):
        return self._is_initialized

    def get_hybrid_communicate_group(self):
        return _hcg

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return max(get_world_size(), 1)

    def is_first_worker(self):
        return self.worker_index() == 0

    def barrier_worker(self):
        from ..communication.group import barrier

        barrier()

    def distributed_model(self, model):
        """Wrap per topology (fleet/model.py:32)."""
        hcg = _hcg
        if hcg is None:
            raise RuntimeError("call fleet.init first")
        if hcg.get_pipe_parallel_world_size() > 1:
            from .meta_parallel.pipeline_parallel import (
                PipelineParallel, PipelineParallelWithInterleave)

            if getattr(model, "_num_virtual", 1) > 1:
                return PipelineParallelWithInterleave(model, hcg, _strategy)
            return PipelineParallel(model, hcg, _strategy)
        if hcg.get_model_parallel_world_size() > 1 or \
                hcg.get_sep_parallel_world_size() > 1:
            from .meta_parallel.tensor_parallel import TensorParallel

            return TensorParallel(model, hcg, _strategy)
        if hcg.get_data_parallel_world_size() > 1 and get_world_size() > 1:
            from ..parallel import DataParallel

            return DataParallel(model, group=hcg.get_data_parallel_group())
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        from .meta_optimizers.hybrid_parallel_optimizer import (
            HybridParallelOptimizer,
        )

        if _hcg is None:
            return optimizer
        return HybridParallelOptimizer(optimizer, _hcg,
                                       strategy or _strategy)

    # static-graph-era APIs kept as informative stubs
    def minimize(self, *a, **k):
        raise NotImplementedError(
            "static-graph fleet.minimize: use distributed_optimizer + "
            "dygraph/TrainStep flow on TPU")


fleet = Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    global _hcg
    if _hcg is None:
        # implicit single-axis topology (world of 1): everything degree 1
        fleet.init()
    return _hcg
