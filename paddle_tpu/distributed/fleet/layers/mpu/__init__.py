from . import mp_layers, mp_ops, random  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
