"""Tensor-parallel (model-parallel) layers.

TPU-native equivalent of the reference's mpu layers (reference:
python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:47, ColumnParallelLinear:333,
RowParallelLinear:540, ParallelCrossEntropy:741 with c_identity/c_concat/
c_split comm ops). The TPU design: weights are mesh-sharded dist tensors;
the matmul is written once and GSPMD partitions it — a column-parallel
linear's output arrives sharded on the feature dim, a row-parallel
linear's contraction emits the all-reduce, exactly the collectives the
reference issues by hand through NCCL. `gather_output` /
`input_is_parallel` become reshard annotations.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from .....core.generator import get_rng_tracker
from .....core.tensor import Tensor
from ..... import nn
from .....nn import functional as F
from .....nn import initializer as I
from .....nn.layer_base import Layer
from ....auto_parallel.api import reshard, shard_tensor
from ....auto_parallel.placement import Replicate, Shard

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _hcg():
    from ... import fleet

    return fleet.get_hybrid_communicate_group()


def _mp_mesh_axis():
    hcg = _hcg()
    mesh = hcg.mesh
    axis = mesh.dim_names.index("mp")
    return mesh, axis


def _placements(mesh, **axis_to_dim):
    pls = [Replicate()] * mesh.ndim
    for name, dim in axis_to_dim.items():
        pls[mesh.dim_names.index(name)] = Shard(dim)
    return pls


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp (mp_layers.py:47)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        mesh, _ = _mp_mesh_axis()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        w = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        self.weight = shard_tensor(w, mesh, _placements(mesh, mp=0))

    def forward(self, x):
        # GSPMD turns the sharded-vocab gather into masked-lookup+allreduce
        # (the c_lookup_table + mp_allreduce pair, mp_ops.py)
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """W sharded on the output dim (mp_layers.py:333)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        mesh, _ = _mp_mesh_axis()
        self._mesh = mesh
        self.gather_output = gather_output
        w = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight = shard_tensor(w, mesh, _placements(mesh, mp=1))
        if has_bias or has_bias is None:
            b = self.create_parameter(shape=[out_features], is_bias=True)
            self.bias = shard_tensor(b, mesh, _placements(mesh, mp=0))
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            mesh = self._mesh
            out = reshard(
                shard_or_self(out, mesh), mesh,
                [Replicate()] * mesh.ndim)
        return out


def shard_or_self(t: Tensor, mesh):
    if t._dist_attr is None:
        t._dist_attr = (mesh, [Replicate()] * mesh.ndim)
    return t


class RowParallelLinear(Layer):
    """W sharded on the input dim; contraction emits the mp all-reduce
    (mp_layers.py:540)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        mesh, _ = _mp_mesh_axis()
        self._mesh = mesh
        self.input_is_parallel = input_is_parallel
        w = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight = shard_tensor(w, mesh, _placements(mesh, mp=0))
        if has_bias:
            # bias replicated; added after the implicit allreduce
            b = self.create_parameter(shape=[out_features], is_bias=True)
            self.bias = shard_tensor(b, mesh, [Replicate()] * mesh.ndim)
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel and isinstance(x, Tensor) and \
                x._dist_attr is None:
            x = shard_or_self(x, self._mesh)
        # GSPMD: [.., in/mp] @ [in/mp, out] contracts the sharded dim →
        # psum over mp inserted by the partitioner
        out = F.linear(x, self.weight, self.bias)
        return out


class ParallelCrossEntropy(Layer):
    """Cross entropy over mp-sharded logits (mp_layers.py:741).

    The reference computes a stable softmax without gathering logits
    (c_softmax_with_cross_entropy). With GSPMD the plain cross-entropy
    over sharded logits compiles to the same pattern (per-shard max/sum +
    mp all-reduce) — no gather of the vocab dim.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
