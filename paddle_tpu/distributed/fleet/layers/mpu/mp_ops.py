"""mp comm ops (reference: fleet/layers/mpu/mp_ops.py — _c_identity,
_c_concat, _c_split, _mp_allreduce over NCCL). On TPU these are reshard
annotations over the mp mesh axis."""
from .....core.tensor import Tensor
from ....auto_parallel.api import reshard, shard_tensor
from ....auto_parallel.placement import Replicate, Shard

__all__ = ["_c_identity", "_c_concat", "_c_split", "_mp_allreduce"]


def _mesh():
    from ... import fleet

    return fleet.get_hybrid_communicate_group().mesh


def _c_identity(tensor, group=None, skip_c_identity_dynamic=False):
    return tensor


def _mp_allreduce(tensor, op=None, group=None, use_calc_stream=True,
                  use_model_parallel=True):
    mesh = _mesh()
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    if t._dist_attr is None:
        return t
    return reshard(t, mesh, [Replicate()] * mesh.ndim)


def _c_split(tensor, group=None):
    mesh = _mesh()
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    pls = [Replicate()] * mesh.ndim
    pls[mesh.dim_names.index("mp")] = Shard(t.ndim - 1)
    if t._dist_attr is None:
        t = shard_tensor(t, mesh, [Replicate()] * mesh.ndim)
    return reshard(t, mesh, pls)


def _c_concat(tensor, group=None):
    mesh = _mesh()
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    if t._dist_attr is None:
        return t
    return reshard(t, mesh, [Replicate()] * mesh.ndim)
