"""TP RNG state tracking (reference: fleet/layers/mpu/random.py:34
RNGStatesTracker) — re-export of the core tracker."""
from .....core.generator import (  # noqa: F401
    RNGStatesTracker, get_rng_tracker, rng_state,
)

def get_rng_state_tracker():
    return get_rng_tracker()

model_parallel_random_seed = None

def determinate_seed(rng_name="global_seed"):
    return 0
