"""HybridParallelOptimizer (reference:
python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:254 — TP-deduped global-norm grad clip,
DP/sharding grad sync before step)."""
from __future__ import annotations

import jax.numpy as jnp

from ....core.engine import no_grad
from ....nn.clip import ClipGradByGlobalNorm

__all__ = ["HybridParallelOptimizer", "HybridParallelGradScaler"]


class _HybridGlobalNormClip(ClipGradByGlobalNorm):
    """Global-norm clip whose squared norm spans TP shards.

    Reference behavior (_obtain_optimizer_parameters_list + clip with
    allreduce over mp group): distributed (sharded) params contribute their
    shard's norm, then the squared norm is summed across the mp axis. With
    dist tensors the per-shard sums are already global values, so the base
    computation is correct as-is; this subclass exists to mirror the
    reference's dedup of replicated (non-distributed) params.
    """

    def __init__(self, base_clip: ClipGradByGlobalNorm, hcg):
        super().__init__(base_clip.clip_norm)
        self._hcg = hcg


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if isinstance(optimizer._grad_clip, ClipGradByGlobalNorm):
            optimizer._grad_clip = _HybridGlobalNormClip(
                optimizer._grad_clip, hcg)
        sharding_degree = hcg.get_sharding_parallel_world_size()
        if sharding_degree > 1:
            from ..meta_parallel.sharding.sharding_optimizer import (
                shard_optimizer_states,
            )

            shard_optimizer_states(optimizer, hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    @no_grad()
    def step(self):
        self._dp_sync_grads()
        self._inner_opt.step()

    def _dp_sync_grads(self):
        """DP gradient averaging before the update (the EagerReducer moment).
        With one process + dist tensors, gradients of replicated params are
        already globally correct (GSPMD psum); multi-process uses the host
        collective."""
        import jax

        if jax.process_count() <= 1:
            return
        from ...communication.collectives import ReduceOp, all_reduce

        group = self._hcg.get_data_parallel_group()
        if group is None or group.nranks <= 1:
            return
        for p in self._inner_opt._parameter_list:
            if p.grad is not None:
                all_reduce(p.grad, op=ReduceOp.AVG, group=group)

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        self.step()
        return None, None

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)


class HybridParallelGradScaler:
    """reference: hybrid_parallel_gradscaler.py — scaler aware of hybrid
    groups; found_inf is or-reduced across the mesh. Single-controller XLA
    computes globally-correct isfinite already, so this wraps GradScaler."""

    def __init__(self, scaler, hcg):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, item):
        return getattr(self._scaler, item)
