from .parallel_layers.pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .pipeline_parallel import PipelineParallel, PipelineParallelWithInterleave  # noqa: F401
from .tensor_parallel import TensorParallel  # noqa: F401
from .sharding.sharding_optimizer import (  # noqa: F401
    DygraphShardingOptimizer, GroupShardedOptimizerStage2, GroupShardedStage2,
    GroupShardedStage3,
)
from ..layers.mpu.mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from ..layers.mpu.random import get_rng_state_tracker  # noqa: F401
