from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
