"""Pipeline model partitioning.

TPU-native equivalent of the reference's PipelineLayer (reference:
fleet/meta_parallel/parallel_layers/pp_layers.py — PipelineLayer:237,
LayerDesc:56, SharedLayerDesc:76 for tied embeddings, segmentation by
uniform/layer-count/flops). Single-controller JAX builds ALL stages in one
process (the mesh, not the process, is the unit of placement); the
partitioner keeps the reference's segmentation semantics so stage
boundaries are identical.
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional

import numpy as np

from .....nn.layer_base import Layer, LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer across stages (tied embeddings, pp_layers.py:76)."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        self._num_virtual = num_virtual_pipeline_stages or 1
        self._recompute_interval = recompute_interval
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe") if "pipe" in \
                topology.get_hybrid_group_names() else topology.get_dim("pp")
        self._num_stages = num_stages or 1

        self._layer_descs = list(layers)
        self._shared_layers = {}

        built: List[Layer] = []
        for desc in self._layer_descs:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name not in self._shared_layers:
                    self._shared_layers[desc.layer_name] = desc.build_layer()
                built.append(_SharedLayerView(
                    self._shared_layers[desc.layer_name], desc.forward_func))
            elif isinstance(desc, LayerDesc):
                built.append(desc.build_layer())
            elif isinstance(desc, Layer):
                built.append(desc)
            elif callable(desc):
                built.append(_FuncLayer(desc))
            else:
                raise TypeError(f"bad pipeline entry {desc!r}")
        self.run_function = LayerList(built)

        self.segment_parts = self._segment(seg_method)

    def _segment(self, seg_method) -> List[int]:
        """Stage boundaries (reference SegmentLayers): 'uniform' splits by
        layer count; 'layer:Prefix' balances only the named layers."""
        n = len(self.run_function)
        stages = self._num_stages
        if isinstance(seg_method, str) and seg_method.startswith("layer:"):
            pat = seg_method[len("layer:"):]
            weights = [1 if re.search(pat, type(l).__name__) else 0
                       for l in self.run_function]
            total = sum(weights) or n
            per = total / stages
            parts = [0]
            acc = 0
            for i, w in enumerate(weights):
                acc += w
                if len(parts) < stages and acc >= per * len(parts):
                    parts.append(i + 1)
            while len(parts) < stages:
                parts.append(n)
            parts.append(n)
            return parts
        cuts = np.linspace(0, n, stages + 1).astype(int).tolist()
        return cuts

    def get_stage_from_index(self, idx: int) -> int:
        for s in range(self._num_stages):
            if self.segment_parts[s] <= idx < self.segment_parts[s + 1]:
                return s
        return self._num_stages - 1

    def stage_layers(self, stage: int) -> List[Layer]:
        lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
        return list(self.run_function)[lo:hi]

    def forward(self, x, stage: Optional[int] = None):
        layers = self.run_function if stage is None \
            else self.stage_layers(stage)
        offset = 0 if stage is None else self.segment_parts[stage]
        for i, layer in enumerate(layers):
            idx = offset + i
            if self._recompute_interval > 0 and \
                    idx % self._recompute_interval == 0 and self.training:
                from ...recompute.recompute import recompute

                x = recompute(layer, *(x if isinstance(x, tuple) else (x,)))
            else:
                x = layer(*(x if isinstance(x, tuple) else (x,)))
        return x

    @property
    def parameters_by_stage(self):
        return [
            [p for l in self.stage_layers(s) for p in l.parameters()]
            for s in range(self._num_stages)]


class _FuncLayer(Layer):
    def __init__(self, fn: Callable):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class _SharedLayerView(Layer):
    def __init__(self, shared: Layer, forward_func=None):
        super().__init__()
        self.shared = shared
        self._forward_func = forward_func

    def forward(self, *args):
        if self._forward_func is not None:
            return self._forward_func(self.shared, *args)
        return self.shared(*args)
