"""Pipeline-parallel execution.

TPU-native equivalent of the reference's PipelineParallel (reference:
fleet/meta_parallel/pipeline_parallel.py — PipelineParallel:150, 1F1B
forward_backward_pipeline:440, train_batch:657; interleave variant :906;
p2p via batch_isend_irecv pp_utils/p2p_communication.py:313).

Single-controller JAX formulation: the 1F1B schedule interleaves
micro-batch forwards and backwards per stage to bound live activations —
warmup forwards (pp_degree - stage - 1 deep), steady 1F1B, cooldown.
Stage handoffs are ordinary array dependencies (the compiled path lowers
them to ICI transfers); gradients accumulate across micro-batches on the
tape. The compiled-overlap schedule (stacked stage weights + shard_map +
ppermute) is the planned follow-up; this class fixes API + numerics.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax.numpy as jnp

from ....core.tensor import Tensor
from ....nn.layer_base import Layer
from .parallel_layers.pp_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pp_cfg = strategy.hybrid_configs.get("pp_configs") \
            if strategy is not None else None
        self.accumulate_steps = getattr(pp_cfg, "accumulate_steps", 1) \
            if pp_cfg else 1
        self.micro_batch_size = getattr(pp_cfg, "micro_batch_size", 1) \
            if pp_cfg else 1
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.total_loss = None

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def forward(self, x):
        return self._layers(x)

    # ---- the schedule ----
    def _split_micro(self, data):
        """Split the global batch into accumulate_steps micro-batches."""
        if isinstance(data, (tuple, list)):
            splits = [self._split_micro(d) for d in data]
            return list(zip(*splits))
        n = self.accumulate_steps
        arr = data._data if isinstance(data, Tensor) else jnp.asarray(data)
        if arr.shape[0] % n != 0:
            raise ValueError(
                f"batch dim {arr.shape[0]} not divisible by "
                f"accumulate_steps {n}")
        return [Tensor(p) for p in jnp.split(arr, n, axis=0)]

    def forward_backward_pipeline(self, data, scaler=None):
        """1F1B (forward_backward_pipeline:440): per-micro forward then
        backward in schedule order; grads accumulate on the tape."""
        inputs, labels = data
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)
        n_micro = self.accumulate_steps
        total = None

        # single-controller: each micro's backward follows its forward
        # (identical accumulated grads to the staged 1F1B ordering)
        for mb in range(n_micro):
            x = micro_inputs[mb]
            y = micro_labels[mb]
            out = self._layers(x if not isinstance(x, tuple) else x)
            loss = self._layers._loss_fn(out, y)
            loss = loss / n_micro
            if scaler is not None:
                scaled = scaler.scale(loss)
                scaled.backward()
            else:
                loss.backward()
            total = loss if total is None else Tensor(
                total._data + loss._data)
        self.total_loss = total
        return total

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """(train_batch:657)"""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        inputs, labels = data
        from ....core.engine import no_grad

        with no_grad():
            out = self._layers(inputs)
            if compute_loss:
                return self._layers._loss_fn(out, labels)
        return out


class PipelineParallelWithInterleave(PipelineParallel):
    """VPP (pipeline_parallel.py:906): virtual stages interleave on each
    rank. Single-controller execution is schedule-equivalent; kept as a
    distinct type for API parity and the compiled-schedule follow-up."""

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
