"""Pipeline-parallel execution over the ``pp`` mesh axis.

TPU-native equivalent of the reference's PipelineParallel (reference:
fleet/meta_parallel/pipeline_parallel.py — PipelineParallel:150, 1F1B
forward_backward_pipeline:440, train_batch:657; interleave variant :906;
p2p via batch_isend_irecv pp_utils/p2p_communication.py:313).

Design (see pp_utils/spmd_pipeline.py for the engines): stages are
placed on the ``pp`` mesh axis — the uniform repeated region of the
PipelineLayer (e.g. the transformer blocks) has its parameters STACKED
into [pp, ...] arrays sharded over that axis; every stage handoff is a
``lax.ppermute`` (collective-permute over ICI) inside one compiled XLA
program. Non-uniform head/tail layers (embedding, final norm + head +
loss) run replicated across pp under GSPMD, exactly like the reference
keeps embedding/head on the first/last stage.

Schedules:
- ``1F1B`` (default): true one-forward-one-backward macro-tick schedule
  with vjp-residual ring buffers of depth 2*pp — live activations stay
  O(pp_depth) regardless of accumulate_steps.
- ``FThenB``: differentiable circular rotation (GPipe order), residuals
  bounded by jax.checkpoint on the stage body.
- interleave (``PipelineParallelWithInterleave``): circular rotation
  with num_virtual_pipeline_stages chunks per device (chunk c on device
  c mod pp), matching the reference's virtual-stage placement.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ....core import engine
from ....core.generator import next_rng_key, use_trace_key
from ....core.tensor import Parameter, Tensor
from ....nn.layer_base import Layer
from .parallel_layers.pp_layers import PipelineLayer
from .pp_utils.spmd_pipeline import (circular_pipeline_fwd,
                                     pipeline_1f1b_grads,
                                     pipeline_interleaved_1f1b_grads)

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


def _trailing_spec(tmpl_p, ndim_stacked: int, pp_axis: str):
    """Per-dim axis names for a stacked param's trailing dims: a template
    param carrying a dist annotation (e.g. ColumnParallelLinear's
    mp=Shard(1)) keeps its sharding on the stacked array, so GSPMD
    partitions the stage matmuls over mp INSIDE the pp shard_map
    (TP+PP composition — reference dygraph_hybrid_dpppmp.py)."""
    trailing = [None] * (ndim_stacked - 1)
    dist = getattr(tmpl_p, "_dist_attr", None)
    if dist is not None:
        dmesh, placements = dist
        from ...auto_parallel.placement import Shard as _Shard

        for ax_name, pl in zip(dmesh.dim_names, placements):
            if isinstance(pl, _Shard) and ax_name != pp_axis:
                trailing[pl.dim] = ax_name
    return trailing


def _scalar_config(layer: Layer):
    """Non-parameter configuration that changes compute (dropout rate,
    eps, activation name, ...) — layers whose config differs must not be
    stacked under one template."""
    out = []
    stack = [("", layer)]
    seen = set()
    while stack:
        prefix, l = stack.pop()
        if id(l) in seen:
            continue
        seen.add(id(l))
        for k in sorted(l.__dict__):
            if k in ("training", "_full_name") or k.startswith("__"):
                continue
            v = l.__dict__[k]
            if isinstance(v, (int, float, bool, str, type(None))):
                out.append((prefix, k, v))
        subs = l.__dict__.get("_sub_layers") or {}
        for name, sub in subs.items():
            if sub is not None:
                stack.append((f"{prefix}.{name}", sub))
    return tuple(sorted(out))


def _layer_sig(layer: Layer):
    """Structural signature: stages must be built from layers with
    identical signatures to be stackable."""
    params = [(n, tuple(p.shape), str(p.dtype))
              for n, p in layer.named_parameters()]
    buffers = [n for n, _ in layer.named_buffers()]
    return (type(layer).__name__, tuple(params), tuple(buffers),
            _scalar_config(layer))


class PipelineParallel(Layer):
    _num_virtual = 1

    def __init__(self, layers, hcg, strategy):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pp_cfg = strategy.hybrid_configs.get("pp_configs") \
            if strategy is not None else None
        self.accumulate_steps = getattr(pp_cfg, "accumulate_steps", 1) \
            if pp_cfg else 1
        self.micro_batch_size = getattr(pp_cfg, "micro_batch_size", 1) \
            if pp_cfg else 1
        self.schedule = getattr(pp_cfg, "schedule_mode", "1F1B") \
            if pp_cfg else "1F1B"
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.total_loss = None
        self._pp_axis = "pp"
        self._step_fn = None
        self._multi_run = False
        self._segments = []
        if self._num_virtual == 1:
            self._num_virtual = getattr(layers, "_num_virtual", 1) or 1
        self._partition_and_stack()

    # ------------------------------------------------------------------
    # stage extraction: pre | uniform run (stacked over pp) | post
    # ------------------------------------------------------------------
    def _partition_and_stack(self):
        built = list(self._layers.run_function)
        sigs = [_layer_sig(l) for l in built]
        n = len(built)
        chunks = self.num_stages * self._num_virtual

        def _stackable_group(lo, q):
            group = sigs[lo:lo + q]
            has_params = any(s[1] for s in group)
            no_buffers = all(not s[2] for s in group)
            return has_params and no_buffers

        # Multi-run decomposition first: when the model has SEVERAL
        # distinct stackable runs (blocks that change config mid-stack),
        # pipelining all of them through per-run circular engines beats
        # stacking only the first run and replicating the rest
        # (reference seg-method flexibility, pp_layers.py:237).
        if self._partition_multi_run(built, sigs):
            return

        # longest run of period-q repeating signatures (q=1 is the plain
        # identical-layer case; q>1 covers e.g. alternating Attn/MLP
        # LayerDescs — the reference's common decomposition)
        best = None  # (usable_layers, -q, lo)
        for q in range(1, n // chunks + 1):
            for lo in range(n - q * chunks + 1):
                if not _stackable_group(lo, q):
                    continue
                j = lo + q
                while j + q <= n and sigs[j:j + q] == sigs[lo:lo + q]:
                    j += q
                ngroups = (j - lo) // q
                gpc = ngroups // chunks      # groups per chunk
                usable = gpc * chunks * q
                if gpc >= 1 and (best is None or
                                 (usable, -q) > (best[0], best[1])):
                    best = (usable, -q, lo)
        if best is None:
            raise ValueError(
                f"PipelineParallel: no repeating layer run long enough "
                f"for pp_degree*virtual ({chunks}); stage stacking over "
                f"the pp mesh axis needs at least one structurally "
                f"identical (same class/shape/config) layer group per "
                f"stage")
        usable, negq, lo = best
        k = usable // chunks                 # layers per chunk
        self._chunk_size = k
        self._pre_layers = built[:lo]
        run = built[lo:lo + usable]
        self._post_layers = built[lo + usable:]
        self._template = run[:k]  # chunk 0: the trace template
        self._template_params = [p for l in self._template
                                 for _, p in l.named_parameters()]

        # stack chunk params device-major: slot j on device p = chunk j*P+p
        import numpy as onp

        P_, v = self.num_stages, self._num_virtual
        mesh = self._hcg.mesh.jax_mesh()
        per_chunk: List[List[Any]] = []
        for c in range(chunks):
            ps = [p for l in run[c * k:(c + 1) * k]
                  for _, p in l.named_parameters()]
            per_chunk.append(ps)
        self._stacked_params: List[Parameter] = []
        tmpl_names = [f"{l._full_name}.{pn}" for l in self._template
                      for pn, _ in l.named_parameters()]
        for q in range(len(self._template_params)):
            tmpl_p = self._template_params[q]
            # build only the local shards (no transient full replica on
            # device): host-side stack, per-shard callback
            host = onp.stack(
                [onp.asarray(per_chunk[j * P_ + p][q]._data)
                 for p in range(P_) for j in range(v)])
            trailing = _trailing_spec(tmpl_p, host.ndim, self._pp_axis)
            sh = NamedSharding(
                mesh, PartitionSpec(self._pp_axis, *trailing))
            arr = jax.make_array_from_callback(
                host.shape, sh, lambda idx, h=host: h[idx])
            sp = Parameter(arr, name=f"pp_stack.{q}.{tmpl_names[q]}",
                           trainable=not tmpl_p.stop_gradient)
            # preserve optimizer-relevant attributes (per-param lr,
            # regularizer, clip) from the template parameter
            sp.optimize_attr = dict(tmpl_p.optimize_attr)
            sp.regularizer = tmpl_p.regularizer
            sp.need_clip = tmpl_p.need_clip
            self._stacked_params.append(sp)
        # release non-template originals — the stacked pp-sharded arrays
        # are now the single source of truth; keeping every per-chunk
        # replica alive would double body-parameter HBM. (The wrapped
        # PipelineLayer must no longer be used directly for compute.)
        from .parallel_layers.pp_layers import _SharedLayerView

        for l in run[k:]:
            if isinstance(l, _SharedLayerView):
                continue
            for _, p in l.named_parameters():
                p._rebind(jnp.zeros((0,), p._data.dtype))
        self._pre_params = [p for l in self._pre_layers
                            for _, p in l.named_parameters()]
        self._post_params = [p for l in self._post_layers
                             for _, p in l.named_parameters()]

    # ------------------------------------------------------------------
    # multi-run decomposition (non-uniform models)
    # ------------------------------------------------------------------
    def _stack_run(self, run, k):
        """Stack a run of ``chunks * k`` layers into device-major
        [chunks, ...] pp-sharded Parameters. Returns
        (template, template_params, stacked_params)."""
        import numpy as onp

        P_, v = self.num_stages, self._num_virtual
        chunks = P_ * v
        mesh = self._hcg.mesh.jax_mesh()
        template = run[:k]
        template_params = [p for l in template
                           for _, p in l.named_parameters()]
        per_chunk = []
        for c in range(chunks):
            per_chunk.append([p for l in run[c * k:(c + 1) * k]
                              for _, p in l.named_parameters()])
        stacked = []
        tmpl_names = [f"{l._full_name}.{pn}" for l in template
                      for pn, _ in l.named_parameters()]
        for q in range(len(template_params)):
            tmpl_p = template_params[q]
            host = onp.stack(
                [onp.asarray(per_chunk[j * P_ + p][q]._data)
                 for p in range(P_) for j in range(v)])
            trailing = _trailing_spec(tmpl_p, host.ndim, self._pp_axis)
            sh = NamedSharding(
                mesh, PartitionSpec(self._pp_axis, *trailing))
            arr = jax.make_array_from_callback(
                host.shape, sh, lambda idx, h=host: h[idx])
            sp = Parameter(arr, name=f"pp_stack.{q}.{tmpl_names[q]}",
                           trainable=not tmpl_p.stop_gradient)
            sp.optimize_attr = dict(tmpl_p.optimize_attr)
            sp.regularizer = tmpl_p.regularizer
            sp.need_clip = tmpl_p.need_clip
            stacked.append(sp)
        from .parallel_layers.pp_layers import _SharedLayerView

        for l in run[k:]:
            if isinstance(l, _SharedLayerView):
                continue
            for _, p in l.named_parameters():
                p._rebind(jnp.zeros((0,), p._data.dtype))
        return template, template_params, stacked

    def _partition_multi_run(self, built, sigs) -> bool:
        """Decompose into [repl | stack | repl | stack | ...] segments
        (reference seg-method flexibility, pp_layers.py:237). Each stack
        run pipelines via the differentiable circular engine; replicated
        sections run on every device under GSPMD. Returns False when the
        model doesn't yield >= 2 stackable runs (then the caller raises
        the single-run error)."""
        if self._num_virtual != 1:
            return False
        chunks = self.num_stages
        n = len(built)

        def _stackable(lo, q):
            group = sigs[lo:lo + q]
            return (any(s[1] for s in group)
                    and all(not s[2] for s in group))

        raw_segs = []
        cur = []
        i = 0
        n_stacks = 0
        while i < n:
            best = None
            for q in range(1, max((n - i) // chunks, 0) + 1):
                if not _stackable(i, q):
                    continue
                j = i + q
                while j + q <= n and sigs[j:j + q] == sigs[i:i + q]:
                    j += q
                gpc = ((j - i) // q) // chunks
                if gpc >= 1:
                    usable = gpc * chunks * q
                    if best is None or usable > best:
                        best = usable
            if best:
                if cur:
                    raw_segs.append(("repl", cur))
                    cur = []
                raw_segs.append(("stack", built[i:i + best]))
                n_stacks += 1
                i += best
            else:
                cur.append(built[i])
                i += 1
        if cur:
            raw_segs.append(("repl", cur))
        if n_stacks < 2:
            return False

        # leading/trailing replicated sections become pre/post
        if raw_segs and raw_segs[0][0] == "repl":
            self._pre_layers = raw_segs.pop(0)[1]
        else:
            self._pre_layers = []
        if raw_segs and raw_segs[-1][0] == "repl":
            self._post_layers = raw_segs.pop()[1]
        else:
            self._post_layers = []

        self._segments = []
        flat_params: List[Parameter] = []
        for kind, layers in raw_segs:
            lo = len(flat_params)
            if kind == "stack":
                k = len(layers) // chunks
                tmpl, tparams, stacked = self._stack_run(layers, k)
                flat_params.extend(stacked)
                self._segments.append({
                    "kind": "stack", "template": tmpl,
                    "tparams": tparams, "stacked": stacked, "k": k,
                    "lo": lo, "hi": len(flat_params)})
            else:
                params = [p for l in layers
                          for _, p in l.named_parameters()]
                flat_params.extend(params)
                self._segments.append({
                    "kind": "repl", "layers": layers, "params": params,
                    "lo": lo, "hi": len(flat_params)})
        self._stacked_params = flat_params
        self._template = None
        self._template_params = []
        self._chunk_size = None
        self._pre_params = [p for l in self._pre_layers
                            for _, p in l.named_parameters()]
        self._post_params = [p for l in self._post_layers
                             for _, p in l.named_parameters()]
        self._multi_run = True
        return True

    # ------------------------------------------------------------------
    # pure functions over raw arrays (trace-time, _SwappedState pattern)
    # ------------------------------------------------------------------
    def _stage_fn(self, template=None, params=None):
        from ....jit.static_function import _SwappedState

        template = template if template is not None else self._template
        params = params if params is not None else self._template_params
        tick_counter = [0]

        def stage_fn(stage_param_leaves, x):
            from ....core.generator import _CURRENT

            base = _CURRENT.trace_key
            tick_counter[0] += 1
            if base is not None:
                # decorrelate dropout per (tick, stage): fold the trace
                # key with the python tick count and the stage index
                key = jax.random.fold_in(base, tick_counter[0])
                key = jax.random.fold_in(
                    key, jax.lax.axis_index(self._pp_axis))
                ctx = use_trace_key(key)
            else:
                import contextlib

                ctx = contextlib.nullcontext()
            with _SwappedState(params, list(stage_param_leaves)), ctx, \
                    engine.no_grad():
                h = Tensor(x)
                for l in template:
                    h = l(h)
            return h._data

        return stage_fn

    def _head_loss_fn(self):
        from ....jit.static_function import _SwappedState

        post_layers, post_params = self._post_layers, self._post_params
        loss_fn = self._layers._loss_fn

        def head_loss(post_leaves, y, label):
            with _SwappedState(post_params, list(post_leaves)), \
                    engine.no_grad():
                h = Tensor(y)
                for l in post_layers:
                    h = l(h)
                loss = loss_fn(h, Tensor(label))
            return loss._data

        return head_loss

    def _pre_fn(self):
        from ....jit.static_function import _SwappedState

        pre_layers, pre_params = self._pre_layers, self._pre_params

        def pre_apply(pre_leaves, xs):
            with _SwappedState(pre_params, list(pre_leaves)), \
                    engine.no_grad():
                h = tuple(Tensor(x) for x in xs)
                for l in pre_layers:
                    h = l(*(h if isinstance(h, tuple) else (h,)))
                    if not isinstance(h, tuple):
                        h = (h,)
                out = h[0] if len(h) == 1 else h
            if isinstance(out, tuple):
                raise ValueError("pipeline stage input must be a single "
                                 "tensor after the pre layers")
            return out._data

        return pre_apply

    def _seg_apply_fn(self, layers, params):
        """Replicated mid-section apply: (param_arrays, h) -> h."""
        from ....jit.static_function import _SwappedState

        def seg_apply(param_arrays, h):
            with _SwappedState(params, list(param_arrays)), \
                    engine.no_grad():
                t = Tensor(h)
                for l in layers:
                    t = l(t)
            return t._data

        return seg_apply

    def _build_step_multirun(self):
        """Compiled step for multi-run models: each stacked run goes
        through the differentiable circular pipeline engine; replicated
        sections run per micro-batch; one jax.value_and_grad over the
        whole chain produces every gradient."""
        mesh = self._hcg.mesh.jax_mesh()
        P_ = self.num_stages
        segs = self._segments
        head_loss = self._head_loss_fn()
        pre_apply = self._pre_fn()
        seg_fns = []
        for seg in segs:
            if seg["kind"] == "stack":
                seg_fns.append(self._stage_fn(seg["template"],
                                              seg["tparams"]))
            else:
                seg_fns.append(self._seg_apply_fn(seg["layers"],
                                                  seg["params"]))

        def step(pre_arrays, seg_arrays, post_arrays, key, x_all,
                 labels_all):
            M = labels_all.shape[0]
            with use_trace_key(key):
                def full_loss(pre_a, seg_a, post_a):
                    h_all = jnp.stack([
                        pre_apply(pre_a, [x[m] for x in x_all])
                        for m in range(M)])
                    for seg, fn in zip(segs, seg_fns):
                        arrs = list(seg_a[seg["lo"]:seg["hi"]])
                        if seg["kind"] == "stack":
                            h_all = circular_pipeline_fwd(
                                fn, arrs, h_all, mesh=mesh,
                                num_stages=P_, num_virtual=1,
                                pp_axis=self._pp_axis)
                        else:
                            h_all = jnp.stack(
                                [fn(arrs, h_all[m]) for m in range(M)])
                    ls = [head_loss(post_a, h_all[m], labels_all[m])
                          for m in range(M)]
                    return jnp.mean(jnp.stack(ls))

                loss, (d_pre, d_seg, d_post) = jax.value_and_grad(
                    full_loss, argnums=(0, 1, 2))(
                    list(pre_arrays), list(seg_arrays),
                    list(post_arrays))
            return loss, list(d_pre), list(d_seg), list(d_post)

        return jax.jit(step)

    # ------------------------------------------------------------------
    # the compiled step
    # ------------------------------------------------------------------
    def _build_step(self):
        if self._multi_run:
            return self._build_step_multirun()
        mesh = self._hcg.mesh.jax_mesh()
        P_, v = self.num_stages, self._num_virtual
        stage_fn = self._stage_fn()
        head_loss = self._head_loss_fn()
        pre_apply = self._pre_fn()
        schedule = self.schedule

        def step(pre_arrays, stacked_leaves, post_arrays, key,
                 x_all: Tuple, labels_all):
            M = labels_all.shape[0]
            with use_trace_key(key):
                h_all, pre_vjp = jax.vjp(
                    lambda pa: jnp.stack([
                        pre_apply(pa, [x[m] for x in x_all])
                        for m in range(M)]), list(pre_arrays))

                if schedule == "1F1B" and v == 1:
                    loss, d_stacked, d_post, dh_all = pipeline_1f1b_grads(
                        stage_fn, head_loss, list(stacked_leaves),
                        list(post_arrays), h_all, labels_all,
                        mesh=mesh, num_stages=P_, pp_axis=self._pp_axis)
                elif schedule == "1F1B":
                    loss, d_stacked, d_post, dh_all = \
                        pipeline_interleaved_1f1b_grads(
                            stage_fn, head_loss, list(stacked_leaves),
                            list(post_arrays), h_all, labels_all,
                            mesh=mesh, num_stages=P_, num_virtual=v,
                            pp_axis=self._pp_axis)
                else:
                    def circ_loss(st, pa, ha):
                        y_all = circular_pipeline_fwd(
                            stage_fn, st, ha, mesh=mesh, num_stages=P_,
                            num_virtual=v, pp_axis=self._pp_axis)
                        ls = [head_loss(pa, y_all[m], labels_all[m])
                              for m in range(M)]
                        return jnp.mean(jnp.stack(ls))

                    loss, (d_stacked, d_post, dh_all) = \
                        jax.value_and_grad(circ_loss, argnums=(0, 1, 2))(
                            list(stacked_leaves), list(post_arrays), h_all)
                (d_pre,) = pre_vjp(dh_all)
            return loss, list(d_pre), list(d_stacked), list(d_post)

        return jax.jit(step)

    def _split_micro_arrays(self, data):
        """Global batch tensor(s) → [M, micro_batch, ...] arrays. When
        the topology has a dp axis, each micro-batch is sharded over it
        — dp, mp and pp then compose inside the ONE compiled step (the
        reference needs a separate DP reducer around the pipeline;
        here GSPMD derives the dp grad all-reduce from the input
        sharding — reference: test/collective/multinode/
        dygraph_hybrid_dpppmp.py composes the same three axes)."""
        n = self.accumulate_steps
        dp_deg = self._hcg.get_data_parallel_world_size()
        dp_mesh = self._hcg.mesh.jax_mesh() if dp_deg > 1 else None

        def one(d):
            arr = d._data if isinstance(d, Tensor) else jnp.asarray(d)
            if arr.shape[0] % n != 0:
                raise ValueError(
                    f"batch dim {arr.shape[0]} not divisible by "
                    f"accumulate_steps {n}")
            arr = arr.reshape((n, arr.shape[0] // n) + arr.shape[1:])
            if dp_mesh is not None and arr.shape[1] % dp_deg == 0:
                import jax as _jax

                sh = NamedSharding(dp_mesh, PartitionSpec(
                    None, "dp", *([None] * (arr.ndim - 2))))
                arr = _jax.device_put(arr, sh)
            return arr

        if isinstance(data, (tuple, list)):
            return tuple(one(d) for d in data)
        return (one(data),)

    # ------------------------------------------------------------------
    # public API (reference parity)
    # ------------------------------------------------------------------
    def forward_backward_pipeline(self, data, scaler=None):
        """One pipelined forward+backward sweep over accumulate_steps
        micro-batches (forward_backward_pipeline:440). Leaves accumulated
        grads on the parameters; returns the mean loss."""
        inputs, labels = data
        x_all = self._split_micro_arrays(inputs)
        (labels_all,) = self._split_micro_arrays(labels)
        if self._step_fn is None:
            self._step_fn = self._build_step()
        key = next_rng_key()
        loss, d_pre, d_stacked, d_post = self._step_fn(
            [p._data for p in self._pre_params],
            [p._data for p in self._stacked_params],
            [p._data for p in self._post_params],
            key, x_all, labels_all)
        for plist, glist in ((self._pre_params, d_pre),
                             (self._stacked_params, d_stacked),
                             (self._post_params, d_post)):
            for p, g in zip(plist, glist):
                if scaler is not None:
                    # grads here are unscaled (manual vjp); pre-scale so
                    # scaler.step's unscale_ sees its usual invariant
                    g = g * scaler._scale
                if p.grad is None:
                    p.grad = Tensor(g)
                else:
                    p.grad = Tensor(p.grad._data + g)
        self.total_loss = Tensor(loss)
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """(train_batch:657)"""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        inputs, labels = data
        with engine.no_grad():
            out = self._apply_sequential(inputs)
            if compute_loss:
                return self._layers._loss_fn(
                    out, labels if isinstance(labels, Tensor)
                    else Tensor(jnp.asarray(labels)))
        return out

    def _apply_sequential(self, x):
        """Replicated sequential execution (eval / debugging): applies
        pre, every chunk in order (slicing the stacked params), post."""
        from ....jit.static_function import _SwappedState

        P_, v, k = self.num_stages, self._num_virtual, self._chunk_size
        h = x if isinstance(x, tuple) else (x,)
        for l in self._pre_layers:
            out = l(*(h if isinstance(h, tuple) else (h,)))
            h = out if isinstance(out, tuple) else (out,)
        h = h[0]
        if self._multi_run:
            for seg in self._segments:
                if seg["kind"] == "repl":
                    for l in seg["layers"]:
                        h = l(h)
                else:
                    for c in range(P_):
                        leaves = [sp._data[c] for sp in seg["stacked"]]
                        with _SwappedState(seg["tparams"], leaves):
                            for l in seg["template"]:
                                h = l(h)
        else:
            for c in range(P_ * v):
                p_, j = c % P_, c // P_
                row = p_ * v + j
                leaves = [sp._data[row] for sp in self._stacked_params]
                with _SwappedState(self._template_params, leaves):
                    for l in self._template:
                        h = l(h)
        for l in self._post_layers:
            h = l(h)
        return h

    def forward(self, x):
        return self._apply_sequential(x)

    # ------------------------------------------------------------------
    # parameters / state
    # ------------------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return (self._pre_params + self._stacked_params +
                self._post_params)

    def named_parameters(self, prefix="", include_sublayers=True):
        out = []
        for p in self._pre_params + self._post_params:
            out.append((p.name, p))
        for sp in self._stacked_params:
            out.append((sp.name, sp))
        return out

    def state_dict(self, *a, **k):
        sd = {}
        for name, p in self.named_parameters():
            sd[name] = p
        return sd

    def set_state_dict(self, state_dict, *a, **k):
        for name, p in self.named_parameters():
            if name in state_dict:
                v = state_dict[name]
                p._rebind(v._data if isinstance(v, Tensor)
                          else jnp.asarray(v))
        return self


class PipelineParallelWithInterleave(PipelineParallel):
    """VPP (pipeline_parallel.py:906): num_virtual_pipeline_stages chunks
    per device, chunk c placed on device c mod pp (the reference's
    interleave placement). Default schedule is the TRUE interleaved 1F1B
    macro-tick engine (``pipeline_interleaved_1f1b_grads`` — one chunk-F
    + one chunk-B per tick, residual ring depth 2*v*pp, ~v× smaller
    bubble); set ``schedule_mode="FThenB"`` in pp_configs to fall back to
    the circular-rotation engine."""

    def __init__(self, layers, hcg, strategy):
        self._num_virtual = max(int(getattr(layers, "_num_virtual", 1) or 1),
                                2)
        super().__init__(layers, hcg, strategy)
