from . import spmd_pipeline  # noqa: F401
