from .sharding_optimizer import (  # noqa: F401
    DygraphShardingOptimizer, GroupShardedOptimizerStage2, GroupShardedStage2,
    GroupShardedStage3, shard_optimizer_states, shard_parameters,
)
