"""Sharding (ZeRO) — optimizer-state / gradient / parameter partitioning.

TPU-native equivalent of the reference's sharding stack (reference:
fleet/meta_parallel/sharding/dygraph_sharding_optimizer.py:48 stage-1,
:470 V2 stage-2 reduce-scatter; group_sharded_stage3.py:85 ZeRO-3
gather-on-use with flat buffers group_sharded_storage.py). The TPU
formulation: ZeRO is a *sharding annotation*, not a runtime protocol —

- stage 1 (os):    optimizer states laid out Shard(0) over the sharding axis
- stage 2 (os_g):  + gradients arrive reduce-scattered (GSPMD emits
                   reduce-scatter instead of all-reduce when the update
                   consumes sharded grads)
- stage 3 (p_g_os): + params themselves Shard(0) — XLA inserts the
                   all-gather at each use point (gather-on-use) and frees
                   the gathered copy after, which is exactly ZeRO-3's
                   prefetch/release behavior, scheduled by the compiler.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax

from .....core.tensor import Parameter, Tensor

__all__ = ["shard_optimizer_states", "shard_parameters",
           "DygraphShardingOptimizer", "GroupShardedOptimizerStage2",
           "GroupShardedStage2", "GroupShardedStage3"]


def _axis_sharding(mesh, axis_name, ndim, dim=0):
    from ....auto_parallel.placement import Replicate, Shard

    placements = [Replicate()] * mesh.ndim
    placements[mesh.dim_names.index(axis_name)] = Shard(dim)
    return mesh.sharding_for(placements, ndim)


def _shardable(shape, degree, dim=0):
    return len(shape) > 0 and shape[dim] % degree == 0 and degree > 1


def _find_shard_dim(shape, degree):
    """First dimension divisible by the sharding degree, else None.

    The reference pads flat grad/param buffers to the degree
    (group_sharded_storage.py); here tensors stay unflattened and GSPMD
    shards a whole dimension, so the fallback for a non-divisible dim-0
    is another divisible dim — and a WARNING (not silence) when no dim
    qualifies."""
    if degree <= 1:
        return None
    for d, s in enumerate(shape):
        if s >= degree and s % degree == 0:
            return d
    return None


def _warn_unshardable(kind, name, shape, degree):
    import warnings

    warnings.warn(
        f"sharding: {kind} {name!r} shape {tuple(shape)} has no dimension "
        f"divisible by degree {degree}; it stays replicated")


def shard_optimizer_states(optimizer, hcg, axis: str = "sharding"):
    """Stage-1: lay optimizer states out sharded over the axis."""
    mesh = hcg.mesh
    degree = mesh.get_dim_size(axis)
    if degree <= 1:
        return optimizer
    orig_init = optimizer._init_state

    def sharded_init(p):
        st = orig_init(p)
        out = {}
        for k, v in st.items():
            d = _find_shard_dim(v.shape, degree) \
                if hasattr(v, "shape") else None
            if d is not None:
                out[k] = jax.device_put(
                    v, _axis_sharding(mesh, axis, v.ndim, dim=d))
            else:
                if hasattr(v, "shape") and v.ndim > 0:
                    _warn_unshardable("optimizer state", f"{p.name}/{k}",
                                      v.shape, degree)
                out[k] = v
        return out

    optimizer._init_state = sharded_init
    return optimizer


def shard_parameters(layer, hcg, axis: str = "sharding"):
    """Stage-3: params sharded over the axis → gather-on-use by XLA."""
    mesh = hcg.mesh
    degree = mesh.get_dim_size(axis)
    if degree <= 1:
        return layer
    from ....auto_parallel.placement import Replicate, Shard

    for _, sub in layer.named_sublayers(include_self=True):
        for name, p in list(sub._parameters.items()):
            if p is None:
                continue
            if p._dist_attr is not None:
                continue  # already TP-sharded; don't double-shard
            d = _find_shard_dim(p._data.shape, degree)
            if d is not None:
                placements = [Replicate()] * mesh.ndim
                placements[mesh.dim_names.index(axis)] = Shard(d)
                p._rebind(jax.device_put(
                    p._data, mesh.sharding_for(placements, p._data.ndim)))
                p._dist_attr = (mesh, placements)
            elif p._data.ndim > 0:
                _warn_unshardable("parameter", name, p._data.shape, degree)
    return layer


class DygraphShardingOptimizer:
    """Stage-1/2 wrapper (dygraph_sharding_optimizer.py:48/:470)."""

    def __init__(self, optimizer, hcg=None):
        if hcg is None:
            from ... import fleet as _fleet

            hcg = _fleet.get_hybrid_communicate_group()
        self._inner_opt = shard_optimizer_states(optimizer, hcg)
        self._hcg = hcg

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)


def _stage2_annotate(optimizer, hcg, axis: str = "sharding"):
    """Stage-2 = stage-1 + reduce-scattered gradients: sharded optimizer
    states plus a grad-shard annotation consumed by TrainStep._shard_grads
    (the compiled step constrains grads to Shard over the axis, so GSPMD
    emits reduce-scatter instead of all-reduce for the dp grad sync —
    reference: dygraph_sharding_optimizer.py:470 reduce_scatter)."""
    shard_optimizer_states(optimizer, hcg, axis)
    mesh = hcg.mesh
    if mesh.get_dim_size(axis) > 1:
        optimizer._grad_shard = (mesh, axis)
    return optimizer


class GroupShardedOptimizerStage2(DygraphShardingOptimizer):
    """group_sharded_optimizer_stage2.py parity: sharded states + grad
    reduce-scatter annotation."""

    def __init__(self, params=None, optim=None, group=None, hcg=None,
                 **kw):
        optimizer = optim if optim is not None else params
        if hcg is None:
            from ... import fleet as _fleet

            hcg = _fleet.get_hybrid_communicate_group()
        self._inner_opt = _stage2_annotate(optimizer, hcg)
        self._hcg = hcg


class GroupShardedStage2:
    """Gradient-sharded model wrapper (group_sharded_stage2.py): the
    layer passes through; the real stage-2 behavior lives on the
    optimizer annotation (grads reduce-scattered, states sharded)."""

    def __init__(self, layer, optimizer, group=None, hcg=None, **kw):
        if hcg is None:
            from ... import fleet as _fleet

            hcg = _fleet.get_hybrid_communicate_group()
        self._layer = layer
        self._optimizer = _stage2_annotate(optimizer, hcg)

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._layer, item)


class GroupShardedStage3:
    """Param-sharded (ZeRO-3) wrapper (group_sharded_stage3.py:85)."""

    def __init__(self, layer, optimizer=None, group=None, hcg=None,
                 segment_size=2 ** 20, offload=False, **kw):
        if hcg is None:
            from ... import fleet as _fleet

            hcg = _fleet.get_hybrid_communicate_group()
        self._layer = shard_parameters(layer, hcg)
        self._optimizer = optimizer
        if optimizer is not None:
            shard_optimizer_states(optimizer, hcg)

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._layer, item)
