"""TensorParallel model wrapper (reference:
fleet/meta_parallel/tensor_parallel.py — broadcasts non-distributed
params across the mp group at init; with dist tensors the mesh placement
already guarantees consistency, so this wrapper is thin)."""
from __future__ import annotations

from ....nn.layer_base import Layer

__all__ = ["TensorParallel"]


class TensorParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
