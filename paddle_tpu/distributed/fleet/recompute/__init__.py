from .recompute import recompute, recompute_hybrid, recompute_sequential  # noqa: F401
