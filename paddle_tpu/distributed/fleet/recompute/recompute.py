"""Recompute (activation checkpointing).

TPU-native equivalent of the reference's recompute (reference:
fleet/recompute/recompute.py — RecomputeFunction:108 PyLayer with RNG
state replay, recompute:404, recompute_sequential:542; offload variant
recompute_hybrid.py). The mechanism here is ``jax.checkpoint``: the
recomputed region's vjp saves only its inputs and rematerialises forward
during backward — identical memory/compute trade, scheduled by XLA.
RNG replay comes free: the region draws from a fold_in'd key captured at
forward time, so the recompute sees identical dropout masks.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ....core import engine
from ....core.generator import next_rng_key, use_trace_key
from ....core.tensor import Tensor
from ....nn.layer_base import Layer
from ....ops.dispatch import eager_apply

__all__ = ["recompute", "recompute_sequential", "recompute_hybrid"]


def recompute(function: Callable, *args, preserve_rng_state: bool = True,
              use_reentrant: bool = True, policy=None, **kwargs):
    """(recompute.py:404 parity)

    ``policy`` (TPU extension): a ``jax.checkpoint_policies`` saveable
    predicate — e.g. ``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``
    keeps matmul outputs resident and rematerializes only the cheap
    elementwise chains, trading a little HBM for most of the recompute
    FLOPs (the full-remat extra forward is ~33% of the step's math)."""
    layer = function if isinstance(function, Layer) else \
        getattr(function, "__self__", None)
    params = [p for _, p in layer.named_parameters()] if layer is not None \
        else []
    buffers = [b for _, b in layer.named_buffers()] if layer is not None \
        else []

    tensor_args = [a if isinstance(a, Tensor) else Tensor(jnp.asarray(a))
                   for a in args]
    n_args = len(tensor_args)
    key = next_rng_key()  # captured once → deterministic replay

    from ...fleet import fleet  # noqa: F401  (import side effects none)
    from ....jit.static_function import _SwappedState

    n_params = len(params)
    n_fn_outs = []  # set at trace time; output structure is trace-invariant

    def raw(*arrays):
        arg_arrays = arrays[:n_args]
        param_arrays = arrays[n_args: n_args + n_params]
        buffer_arrays = arrays[n_args + n_params:]
        # Buffers are swapped like params (same pattern as
        # static_function._Program) so a buffer-mutating layer (e.g.
        # BatchNorm updating running stats) mutates the swapped trace
        # value, not the live eager buffer; the mutated values are
        # surfaced as extra outputs and rebound after the call.
        with _SwappedState(params + buffers,
                           list(param_arrays) + list(buffer_arrays)), \
                use_trace_key(key), engine.no_grad():
            out = function(*[Tensor(a) for a in arg_arrays], **kwargs)
            new_buffer_arrays = [b._data for b in buffers]
        outs = tuple(o._data for o in out) if isinstance(out, tuple) \
            else (out._data,)
        if not n_fn_outs:
            n_fn_outs.append(len(outs))
        return outs + tuple(new_buffer_arrays)

    ckpt = jax.checkpoint(raw, policy=policy) if policy is not None \
        else jax.checkpoint(raw)
    res = eager_apply("recompute", ckpt, tensor_args + params + buffers,
                      n_outputs=None)
    res = res if isinstance(res, tuple) else (res,)
    n_out = n_fn_outs[0]
    outs, new_bufs = res[:n_out], res[n_out:]
    for b, nb in zip(buffers, new_bufs):
        b._rebind(nb._data)
    return outs if len(outs) > 1 else outs[0]


def recompute_sequential(ctx: dict, functions, *args, **kwargs):
    """(recompute.py:542) — checkpoint a Sequential in segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    preserve = ctx.get("preserve_rng_state", True) if isinstance(ctx, dict) \
        else True
    if isinstance(functions, Layer):
        functions = list(functions)
    n = len(functions)
    seg_size = max(n // max(segments, 1), 1)
    out = args
    i = 0
    while i < n:
        chunk = functions[i: i + seg_size]

        class _Chunk(Layer):
            def __init__(self, layers_):
                super().__init__()
                from ....nn.layer_base import LayerList

                self.ls = LayerList(layers_)

            def forward(self, *xs):
                y = xs if len(xs) > 1 else xs[0]
                for l in self.ls:
                    y = l(*(y if isinstance(y, tuple) else (y,)))
                return y

        out = recompute(_Chunk(chunk),
                        *(out if isinstance(out, tuple) else (out,)),
                        preserve_rng_state=preserve)
        i += seg_size
    return out


def recompute_hybrid(ctx, function, *args, **kwargs):
    """(recompute_hybrid.py) — offload variant; on TPU remat already frees
    HBM so offload reduces to plain recompute."""
    return recompute(function, *args, **kwargs)
