from . import fs  # noqa: F401
from . import sequence_parallel_utils  # noqa: F401
from .fs import FS, HDFSClient, LocalFS  # noqa: F401
