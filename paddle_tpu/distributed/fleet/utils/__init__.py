from . import sequence_parallel_utils  # noqa: F401
