"""Filesystem abstraction: LocalFS + HDFSClient.

TPU-native equivalent of the reference's fleet fs layer (reference:
python/paddle/distributed/fleet/utils/fs.py — an FS interface with a
LocalFS implementation and an HDFSClient shelling out to the hadoop
CLI). LocalFS is fully implemented over the stdlib; HDFSClient keeps
the same surface and drives a ``hadoop fs`` binary when one exists
(zero-egress container ships none — construction raises with guidance
unless the binary is found).
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional, Tuple

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(RuntimeError):
    pass


class FSFileNotExistsError(RuntimeError):
    pass


class FS:
    """(reference fs.py:50) abstract surface."""

    def ls_dir(self, fs_path) -> Tuple[List[str], List[str]]:
        raise NotImplementedError

    def is_file(self, fs_path) -> bool:
        raise NotImplementedError

    def is_dir(self, fs_path) -> bool:
        raise NotImplementedError

    def is_exist(self, fs_path) -> bool:
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self) -> bool:
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path) -> List[str]:
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path=None) -> str:
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem (reference fs.py:113) — same semantics:
    ls_dir returns (dirs, files); mv raises on a missing source when
    ``test_exists`` and on an existing destination unless
    ``overwrite``."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for e in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, e))
             else files).append(e)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def upload(self, local_path, fs_path):
        # local->local copy keeps API parity for code written against
        # a remote FS
        if self.is_dir(local_path):
            shutil.copytree(local_path, fs_path)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            if test_exists:
                raise FSFileNotExistsError(f"{src_path} not found")
            return
        if self.is_exist(dst_path):
            if not overwrite:
                raise FSFileExistsError(f"{dst_path} exists")
            self.delete(dst_path)
        os.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        with open(fs_path, "a"):
            pass

    def cat(self, fs_path=None):
        with open(fs_path) as f:
            return f.read()


class HDFSClient(FS):
    """``hadoop fs`` CLI wrapper (reference fs.py:447). The zero-egress
    image ships no hadoop binary — construction probes for one and
    raises with guidance otherwise, keeping the API importable for
    code paths that select an FS by config."""

    def __init__(self, hadoop_home: Optional[str] = None,
                 configs: Optional[dict] = None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        hadoop_home = hadoop_home or os.environ.get("HADOOP_HOME", "")
        binary = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else shutil.which("hadoop")
        if not binary or not os.path.exists(binary):
            raise RuntimeError(
                "HDFSClient needs a hadoop CLI (set HADOOP_HOME or put "
                "`hadoop` on PATH); this zero-egress image ships none — "
                "use LocalFS, or mount your cluster's client")
        self._binary = binary
        self._configs = configs or {}
        self._timeout_s = max(time_out, 1000) / 1000.0

    def _run(self, *args) -> str:
        cmd = [self._binary, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=self._timeout_s)
        if proc.returncode != 0:
            raise RuntimeError(f"hadoop {' '.join(args)} failed: "
                               f"{proc.stderr[-500:]}")
        return proc.stdout

    def _test(self, flag: str, fs_path) -> bool:
        try:
            self._run("-test", flag, fs_path)
            return True
        except RuntimeError:
            return False
        # TimeoutExpired propagates: a hung cluster must fail LOUDLY —
        # mapping it to False would let mv's guards silently skip or
        # nest moves

    def is_exist(self, fs_path):
        return self._test("-e", fs_path)

    def is_dir(self, fs_path):
        return self._test("-d", fs_path)

    def is_file(self, fs_path):
        # single -test -f round trip (each hadoop call is a JVM start)
        return self._test("-f", fs_path)

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        # honor the FS contract LocalFS implements (typed errors for a
        # missing source / existing destination — a bare `hadoop fs
        # -mv` onto an existing dir silently nests the source into it)
        # with the fewest CLI round-trips (each is a JVM start):
        if test_exists and not self.is_exist(src_path):
            raise FSFileNotExistsError(f"{src_path} not found")
        if overwrite:
            # confirm the source exists BEFORE destroying the
            # destination — otherwise a missing src leaves dst deleted
            # with nothing moved in (ADVICE r4)
            if not (test_exists or self.is_exist(src_path)):
                raise FSFileNotExistsError(f"{src_path} not found")
            self.delete(dst_path)        # -rm -f: no error if absent
        elif self.is_exist(dst_path):
            raise FSFileExistsError(f"{dst_path} exists")
        # (without test_exists a missing source surfaces as the CLI's
        # own RuntimeError rather than LocalFS's silent return)
        self._run("-mv", src_path, dst_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        self._run("-touchz", fs_path)

    def cat(self, fs_path=None):
        return self._run("-cat", fs_path)

    def need_upload_download(self):
        return True
