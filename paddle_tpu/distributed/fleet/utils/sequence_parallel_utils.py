"""Sequence parallelism (Megatron-style SP).

TPU-native equivalent of the reference's SP utils (reference:
fleet/utils/sequence_parallel_utils.py — ScatterOp:85/GatherOp/
AllGatherOp:111/ReduceScatterOp:127 PyLayers;
ColumnSequenceParallelLinear:230, RowSequenceParallelLinear:340). On TPU
these transitions are reshard annotations along the sequence dim over the
mp axis — GSPMD emits the all-gather / reduce-scatter pairs, and because
they're inside the compiled program XLA overlaps them with compute.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn import initializer as I
from ....nn.layer_base import Layer
from ...auto_parallel.api import reshard, shard_tensor
from ...auto_parallel.placement import Replicate, Shard

__all__ = ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks"]


def _mp_mesh():
    from .. import fleet

    hcg = fleet.get_hybrid_communicate_group()
    return hcg.mesh


def _seq_placements(mesh, seq_dim=0, shard=True):
    pls = [Replicate()] * mesh.ndim
    if shard:
        pls[mesh.dim_names.index("mp")] = Shard(seq_dim)
    return pls


class ScatterOp:
    """Split activations along seq over mp (ScatterOp:85). The sequence
    dim convention follows the reference: [s, b, h]."""

    @staticmethod
    def apply(x, axis=0):
        mesh = _mp_mesh()
        t = x if isinstance(x, Tensor) else Tensor(x)
        if t._dist_attr is None:
            t = shard_tensor(t, mesh, [Replicate()] * mesh.ndim)
        return reshard(t, mesh, _seq_placements(mesh, axis))


class GatherOp:
    """Gather seq-sharded activations (inverse of Scatter)."""

    @staticmethod
    def apply(x, axis=0):
        mesh = _mp_mesh()
        t = x if isinstance(x, Tensor) else Tensor(x)
        if t._dist_attr is None:
            return t
        return reshard(t, mesh, [Replicate()] * mesh.ndim)


class AllGatherOp:
    """(AllGatherOp:111) — forward all-gather, backward reduce-scatter;
    the adjoint pair falls out of differentiating the reshard."""

    @staticmethod
    def apply(x):
        return GatherOp.apply(x, axis=0)


class ReduceScatterOp:
    """(ReduceScatterOp:127) — forward reduce-scatter over seq."""

    @staticmethod
    def apply(x):
        return ScatterOp.apply(x, axis=0)


def mark_as_sequence_parallel_parameter(param):
    param.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """(sequence_parallel_utils.py:192) — with dist tensors the SP-param
    grad allreduce is emitted by GSPMD inside the compiled step; nothing to
    hook eagerly."""
    return None


class ColumnSequenceParallelLinear(Layer):
    """(:230) input arrives seq-sharded; all-gather seq → column matmul →
    output feature-sharded."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        mesh = _mp_mesh()
        self._mesh = mesh
        self.gather_output = gather_output
        w = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        pls = [Replicate()] * mesh.ndim
        pls[mesh.dim_names.index("mp")] = Shard(1)
        self.weight = shard_tensor(w, mesh, pls)
        if has_bias or has_bias is None:
            b = self.create_parameter(shape=[out_features], is_bias=True)
            bpl = [Replicate()] * mesh.ndim
            bpl[mesh.dim_names.index("mp")] = Shard(0)
            self.bias = shard_tensor(b, mesh, bpl)
        else:
            self.bias = None

    def forward(self, x):
        x = AllGatherOp.apply(x)
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = reshard(out, self._mesh,
                          [Replicate()] * self._mesh.ndim)
        return out


class RowSequenceParallelLinear(Layer):
    """(:340) row matmul (input feature-sharded) → reduce-scatter over seq."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        mesh = _mp_mesh()
        self._mesh = mesh
        w = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        pls = [Replicate()] * mesh.ndim
        pls[mesh.dim_names.index("mp")] = Shard(0)
        self.weight = shard_tensor(w, mesh, pls)
        if has_bias:
            b = self.create_parameter(shape=[out_features], is_bias=True)
            self.bias = shard_tensor(b, mesh, [Replicate()] * mesh.ndim)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        return ReduceScatterOp.apply(out)
