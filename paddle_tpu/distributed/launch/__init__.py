from .main import launch  # noqa: F401
