"""Distributed launcher CLI.

TPU-native equivalent of the reference's launcher (reference:
python/paddle/distributed/launch/main.py:20 ``launch()``;
controllers/collective.py:22 CollectiveController builds per-rank envs +
log dirs and watches processes; controllers/watcher.py). Usage:

    python -m paddle_tpu.distributed.launch \
        --nproc_per_node 2 --log_dir ./logs train.py --my-arg ...

Sets the PADDLE_* env contract consumed by ``init_parallel_env``
(MASTER_ADDR/PORT, PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
PADDLE_LOCAL_RANK, PADDLE_TRAINER_ENDPOINTS) plus JAX process env. On a
TPU pod each host usually runs ONE process owning its local chips
(jax.distributed), unlike the reference's one-process-per-GPU model —
``--nproc_per_node`` defaults to 1 for that reason but can be raised for
CPU-simulated multi-process tests.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List

__all__ = ["launch", "main"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed training (launch/main.py:20 parity)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--master", default=None,
                   help="host:port of the coordinator (rank-0 host)")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--devices", default=None,
                   help="comma-separated local device ids to expose")
    p.add_argument("--elastic_level", type=int, default=0,
                   help=">0: relaunch failed workers up to "
                        "--max_restarts times (elastic/manager.py parity)")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _spawn(args, global_rank: int, local_rank: int, world: int,
           master: str, endpoints: str) -> subprocess.Popen:
    env = dict(os.environ)
    addr, port = master.rsplit(":", 1)
    env.update({
        "MASTER_ADDR": addr,
        "MASTER_PORT": port,
        "PADDLE_TRAINER_ID": str(global_rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_RANK_IN_NODE": str(local_rank),
        "PADDLE_TRAINER_ENDPOINTS": endpoints,
        "PADDLE_CURRENT_ENDPOINT":
            endpoints.split(",")[global_rank],
    })
    if args.devices:
        env["CUDA_VISIBLE_DEVICES"] = args.devices  # compat no-op on TPU
    stdout = stderr = None
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        logf = open(os.path.join(args.log_dir,
                                 f"workerlog.{global_rank}"), "w")
        stdout = stderr = logf
    cmd = [sys.executable, args.script] + list(args.script_args)
    return subprocess.Popen(cmd, env=env, stdout=stdout, stderr=stderr)


def launch(argv: List[str] = None) -> int:
    """(main.py:20) spawn per-rank workers, watch, propagate failure."""
    args = _parse(argv if argv is not None else sys.argv[1:])
    world = args.nnodes * args.nproc_per_node
    if args.master is None:
        if args.nnodes > 1:
            raise SystemExit("--master host:port is required for "
                             "multi-node launches")
        args.master = f"127.0.0.1:{_free_port()}"
    addr = args.master.rsplit(":", 1)[0]
    base_port = int(args.master.rsplit(":", 1)[1])
    endpoints = ",".join(
        f"{addr}:{base_port + i}" for i in range(world))

    restarts = 0
    while True:
        procs = []
        for local_rank in range(args.nproc_per_node):
            global_rank = args.node_rank * args.nproc_per_node + local_rank
            procs.append(_spawn(args, global_rank, local_rank, world,
                                args.master, endpoints))

        # watcher (controllers/watcher.py parity): poll until all exit or
        # one fails
        rc = 0
        try:
            while procs:
                alive = []
                for p in procs:
                    r = p.poll()
                    if r is None:
                        alive.append(p)
                    elif r != 0:
                        rc = r
                if rc != 0:
                    for p in procs:
                        if p.poll() is None:
                            p.send_signal(signal.SIGTERM)
                    deadline = time.time() + 10
                    for p in procs:
                        try:
                            p.wait(max(0.1, deadline - time.time()))
                        except subprocess.TimeoutExpired:
                            p.kill()
                    break
                procs = alive
                if procs:
                    time.sleep(0.2)
        except KeyboardInterrupt:
            for p in procs:
                p.send_signal(signal.SIGTERM)
            raise
        if rc == 0:
            return 0
        restarts += 1
        # exit codes 101/102 are the elastic-restart REQUEST contract
        # (fleet.elastic ELASTIC_EXIT_CODE / auto-parallel variant,
        # reference manager.py:32) — honor them even without
        # --elastic_level; other failures relaunch only when elastic
        elastic_requested = rc in (101, 102)
        if not elastic_requested and args.elastic_level <= 0:
            return rc
        if restarts > args.max_restarts:
            return rc
        print(f"launch: worker exited rc={rc} "
              f"({'elastic restart requested' if elastic_requested else 'failure'}); "
              f"relaunch {restarts}/{args.max_restarts}", file=sys.stderr)


def main():
    raise SystemExit(launch())


if __name__ == "__main__":
    main()
