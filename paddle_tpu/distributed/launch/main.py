"""Distributed launcher CLI.

TPU-native equivalent of the reference's launcher (reference:
python/paddle/distributed/launch/main.py:20 ``launch()``;
controllers/collective.py:22 CollectiveController builds per-rank envs +
log dirs and watches processes; controllers/watcher.py). Usage:

    python -m paddle_tpu.distributed.launch \
        --nproc_per_node 2 --log_dir ./logs train.py --my-arg ...

Sets the PADDLE_* env contract consumed by ``init_parallel_env``
(MASTER_ADDR/PORT, PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
PADDLE_LOCAL_RANK, PADDLE_TRAINER_ENDPOINTS) plus JAX process env. On a
TPU pod each host usually runs ONE process owning its local chips
(jax.distributed), unlike the reference's one-process-per-GPU model —
``--nproc_per_node`` defaults to 1 for that reason but can be raised for
CPU-simulated multi-process tests.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List

__all__ = ["launch", "main"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed training (launch/main.py:20 parity)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=None,
                   help="this node's rank; omit for arrival-order "
                        "auto-assignment by the built-in master")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--master", default=None,
                   help="host:port of the coordinator (rank-0 host)")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--devices", default=None,
                   help="comma-separated local device ids to expose")
    p.add_argument("--elastic_level", type=int, default=0,
                   help=">0: relaunch failed workers up to "
                        "--max_restarts times (elastic/manager.py parity)")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _spawn(args, global_rank: int, local_rank: int, world: int,
           master: str, endpoints: str,
           generation: int = 0) -> subprocess.Popen:
    env = dict(os.environ)
    addr, port = master.rsplit(":", 1)
    env.update({
        "MASTER_ADDR": addr,
        "MASTER_PORT": port,
        "PADDLE_TRAINER_ID": str(global_rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_RANK_IN_NODE": str(local_rank),
        "PADDLE_TRAINER_ENDPOINTS": endpoints,
        "PADDLE_CURRENT_ENDPOINT":
            endpoints.split(",")[global_rank],
        "PADDLE_RESTART_GENERATION": str(generation),
    })
    if args.devices:
        env["CUDA_VISIBLE_DEVICES"] = args.devices  # compat no-op on TPU
    stdout = stderr = None
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        logf = open(os.path.join(args.log_dir,
                                 f"workerlog.{global_rank}"), "w")
        stdout = stderr = logf
    cmd = [sys.executable, args.script] + list(args.script_args)
    return subprocess.Popen(cmd, env=env, stdout=stdout, stderr=stderr)


def launch(argv: List[str] = None) -> int:
    """(main.py:20) spawn per-rank workers, watch, propagate failure.
    Multi-node runs rendezvous through the built-in KV master
    (controllers/master.py parity — see launch/master.py): pass the
    SAME --master on every node, ranks auto-assign, heartbeats detect
    dead nodes and drive elastic re-rendezvous."""
    args = _parse(argv if argv is not None else sys.argv[1:])
    if args.nnodes > 1:
        return _launch_multinode(args)
    world = args.nproc_per_node
    node_rank = args.node_rank or 0
    if args.master is None:
        args.master = f"127.0.0.1:{_free_port()}"
    addr = args.master.rsplit(":", 1)[0]
    base_port = int(args.master.rsplit(":", 1)[1])
    endpoints = ",".join(
        f"{addr}:{base_port + i}" for i in range(world))

    restarts = 0
    while True:
        procs = []
        for local_rank in range(args.nproc_per_node):
            global_rank = node_rank * args.nproc_per_node + local_rank
            procs.append(_spawn(args, global_rank, local_rank, world,
                                args.master, endpoints))

        # watcher (controllers/watcher.py parity): poll until all exit or
        # one fails
        try:
            rc = _watch(procs)
        except KeyboardInterrupt:
            for p in procs:
                p.send_signal(signal.SIGTERM)
            raise
        if rc == 0:
            return 0
        restarts += 1
        # exit codes 101/102 are the elastic-restart REQUEST contract
        # (fleet.elastic ELASTIC_EXIT_CODE / auto-parallel variant,
        # reference manager.py:32) — honor them even without
        # --elastic_level; other failures relaunch only when elastic
        elastic_requested = rc in (101, 102)
        if not elastic_requested and args.elastic_level <= 0:
            return rc
        if restarts > args.max_restarts:
            return rc
        print(f"launch: worker exited rc={rc} "
              f"({'elastic restart requested' if elastic_requested else 'failure'}); "
              f"relaunch {restarts}/{args.max_restarts}", file=sys.stderr)


def _watch(procs, on_tick=None):
    """Poll workers until all exit (rc 0) or one fails; on failure kill
    the rest. ``on_tick()`` may return a non-None rc to force teardown
    (the dead-peer path). Returns the first non-zero rc (or 0)."""
    rc = 0
    while procs:
        alive = []
        for p in procs:
            r = p.poll()
            if r is None:
                alive.append(p)
            elif r != 0 and rc == 0:
                rc = r
        if rc == 0 and on_tick is not None:
            forced = on_tick()
            if forced is not None:
                rc = forced
        if rc != 0:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            deadline = time.time() + 10
            for p in procs:
                try:
                    p.wait(max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    p.kill()
            return rc
        procs = alive
        if procs:
            time.sleep(0.2)
    return 0


DEAD_PEER_RC = 101  # reuse the elastic-restart contract code


def _launch_multinode(args) -> int:
    """Rendezvous via the built-in master, heartbeat, elastic failover
    (reference: controllers/master.py + fleet elastic manager)."""
    from .master import LaunchMaster

    if args.master is None:
        raise SystemExit(
            "--master host:port is required for multi-node launches "
            "(the SAME address on every node; whichever node can bind "
            "it hosts the built-in KV master)")
    master = LaunchMaster(args.master, args.nnodes)
    generation = master.current_generation()
    requested_rank = args.node_rank if args.node_rank is not None else -1
    world = args.nnodes * args.nproc_per_node
    restarts = 0

    while True:
        from .master import RanksClaimedError

        deadline = time.time() + 180
        while True:
            try:
                node_rank, peers = master.rendezvous(
                    requested_rank, args.nproc_per_node, generation)
                break
            except RanksClaimedError:
                # late joiner (restarted node): the running epoch is
                # full — wait for the survivors to notice the failure
                # and bump, then join the fresh generation
                if time.time() > deadline:
                    raise
                time.sleep(2.0)
                generation = max(generation,
                                 master.current_generation())
        # the node-0 launcher publishes a FRESH coordinator endpoint per
        # generation (the jax coordination service cannot be reused
        # across failovers)
        coord_key = f"g{generation}/coord"
        if node_rank == 0 and not master.store.check(coord_key):
            master.store.set(coord_key,
                             f"{peers[0]['host']}:{_free_port()}")
        coord = master.store.get(coord_key).decode()
        # real per-rank ports (single-node convention: base_port+i per
        # node) so ParallelEnv endpoints are distinct and addressable
        # rather than duplicate host:0 placeholders (ADVICE r4)
        base_port = int(args.master.rsplit(":", 1)[1]) + 1
        endpoints = []
        rank_off = 0  # global offset: two nodes on one host (a
        for nr, peer in enumerate(peers):  # supported topology) must
            for lr in range(peer["nproc"]):  # not reuse ports
                endpoints.append(
                    f"{peer['host']}:{base_port + rank_off + lr}")
            rank_off += peer["nproc"]
        endpoints = ",".join(endpoints)
        master.start_heartbeat(node_rank, generation)

        procs = []
        for local_rank in range(args.nproc_per_node):
            global_rank = node_rank * args.nproc_per_node + local_rank
            procs.append(_spawn(args, global_rank, local_rank, world,
                                coord, endpoints, generation))

        gen = generation

        def dead_check(_last=[0.0]):
            now = time.time()
            if now - _last[0] < 1.0:
                return None
            _last[0] = now
            dead = master.dead_peers(node_rank, gen)
            if dead:
                print(f"launch: node(s) {dead} heartbeat lost "
                      f"(generation {gen}); tearing down for "
                      "re-rendezvous", file=sys.stderr)
                return DEAD_PEER_RC
            return None

        try:
            rc = _watch(procs, on_tick=dead_check)
        except KeyboardInterrupt:
            for p in procs:
                p.send_signal(signal.SIGTERM)
            master.stop_heartbeat()
            raise
        master.stop_heartbeat()
        if rc == 0:
            # tell peers this node FINISHED (stopped beats != death)
            master.mark_done(node_rank, generation)
            return 0
        elastic = rc in (101, 102) or args.elastic_level > 0
        restarts += 1
        if not elastic or restarts > args.max_restarts:
            return rc
        generation = master.bump_generation(generation)
        requested_rank = node_rank  # keep my rank across failovers
        print(f"launch: re-rendezvous at generation {generation} "
              f"({restarts}/{args.max_restarts})", file=sys.stderr)


def main():
    raise SystemExit(launch())


if __name__ == "__main__":
    main()
