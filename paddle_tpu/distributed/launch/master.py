"""Built-in launch master: KV rendezvous + node heartbeats.

TPU-native equivalent of the reference's launch master (reference:
python/paddle/distributed/launch/controllers/master.py — HTTPMaster
over utils/kv_server.py for single-shot rendezvous, ETCDMaster for
heartbeat + peer-failure watching). Here both roles ride the framework's
native C++ TCPStore (core/native/tcp_store.cc):

  - every launcher is given the SAME ``--master host:port``; whichever
    node can bind it hosts the KV server (no separate etcd / hand-wired
    rank-0 bootstrapping), everyone else connects as a client;
  - rendezvous is generation-scoped: nodes register under
    ``g{N}/``-prefixed keys, ranks are assigned by arrival (or honored
    when ``--node_rank`` is pinned), and the assembled peer list is
    what ``_spawn`` turns into the PADDLE_* env contract;
  - each launcher heartbeats ``g{N}/beat/{rank}`` and watches the
    others; a stale peer (launcher died / node lost) triggers the
    elastic path: kill local workers, bump the generation, re-
    rendezvous, respawn — the reference ETCDMaster flow.
"""
from __future__ import annotations

import json
import socket
import threading
import time
from typing import List, Optional, Tuple

__all__ = ["LaunchMaster", "RanksClaimedError"]


class RanksClaimedError(RuntimeError):
    """Every rank of this generation is already claimed — the caller is
    late to a completed rendezvous (typically a restarted launcher that
    read the generation before the survivors bumped it). Refresh the
    generation and retry."""


class LaunchMaster:
    HEARTBEAT_INTERVAL = 1.0

    def __init__(self, endpoint: str, nnodes: int):
        from ...core.native import TCPStore

        self.endpoint = endpoint
        self.nnodes = nnodes
        host, port = endpoint.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.is_server = False
        try:
            # whichever launcher can bind hosts the KV map
            self.store = TCPStore(host="0.0.0.0", port=self.port,
                                  is_master=True)
            self.store.host = host  # clients elsewhere dial the name
            self.is_server = True
        except RuntimeError:
            self.store = TCPStore(host=host, port=self.port,
                                  is_master=False)
        self._beat_stop: Optional[threading.Event] = None

    # ---------------- rendezvous ----------------

    def rendezvous(self, node_rank: int, nproc: int, generation: int,
                   timeout: float = 120.0) -> Tuple[int, List[str]]:
        """Register this node and block until all ``nnodes`` peers of
        this generation are present. Returns (node_rank, node descriptor
        list sorted by rank). node_rank < 0 → assigned by arrival order
        (the reference's job_id-keyed sync_peers)."""
        g = f"g{generation}"
        if node_rank < 0:
            # claim the first free rank (atomic add — a survivor that
            # KEPT its rank across a failover claims it explicitly, so
            # arrival order alone would collide)
            for r in range(self.nnodes):
                if self.store.add(f"{g}/claim/{r}", 1) == 1:
                    node_rank = r
                    break
            else:
                raise RanksClaimedError(
                    f"rendezvous generation {generation}: all "
                    f"{self.nnodes} ranks already claimed")
        elif self.store.add(f"{g}/claim/{node_rank}", 1) != 1:
            raise RuntimeError(
                f"--node_rank {node_rank} is already claimed in "
                f"generation {generation}: two launchers were started "
                "with the same rank (omit --node_rank for arrival-order "
                "assignment)")
        me = json.dumps({"host": _my_host(self.host), "nproc": nproc})
        self.store.set(f"{g}/peers/{node_rank}", me)
        deadline = time.time() + timeout
        while True:
            if all(self.store.check(f"{g}/peers/{r}")
                   for r in range(self.nnodes)):
                break
            if time.time() > deadline:
                raise TimeoutError(
                    f"rendezvous generation {generation}: "
                    f"{self.nnodes} nodes required")
            time.sleep(0.2)
        peers = [json.loads(self.store.get(f"{g}/peers/{r}").decode())
                 for r in range(self.nnodes)]
        return node_rank, peers

    # ---------------- heartbeats ----------------

    def start_heartbeat(self, node_rank: int, generation: int) -> None:
        self.stop_heartbeat()
        stop = threading.Event()
        g = f"g{generation}"

        def beat():
            while not stop.is_set():
                try:
                    self.store.set(f"{g}/beat/{node_rank}",
                                   repr(time.time()))
                except Exception:
                    return  # store gone — launcher is exiting anyway
                stop.wait(self.HEARTBEAT_INTERVAL)

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        self._beat_stop = stop

    def stop_heartbeat(self) -> None:
        if self._beat_stop is not None:
            self._beat_stop.set()
            self._beat_stop = None

    def mark_done(self, node_rank: int, generation: int) -> None:
        """Record a clean exit so peers don't mistake a finished node
        (whose beats stop) for a dead one."""
        try:
            self.store.set(f"g{generation}/done/{node_rank}", b"1")
        except Exception:
            pass  # store host may be the one exiting

    def dead_peers(self, node_rank: int, generation: int,
                   ttl: float = 5.0) -> List[int]:
        """Ranks whose heartbeat VALUE stopped changing for ``ttl``
        seconds of LOCAL time (skew-free: remote timestamps are treated
        as opaque change tokens, never compared to our clock — the
        ETCDMaster fetch_peer_alive diff). A peer that never beat yet
        has grace until its first beat; a peer that marked itself done
        is finished, not dead."""
        g = f"g{generation}"
        if getattr(self, "_beat_seen_gen", None) != generation:
            self._beat_seen = {}
            self._beat_seen_gen = generation
        now = time.monotonic()
        dead = []
        for r in range(self.nnodes):
            if r == node_rank:
                continue
            if not self.store.check(f"{g}/beat/{r}"):
                continue
            if self.store.check(f"{g}/done/{r}"):
                continue
            val = self.store.get(f"{g}/beat/{r}")
            seen = self._beat_seen.get(r)
            if seen is None or seen[0] != val:
                self._beat_seen[r] = (val, now)
                continue
            if now - seen[1] > ttl:
                dead.append(r)
        return dead

    def current_generation(self) -> int:
        """Latest generation (0 when the job never failed over). A
        RESTARTED launcher calls this to join the survivors' epoch."""
        if self.store.check("generation"):
            return int(self.store.get("generation").decode())
        return 0

    def bump_generation(self, current: int) -> int:
        """Advance past a failover of generation ``current``: exactly
        one detector moves the counter (the per-generation bump marker
        makes racing survivors idempotent), everyone returns
        ``current + 1``. Known race (documented, reference ETCDMaster
        has the analogue): a peer restarted BEFORE any survivor
        detected the failure re-joins the stale generation until a
        heartbeat TTL elapses."""
        if self.store.add(f"gen_bump/{current}", 1) == 1:
            self.store.set("generation", str(current + 1))
        return current + 1


def _my_host(master_host: str) -> str:
    if master_host in ("127.0.0.1", "localhost", "0.0.0.0"):
        return "127.0.0.1"
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return socket.gethostname()
