"""Process bootstrap + dygraph DataParallel.

TPU-native equivalent of the reference's parallel bootstrap (reference:
python/paddle/distributed/parallel.py — init_parallel_env:943 builds
TCPStore + default NCCL group; DataParallel:202 with EagerReducer bucketed
allreduce, reducer.cc). Here bootstrap = ``jax.distributed.initialize``
(the coordinator service is JAX's TCPStore equivalent); the default group
maps onto the full device set. DataParallel syncs grads at backward end
with bucketed host-collectives in the multi-process case; in the compiled
path (TrainStep over a dp mesh axis) GSPMD inserts the gradient psum and
the wrapper is transparent.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import Layer
from . import env as _env
from .communication.collectives import ReduceOp, all_reduce
from .communication.group import Group, _get_default_group, _set_default_group

__all__ = ["init_parallel_env", "DataParallel", "get_rank", "get_world_size",
           "is_initialized"]

get_rank = _env.get_rank
get_world_size = _env.get_world_size


def is_initialized() -> bool:
    return _env.is_initialized()


def init_parallel_env(*args, **kwargs) -> Group:
    """Initialize the distributed context (parallel.py:943 parity).

    Env contract matches the reference launcher: MASTER_ADDR/MASTER_PORT,
    PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM. With >1 processes this calls
    ``jax.distributed.initialize`` (coordinator = rank 0, the TCPStore
    equivalent at tcp_store.h:121); single process is a no-op that still
    registers the default group over local devices.
    """
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if world > 1 and not _env.is_initialized():
        addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
        port = os.environ.get("MASTER_PORT", "8701")
        jax.distributed.initialize(
            coordinator_address=f"{addr}:{port}",
            num_processes=world, process_id=rank)
    _env._mark_initialized()
    # fleet-observability stamp: every rank's stats snapshot carries its
    # coordinates as gauges, so merged snapshots (tools/trace_merge.py)
    # show the world shape even before any collective runs
    from ..profiler import stats as _stats

    _stats.set_gauge("dist.process_index", _env.get_rank())
    _stats.set_gauge("dist.process_count", _env.get_world_size())
    g = Group(rank, 0, list(range(max(world, 1))), "default")
    _set_default_group(g)
    return g


class DataParallel(Layer):
    """Dygraph data parallel (parallel.py:202).

    Gradient sync happens once per backward at the last grad hook — grads
    are flattened into fused buckets (EagerReducer's bucketing,
    reducer.cc) and all-reduced; `no_sync` defers sync for gradient
    accumulation. With one process (TPU SPMD style), sync is a no-op and
    parallelism comes from the compiled step over the dp mesh axis.
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size: int = 25,
                 last_comm_buffer_size: int = 1, find_unused_parameters=False,
                 group: Group = None, **kw):
        super().__init__()
        self._layers = layers
        self.group = group or _get_default_group()
        self.comm_buffer_size_mb = comm_buffer_size
        self.find_unused_parameters = find_unused_parameters
        self._grads_synced = False
        self._sync_enabled = True
        self._hooked = []
        if self.group.nranks > 1:
            self._register_hooks()

    # ---- reference API ----
    @property
    def _sublayer(self):
        return self._layers

    def forward(self, *inputs, **kwargs):
        self._grads_synced = False
        return self._layers(*inputs, **kwargs)

    def no_sync(self):
        import contextlib

        dp = self

        @contextlib.contextmanager
        def ctx():
            prev = dp._sync_enabled
            dp._sync_enabled = False
            try:
                yield
            finally:
                dp._sync_enabled = prev

        return ctx()

    def scale_loss(self, loss):
        return loss

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    # ---- grad sync (EagerReducer equivalent) ----
    def _register_hooks(self):
        from ..core.engine import register_backward_final_hook

        self._tracked = [p for p in self._layers.parameters()
                         if not p.stop_gradient]
        self._needs_sync = False
        self.register_forward_pre_hook(
            lambda l, i: setattr(self, "_needs_sync", True))

        def on_backward_done():
            # fires at the end of every backward sweep — robust to unused
            # parameters (find_unused_parameters is implicit: only params
            # that actually received grads participate)
            if self._needs_sync and self._sync_enabled:
                self._needs_sync = False
                self._sync_all_grads()

        self._bf_hook = register_backward_final_hook(on_backward_done)

    def _sync_all_grads(self):
        """Bucketed allreduce of all grads (fused flat buffers,
        reducer.cc / group_sharded_storage.py pattern)."""
        params = [p for p in self._tracked if p.grad is not None]
        if not params:
            return
        nranks = self.group.nranks
        flat = jnp.concatenate([p.grad._data.reshape(-1).astype(jnp.float32)
                                for p in params])
        t = Tensor(flat)
        all_reduce(t, op=ReduceOp.SUM, group=self.group)
        flat = t._data / nranks
        offset = 0
        for p in params:
            n = p.grad.size
            p.grad._rebind(flat[offset:offset + n].reshape(
                p.grad._data.shape).astype(p.grad._data.dtype))
            offset += n

    def sync_params_buffers(self):
        from .communication.collectives import broadcast

        # src is a GLOBAL rank (reference broadcast.py: "source rank in
        # global view") — use the group's first member, not literal 0
        src = (self.group._ranks[0]
               if getattr(self.group, "_ranks", None) else 0)
        for p in self._layers.parameters():
            broadcast(p, src=src, group=self.group)
