"""Parameter-server surface — deliberately not rebuilt (SURVEY §7.3).

The reference's brpc parameter server (reference:
paddle/fluid/distributed/ps — BrpcPsClient/Server, sparse tables,
GeoSGD; python/paddle/distributed/ps TheOnePSRuntime) targets CPU
recsys clusters; on TPU the same workloads run SPMD with sharded
embedding tables. The public entry points exist and raise with that
guidance so reference code fails loudly, not mysteriously.
"""
from __future__ import annotations

__all__ = ["TheOnePSRuntime", "DistributedInfer", "PsProgramBuilder"]

_MSG = ("the parameter-server stack is not part of the TPU build "
        "(SURVEY §7.3): brpc PS targets CPU recsys clusters; use SPMD "
        "sharded embeddings (fleet.layers.mpu.VocabParallelEmbedding / "
        "distributed.shard_tensor) instead")


class TheOnePSRuntime:
    """reference: python/paddle/distributed/ps/the_one_ps.py."""

    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)


class DistributedInfer:
    """reference: python/paddle/distributed/ps/utils/ps_infer_utils."""

    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)


class PsProgramBuilder:
    """reference: python/paddle/distributed/ps/utils/ps_program_builder."""

    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)
