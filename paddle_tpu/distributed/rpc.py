"""paddle.distributed.rpc — minimal RPC over the coordination service.

TPU-native equivalent of the reference's brpc-backed RPC (reference:
python/paddle/distributed/rpc/rpc.py — init_rpc, rpc_sync, rpc_async,
shutdown, get_worker_info; C++ paddle/fluid/distributed/rpc). The
transport here is the JAX coordination-service KV store (the TCPStore
equivalent): each worker owns an ordered inbox (a KV counter hands out
slots), a daemon thread executes incoming pickled calls, and responses
land on per-call keys. Control-plane scale by design — the data plane
is XLA collectives.
"""
from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]

_TIMEOUT_MS = 120_000
_state: Dict[str, Any] = {"inited": False}


class WorkerInfo:
    """(reference rpc.py WorkerInfo) name/rank/ip/port — transport is
    the coordinator, so ip/port are informational."""

    def __init__(self, name: str, rank: int, ip: str = "", port: int = 0):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return f"WorkerInfo(name={self.name}, rank={self.rank})"


def _client():
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "rpc needs jax.distributed initialized "
            "(init_parallel_env / init_rpc with master_endpoint)")
    return client


class _Future:
    """(reference rpc_async return) .wait() joins the response key."""

    def __init__(self, key: str, timeout_ms: int = _TIMEOUT_MS):
        self._key = key
        self._timeout_ms = timeout_ms
        self._done = False
        self._value = None
        self._error = None

    def wait(self, timeout_ms: Optional[int] = None):
        if self._done:
            if self._error is not None:  # re-raise on every wait
                raise RuntimeError(self._error)
            return self._value
        blob = _client().blocking_key_value_get_bytes(
            self._key,
            timeout_ms if timeout_ms is not None else self._timeout_ms)
        _client().key_value_delete(self._key)
        ok, payload = pickle.loads(blob)
        self._done = True
        if not ok:
            self._error = f"rpc remote exception: {payload}"
            raise RuntimeError(self._error)
        self._value = payload
        return self._value


def _inbox_loop(rank: int, start_slot: int):
    client = _client()
    slot = start_slot
    while True:
        try:
            blob = client.blocking_key_value_get_bytes(
                f"paddle_tpu/rpc/req/{rank}/{slot}", 3_600_000)
        except Exception:
            if _state.get("stopping"):
                return
            continue  # retry the SAME slot — skipping would orphan it
        client.key_value_delete(f"paddle_tpu/rpc/req/{rank}/{slot}")
        # persist consumption progress so a re-init resumes exactly
        # after the last handled slot (requests sent while the worker
        # was down still get served — no orphaned slots)
        try:
            client.key_value_delete(f"paddle_tpu/rpc/consumed/{rank}")
        except Exception:
            pass
        client.key_value_set(f"paddle_tpu/rpc/consumed/{rank}",
                             str(slot))
        slot += 1
        req = pickle.loads(blob)
        if req.get("op") == "__shutdown__":
            return
        fn, args, kwargs, resp_key = (req["fn"], req["args"],
                                      req["kwargs"], req["resp"])
        try:
            result = (True, fn(*args, **(kwargs or {})))
        except Exception as e:  # ship the error back, don't kill the loop
            result = (False, repr(e))
        client.key_value_set_bytes(resp_key,
                                   pickle.dumps(result, protocol=4))


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """(reference rpc.py init_rpc) Join the RPC group under ``name``."""
    import jax

    from . import parallel as _par

    if _state.get("inited"):
        raise RuntimeError(
            "rpc already initialized in this process; call shutdown() "
            "first (a second inbox thread would double-execute requests)")
    try:
        _client()
    except RuntimeError:
        _par.init_parallel_env()
    my_rank = jax.process_index() if rank is None else rank
    client = _client()
    try:  # re-init: the name key persists in the coordinator
        client.key_value_delete(f"paddle_tpu/rpc/name/{my_rank}")
    except Exception:
        pass
    client.key_value_set(f"paddle_tpu/rpc/name/{my_rank}", name)
    # resume after the last slot the previous inbox consumed (persisted
    # by the loop): slots written while the worker was down are still
    # pending and get served; nothing is orphaned across re-init
    try:
        consumed = int(client.blocking_key_value_get(
            f"paddle_tpu/rpc/consumed/{my_rank}", 1000))
    except Exception:
        consumed = 0
    start = consumed + 1
    _state.update(inited=True, name=name, rank=my_rank,
                  world_size=world_size or jax.process_count(),
                  stopping=False)
    t = threading.Thread(target=_inbox_loop, args=(my_rank, start),
                         daemon=True, name="paddle-rpc-inbox")
    t.start()
    _state["thread"] = t
    # wait until every peer registered (the reference barriers too),
    # caching the immutable name->rank registry for _resolve
    names = {}
    for r in range(_state["world_size"]):
        names[client.blocking_key_value_get(
            f"paddle_tpu/rpc/name/{r}", _TIMEOUT_MS)] = r
    _state["names"] = names


def _resolve(to) -> int:
    if isinstance(to, int):
        return to
    # names are immutable after the init barrier — resolved from the
    # cached registry, no KV round-trips per call
    names = _state.get("names", {})
    if to in names:
        return names[to]
    raise ValueError(f"unknown rpc worker {to!r}")


def rpc_async(to, fn, args=None, kwargs=None,
              timeout=_TIMEOUT_MS / 1000) -> _Future:
    """(reference rpc.py rpc_async) Returns a Future honoring
    ``timeout`` (seconds) in its wait()."""
    if not _state.get("inited"):
        raise RuntimeError("call init_rpc first")
    client = _client()
    dst = _resolve(to)
    me = _state["rank"]
    slot = client.key_value_increment(f"paddle_tpu/rpc/inbox/{dst}", 1)
    resp_key = f"paddle_tpu/rpc/resp/{me}/{dst}/{slot}"
    payload = pickle.dumps(
        {"fn": fn, "args": tuple(args or ()), "kwargs": kwargs,
         "resp": resp_key}, protocol=4)
    client.key_value_set_bytes(f"paddle_tpu/rpc/req/{dst}/{slot}",
                               payload)
    return _Future(resp_key, timeout_ms=int(timeout * 1000))


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_TIMEOUT_MS / 1000):
    """(reference rpc.py rpc_sync)"""
    return rpc_async(to, fn, args, kwargs).wait(int(timeout * 1000))


def get_worker_info(name_or_rank) -> WorkerInfo:
    for info in get_all_worker_infos():
        if info.name == name_or_rank or info.rank == name_or_rank:
            return info
    raise ValueError(f"unknown worker {name_or_rank!r}")


def get_all_worker_infos() -> List[WorkerInfo]:
    names = _state.get("names")
    if names:  # immutable post-init registry
        return [WorkerInfo(n, r) for n, r in sorted(names.items(),
                                                    key=lambda kv: kv[1])]
    client = _client()
    out = []
    for r in range(_state.get("world_size", 0)):
        try:
            name = client.blocking_key_value_get(
                f"paddle_tpu/rpc/name/{r}", 1000)
        except Exception:
            continue
        out.append(WorkerInfo(name, r))
    return out


def shutdown():
    """(reference rpc.py shutdown) Drain own inbox thread; peers stop
    via their own shutdown calls (graceful barrier-free teardown)."""
    if not _state.get("inited"):
        return
    _state["stopping"] = True
    client = _client()
    me = _state["rank"]
    slot = client.key_value_increment(f"paddle_tpu/rpc/inbox/{me}", 1)
    client.key_value_set_bytes(
        f"paddle_tpu/rpc/req/{me}/{slot}",
        pickle.dumps({"op": "__shutdown__"}, protocol=4))
    t = _state.get("thread")
    if t is not None:
        t.join(timeout=10)
    _state["inited"] = False
