"""Tensor-parallel serving: the ``mp`` mesh axis for the decode stack.

TPU-native equivalent of the reference's multi-rank fused-transformer
serving (reference: ``fused_multi_transformer_op.cu:220,529`` — one
``ring_id`` allreduce after each row-parallel matmul — driven by the
multi-rank engine ``dist_model.cc:172``). Here the sharding is GSPMD
``shard_map`` over a named ``mp`` axis:

- **column-parallel** QKV and FFN1 (``[K, N/mp]`` shards — attention
  heads partition naturally with the QKV columns),
- **row-parallel** O-proj and FFN2 (``[K/mp, N]`` shards) whose partial
  sums meet in exactly ONE ``psum`` per projection pair — two per
  layer, the same two allreduce points as the reference; the sequential
  pre-LN math admits no fewer without changing the model,
- the **paged KV pool sharded by kv-head** (page tables are host-side
  ints and stay replicated, so the paged-pool bookkeeping — prefix
  cache, refcounts, preemption — is untouched by TP).

GQA small-kv fallback: when ``mp`` does not divide ``num_kv_heads`` but
``num_kv_heads`` divides ``mp``, each kv head is REPLICATED across
``mp // num_kv_heads`` adjacent shards (each shard stores one kv head
and computes that head's K/V redundantly); its query heads still
partition, so weight/KV traffic stays ~1/mp per chip. Any other
combination is a configuration error and raises early with the exact
divisibility constraint.

Weights are sharded AT LOAD: ``TPContext.shard_stack`` rearranges the
stacked host arrays so each shard's block is contiguous (only the QKV
stack needs a column gather — its q/k/v regions interleave per shard)
and ``device_put``s them under a ``NamedSharding`` — no chip ever
materializes the full stack.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

__all__ = ["split_kv_heads", "serving_mesh", "TPContext",
           "shard_map_fn", "axis_extent", "ring_chunk_reduce",
           "ring_reduce", "reduce_over_axis", "ring_census",
           "resolve_overlap"]


def shard_map_fn():
    """shard_map across jax versions (jax >= 0.7 promotes it out of
    experimental; 0.4.x only has the experimental home)."""
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
    return sm


def split_kv_heads(num_kv_heads: int, mp: int):
    """Per-shard kv-head layout for an ``mp``-way tensor-parallel pool.

    Returns ``(kv_heads_per_shard, kv_replication)``:

    - ``num_kv_heads % mp == 0`` → each shard owns a contiguous block of
      ``num_kv_heads // mp`` heads (``kv_replication == 1``);
    - ``mp % num_kv_heads == 0`` (GQA small-kv) → each kv head is
      replicated over ``mp // num_kv_heads`` adjacent shards, one head
      per shard (shard ``s`` holds head ``s // kv_replication``);
    - anything else raises with the exact constraint (a silent shape
      crash deep inside the pool scatter would be undebuggable).
    """
    mp = int(mp)
    num_kv_heads = int(num_kv_heads)
    if mp <= 1:
        return num_kv_heads, 1
    if num_kv_heads % mp == 0:
        return num_kv_heads // mp, 1
    if mp % num_kv_heads == 0:
        return 1, mp // num_kv_heads
    raise ValueError(
        f"num_kv_heads={num_kv_heads} is not shardable over "
        f"mp_degree={mp}: tensor-parallel serving needs "
        f"num_kv_heads % mp == 0 (kv-head sharding) or "
        f"mp % num_kv_heads == 0 (kv-head replication, the GQA "
        f"small-kv fallback); pick an mp degree from the divisors/"
        f"multiples of {num_kv_heads}")


def serving_mesh(mp_degree: int, devices=None, axis: str = "mp"):
    """A 1-D jax Mesh over the first ``mp_degree`` devices (or the
    given ones) with the serving ``mp`` axis name."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    mp_degree = int(mp_degree)
    if len(devices) < mp_degree:
        raise ValueError(
            f"mp_degree={mp_degree} needs {mp_degree} devices, "
            f"have {len(devices)}")
    return Mesh(np.array(devices[:mp_degree]), (axis,))


#: stacked-weight name -> sharding layout kind. ``col3`` shards the
#: output (last) axis of [L, K, N]; ``row3`` shards the contraction
#: axis; ``col2`` shards per-output vectors [L, N]; ``rep`` replicates
#: (LN params and the row-parallel biases/scales, which apply to the
#: FULL output and are added once, after the psum). ``ep4``/``ep3``
#: shard the EXPERT axis (dim 1) of the MoE bank over the ``ep`` mesh
#: axis — each chip streams only its 1/ep expert slice; the gate stays
#: replicated (every shard routes its own token block).
_STACK_LAYOUT = {
    "qkv_weight": "col3", "qkv_bias": "col2", "qkv_scale": "col2",
    "ffn1_weight": "col3", "ffn1_bias": "col2", "ffn1_scale": "col2",
    "out_weight": "row3", "ffn2_weight": "row3",
    "gate_weight": "rep",
    "moe_w1": "ep4", "moe_b1": "ep3",
    "moe_w2": "ep4", "moe_b2": "ep3",
}

#: LoRA adapter-bank operand -> layout (serving/adapters.py, banks
#: ``{proj}_a [L, S, K, R]`` / ``{proj}_b [L, S, R, N]``). The delta
#: composes with the base shards WITHOUT new collectives: column-
#: parallel projections (qkv, ffn1) replicate A and column-split B
#: (the delta's output columns shard exactly like the base output);
#: row-parallel projections (out, ffn2) row-split A along the base
#: contraction shards and replicate B (``x·A = Σ_s x_s·A_s``, so each
#: shard's delta partial joins the base partial BEFORE the layer's
#: existing psum — still exactly 2 psums/layer).
_ADAPTER_LAYOUT = {
    "qkv_a": "rep", "qkv_b": "col_b",
    "ffn1_a": "rep", "ffn1_b": "col_b",
    "out_a": "row_a", "out_b": "rep",
    "ffn2_a": "row_a", "ffn2_b": "rep",
}


@dataclasses.dataclass(frozen=True)
class TPContext:
    """Resolved tensor/expert-parallel geometry for one serving engine.

    ``heads_per_shard`` / ``kv_heads_per_shard`` are what the per-shard
    transformer view computes with; ``kv_replication`` > 1 marks the
    GQA fallback (shard ``s`` holds kv head ``s // kv_replication``).
    ``ep`` > 1 marks expert parallelism (ISSUE 15): the MoE expert
    bank shards 1/ep per chip over the ``ep_axis`` mesh axis and the
    MoE FFN's dispatch/combine run as the two ``lax.all_to_all`` of
    the EP exchange inside the same shard_map the ``mp`` path uses.
    """

    mesh: Any               # jax.sharding.Mesh with the mp and/or ep axis
    axis: str               # tensor-parallel mesh axis name ("mp")
    mp: int
    num_heads: int          # global query heads
    num_kv_heads: int       # global kv heads
    head_dim: int
    heads_per_shard: int
    kv_heads_per_shard: int
    kv_replication: int
    ep: int = 1             # expert-parallel degree
    ep_axis: str = "ep"     # expert-parallel mesh axis name

    @classmethod
    def create(cls, num_heads: int, num_kv_heads: int, head_dim: int,
               mp_degree: Optional[int] = None, mesh=None,
               axis: str = "mp", ep_degree: Optional[int] = None,
               ep_axis: str = "ep") -> Optional["TPContext"]:
        """Resolve engine kwargs into a context (None = single-chip).

        ``mesh`` may be a jax Mesh or anything with ``.jax_mesh()``
        (e.g. a ProcessMesh); it must carry an ``mp``- and/or
        ``ep``-named axis. With only degrees given, a mesh over the
        first ``ep*mp`` devices is built (``(ep, mp)`` axes when both
        exceed 1).
        """
        mp_req = None if mp_degree is None else int(mp_degree)
        ep_req = None if ep_degree is None else int(ep_degree)
        if mesh is None and (mp_req or 1) <= 1 and (ep_req or 1) <= 1:
            return None
        if mesh is not None and hasattr(mesh, "jax_mesh"):
            mesh = mesh.jax_mesh()
        if mesh is None:
            import numpy as np

            import jax
            from jax.sharding import Mesh

            mp_n, ep_n = mp_req or 1, ep_req or 1
            if ep_n > 1 and mp_n > 1:
                devices = jax.devices()
                if len(devices) < ep_n * mp_n:
                    raise ValueError(
                        f"ep{ep_n} x mp{mp_n} needs {ep_n * mp_n} "
                        f"devices, have {len(devices)}")
                mesh = Mesh(np.array(devices[:ep_n * mp_n])
                            .reshape(ep_n, mp_n), (ep_axis, axis))
            elif ep_n > 1:
                mesh = serving_mesh(ep_n, axis=ep_axis)
            else:
                mesh = serving_mesh(mp_n, axis=axis)
        names = tuple(mesh.axis_names)
        if axis not in names and ep_axis not in names:
            raise ValueError(
                f"tensor/expert-parallel mesh must carry an {axis!r} "
                f"and/or {ep_axis!r} axis, got axes {names}")
        mp = int(mesh.shape[axis]) if axis in names else 1
        ep = int(mesh.shape[ep_axis]) if ep_axis in names else 1
        if mp_req is not None and mp_req != mp:
            raise ValueError(
                f"mp_degree={mp_req} disagrees with the mesh's "
                f"{axis!r} extent {mp}")
        if ep_req is not None and ep_req != ep:
            raise ValueError(
                f"ep_degree={ep_req} disagrees with the mesh's "
                f"{ep_axis!r} extent {ep}")
        if mp <= 1 and ep <= 1:
            return None
        if mp > 1 and num_heads % mp != 0:
            raise ValueError(
                f"num_heads={num_heads} must divide evenly over "
                f"mp_degree={mp} (query heads partition with the QKV "
                f"columns)")
        kvs, repl = split_kv_heads(num_kv_heads, mp)
        return cls(mesh=mesh, axis=axis, mp=mp, num_heads=num_heads,
                   num_kv_heads=num_kv_heads, head_dim=head_dim,
                   heads_per_shard=num_heads // mp,
                   kv_heads_per_shard=kvs, kv_replication=repl,
                   ep=ep, ep_axis=ep_axis)

    # ---------------- specs ----------------

    @property
    def kv_pool_heads(self) -> int:
        """GLOBAL kv-head extent of the sharded pool array: the
        original head count when sharded, ``mp`` (one replicated head
        per shard) in the GQA fallback."""
        return self.kv_heads_per_shard * self.mp

    def pspec(self, *parts):
        from jax.sharding import PartitionSpec

        return PartitionSpec(*parts)

    def sharding(self, *parts):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self.pspec(*parts))

    def kv_spec(self):
        """PartitionSpec of a pool side [L*P, kv_heads, page, hd]:
        kv-head-sharded over ``mp``; replicated on an ep-only mesh
        (EP shards the EXPERT bank — every shard attends its own token
        block against the same replicated pool)."""
        if self.mp <= 1:
            return self.pspec()
        return self.pspec(None, self.axis, None, None)

    def stack_spec(self, name: str):
        """PartitionSpec for one stacked-weight entry (shard_map
        in_spec / device placement)."""
        kind = _STACK_LAYOUT.get(name, "rep")
        if kind == "col3" and self.mp > 1:
            return self.pspec(None, None, self.axis)
        if kind == "row3" and self.mp > 1:
            return self.pspec(None, self.axis, None)
        if kind == "col2" and self.mp > 1:
            return self.pspec(None, self.axis)
        if kind == "ep4" and self.ep > 1:
            return self.pspec(None, self.ep_axis, None, None)
        if kind == "ep3" and self.ep > 1:
            return self.pspec(None, self.ep_axis, None)
        return self.pspec()

    def adapter_spec(self, name: str):
        """PartitionSpec for one LoRA adapter-bank operand
        (``_ADAPTER_LAYOUT``): B of column-parallel projections splits
        its output columns [L, S, R, N/mp], A of row-parallel ones
        splits its contraction rows [L, S, K/mp, R], everything else
        replicates."""
        kind = _ADAPTER_LAYOUT.get(name, "rep")
        if kind == "col_b" and self.mp > 1:
            return self.pspec(None, None, None, self.axis)
        if kind == "row_a" and self.mp > 1:
            return self.pspec(None, None, self.axis, None)
        return self.pspec()

    def replicate(self, arr):
        """device_put an operand replicated over the mesh (mixing
        single-device-committed arrays with mesh-sharded ones in one
        jit call is an error; replicating once at engine init also
        avoids a per-call host transfer)."""
        import jax

        return jax.device_put(arr, self.sharding())

    # ---------------- weight rearrangement ----------------

    def qkv_col_index(self):
        """Column gather index making each shard's QKV block contiguous.

        The stacked QKV output axis is ``[q0..qH-1, k0..k{nkv}-1,
        v0..]`` (head-major, ``head_dim`` wide each); shard ``s`` needs
        ``[q of its heads, k of its kv heads, v of its kv heads]``
        contiguous so a plain even split of the LAST axis is the shard
        layout. In the GQA fallback the kv columns are DUPLICATED per
        replica shard, so the rearranged width grows to
        ``mp * (heads_per_shard + 2) * head_dim``.
        """
        import numpy as np

        hd = self.head_dim
        H, nkv = self.num_heads, self.num_kv_heads
        Hs, kvs = self.heads_per_shard, self.kv_heads_per_shard
        within = np.arange(hd)
        cols = []
        for s in range(self.mp):
            qh = np.arange(s * Hs, (s + 1) * Hs)
            if self.kv_replication == 1:
                kvh = np.arange(s * kvs, (s + 1) * kvs)
            else:
                kvh = np.array([s // self.kv_replication])
            cols.append((qh[:, None] * hd + within).ravel())
            cols.append((H * hd) + (kvh[:, None] * hd + within).ravel())
            cols.append(((H + nkv) * hd)
                        + (kvh[:, None] * hd + within).ravel())
        return np.concatenate(cols)

    def shard_stack(self, weights: dict) -> dict:
        """Per-shard stacked weights, sharded AT LOAD: rearrange on the
        host (only ``qkv_*`` needs the column gather) and ``device_put``
        each stack under its NamedSharding — every chip receives only
        its ``[K, N/mp]`` / ``[K/mp, N]`` slice, never the full stack.
        """
        import numpy as np

        import jax

        qkv_idx = None
        out = {}
        for name, arr in weights.items():
            a = np.asarray(arr)
            if name.startswith("qkv_") and self.mp > 1:
                if qkv_idx is None:
                    qkv_idx = self.qkv_col_index()
                a = np.take(a, qkv_idx, axis=-1)
            out[name] = jax.device_put(
                a, self.sharding(*self.stack_spec(name)))
        return out


# ---------------- collective overlap: ring reduction (ISSUE 19) ----------------

def axis_extent(axis_name) -> int:
    """Static extent of a named mesh axis at trace time (``psum`` of a
    Python literal folds to the axis size without emitting a
    collective — the jax idiom for a shard_map body that must branch
    on its own parallelism degree)."""
    import jax

    return int(jax.lax.psum(1, axis_name))


def ring_chunk_reduce(chunk, axis_name, size: int):
    """All-reduce ONE column chunk of a row-parallel partial around the
    ring: ``size - 1`` ``ppermute`` steps circulate every shard's
    partial; the shard then re-orders the collected partials into
    GLOBAL rank order and sums them left-to-right, so every shard
    produces the bitwise-identical result (a rank-local accumulation
    order would let replicas drift apart one ulp at a time).

    Each step depends only on THIS chunk's partial, so XLA's async
    collective-permute scheduler is free to run it under the next
    chunk's GEMM — the overlap ``stream_linear(overlap="ring")``
    pipelines for.
    """
    import jax
    import jax.numpy as jnp

    if size == 1:
        return chunk
    perm = [(i, (i + 1) % size) for i in range(size)]
    vals = [chunk]
    recv = chunk
    for _ in range(size - 1):
        recv = jax.lax.ppermute(recv, axis_name, perm)
        vals.append(recv)
    # vals[t] holds shard (rank - t) % size's partial; re-index so
    # position j holds shard j's partial, same on every member
    idx = jax.lax.axis_index(axis_name)
    stacked = jnp.stack(vals)
    order = (idx - jnp.arange(size, dtype=idx.dtype)) % size
    ordered = jnp.take(stacked, order, axis=0)
    acc = ordered[0]
    for j in range(1, size):
        acc = acc + ordered[j]
    return acc


def ring_reduce(part, axis_name, size: Optional[int] = None):
    """Software-pipelined replacement for ``jax.lax.psum(part, axis)``
    on a row-parallel partial: the last dim splits into ``size`` column
    chunks and each chunk all-reduces independently via
    ``ring_chunk_reduce`` — ``size * (size - 1)`` ``ppermute`` steps
    total, none of which blocks the others, where the single psum
    serialized the whole reduction behind the slowest shard."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    if size is None:
        size = axis_extent(axis_name)
    if size == 1:
        return part
    n = part.shape[-1]
    bounds = np.linspace(0, n, size + 1).astype(int)
    chunks = [
        ring_chunk_reduce(
            jax.lax.slice_in_dim(part, int(lo), int(hi), axis=-1),
            axis_name, size)
        for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo]
    return jnp.concatenate(chunks, axis=-1) if len(chunks) > 1 \
        else chunks[0]


def reduce_over_axis(part, axis_name, overlap: str = "psum"):
    """The row-parallel reduction seam with the ``overlap`` knob:
    ``"psum"`` is the single blocking all-reduce (the bitwise/census
    reference), ``"ring"`` the chunked ``ppermute`` pipeline. An axis
    of extent 1 (a single-shard TP view) skips the collective entirely
    at trace time — the program census must not carry a no-op psum."""
    import jax

    from ..profiler import stats as _stats

    size = axis_extent(axis_name)
    if size == 1:
        return part
    if overlap == "ring":
        _stats.counter("dist.overlap_ring_reduces").inc()
        _stats.gauge("dist.overlap_ring_phases").set(
            float(size * (size - 1)))
        return ring_reduce(part, axis_name, size)
    if overlap != "psum":
        raise ValueError(
            f"overlap={overlap!r}: expected 'ring' or 'psum'")
    return jax.lax.psum(part, axis_name)


def ring_census(axis_name, size: int, reductions: int = 1):
    """The EXACT collective sequence ``reductions`` ring reductions
    trace to — ``(prim, axes)`` pairs in ``trace_census`` format — for
    census pins: ``size * (size - 1)`` ppermutes per reduction, zero
    psums."""
    step = ("ppermute", str((axis_name,)))
    return [step] * (size * (size - 1)) * reductions


def resolve_overlap(overlap: Optional[str]) -> str:
    """The effective TP overlap mode: an explicit knob wins, else
    ``FLAGS_tp_overlap``."""
    if overlap is not None:
        return overlap
    from ..core.flags import flag

    return flag("tp_overlap")
