"""paddle_tpu.distribution — probability distributions.

TPU-native equivalent of the reference's distribution package (reference:
python/paddle/distribution — Distribution base distribution/distribution.py,
Normal normal.py, Uniform uniform.py, Categorical categorical.py,
Bernoulli bernoulli.py, kl_divergence kl.py with a registered-pair
dispatch table). Sampling draws keys from the framework's stateful
Generator (core/generator.py) so paddle.seed governs it; log_prob/entropy
are pure jnp and differentiable through the tape.
"""
from __future__ import annotations

from typing import Dict, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.generator import next_rng_key
from ..core.tensor import Tensor

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
    "kl_divergence", "register_kl",
]


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x, jnp.float32) if isinstance(
        x, (int, float, list, tuple, np.ndarray)) else x


class Distribution:
    """Base class (reference: distribution/distribution.py:40)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other: "Distribution"):
        return kl_divergence(self, other)


class Normal(Distribution):
    """Gaussian (reference: distribution/normal.py)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    def sample(self, shape=()):
        eps = jax.random.normal(next_rng_key(),
                                tuple(shape) + self.batch_shape)
        return Tensor(self.loc + self.scale * eps)

    rsample = sample  # reparameterized by construction

    def log_prob(self, value):
        v = _arr(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale)
                      - 0.5 * jnp.log(2 * jnp.pi))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            0.5 + 0.5 * jnp.log(2 * jnp.pi) + jnp.log(self.scale),
            self.batch_shape))


class Uniform(Distribution):
    """U[low, high) (reference: distribution/uniform.py)."""

    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.low), jnp.shape(self.high)))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to((self.low + self.high) / 2,
                                       self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to((self.high - self.low) ** 2 / 12,
                                       self.batch_shape))

    def sample(self, shape=()):
        u = jax.random.uniform(next_rng_key(),
                               tuple(shape) + self.batch_shape)
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.broadcast_to(jnp.log(self.high - self.low),
                                       self.batch_shape))


class Categorical(Distribution):
    """Categorical over the last axis of ``logits`` (reference:
    distribution/categorical.py)."""

    def __init__(self, logits, name=None):
        self.logits = _arr(logits)
        self._log_p = jax.nn.log_softmax(self.logits, axis=-1)
        super().__init__(jnp.shape(self.logits)[:-1])

    @property
    def probs(self):
        return Tensor(jnp.exp(self._log_p))

    def sample(self, shape=()):
        idx = jax.random.categorical(next_rng_key(), self.logits,
                                     shape=tuple(shape) + self.batch_shape)
        return Tensor(idx)

    def log_prob(self, value):
        v = _arr(value).astype(jnp.int32)
        lp = jnp.broadcast_to(self._log_p,
                              v.shape + self._log_p.shape[-1:])
        return Tensor(jnp.take_along_axis(lp, v[..., None],
                                          axis=-1)[..., 0])

    def entropy(self):
        p = jnp.exp(self._log_p)
        return Tensor(-jnp.sum(p * self._log_p, axis=-1))


class Bernoulli(Distribution):
    """Bernoulli(p) (reference: distribution/bernoulli.py)."""

    def __init__(self, probs, name=None):
        self.probs_ = jnp.clip(_arr(probs), 1e-7, 1 - 1e-7)
        super().__init__(jnp.shape(self.probs_))

    @property
    def mean(self):
        return Tensor(self.probs_)

    @property
    def variance(self):
        return Tensor(self.probs_ * (1 - self.probs_))

    def sample(self, shape=()):
        u = jax.random.uniform(next_rng_key(),
                               tuple(shape) + self.batch_shape)
        return Tensor((u < self.probs_).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(v * jnp.log(self.probs_)
                      + (1 - v) * jnp.log1p(-self.probs_))

    def entropy(self):
        p = self.probs_
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


# ------------- KL dispatch (reference: distribution/kl.py) -------------

_KL_REGISTRY: Dict[Tuple[Type, Type], callable] = {}


def register_kl(type_p: Type, type_q: Type):
    """Decorator registering a KL(p||q) rule for a distribution pair
    (reference: kl.py register_kl)."""

    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    for (tp, tq), fn in _KL_REGISTRY.items():
        if isinstance(p, tp) and isinstance(q, tq):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL rule registered for ({type(p).__name__}, "
        f"{type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal_normal(p: Normal, q: Normal):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p: Uniform, q: Uniform):
    inside = (q.low <= p.low) & (p.high <= q.high)
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return Tensor(jnp.where(inside, kl, jnp.inf))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p: Categorical, q: Categorical):
    pp = jnp.exp(p._log_p)
    return Tensor(jnp.sum(pp * (p._log_p - q._log_p), axis=-1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p: Bernoulli, q: Bernoulli):
    a, b = p.probs_, q.probs_
    return Tensor(a * (jnp.log(a) - jnp.log(b))
                  + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))


from .transform import (  # noqa: E402,F401
    AffineTransform, ChainTransform, ExpTransform, SigmoidTransform,
    Transform, TransformedDistribution)

__all__ += ["Transform", "AffineTransform", "ExpTransform",
            "SigmoidTransform", "ChainTransform",
            "TransformedDistribution"]

from .family import (  # noqa: E402,F401
    Beta, Binomial, Cauchy, ContinuousBernoulli, Dirichlet,
    ExponentialFamily, Gamma, Geometric, Gumbel, Independent, Laplace,
    LogNormal, Multinomial, MultivariateNormal, Poisson)

__all__ += ["ExponentialFamily", "Beta", "Dirichlet", "Gamma", "Laplace",
            "LogNormal", "Gumbel", "Multinomial", "MultivariateNormal",
            "Poisson", "Binomial", "Geometric", "Cauchy",
            "ContinuousBernoulli", "Independent"]

from . import constraint  # noqa: E402,F401
from . import variable  # noqa: E402,F401

__all__ += ["constraint", "variable"]
