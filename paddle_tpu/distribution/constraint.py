"""Distribution support constraints (reference:
python/paddle/distribution/constraint.py — Constraint/Real/Range/
Positive/Simplex used by transforms to validate domains/codomains)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Constraint", "Real", "Range", "Positive", "Simplex",
           "real", "positive"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class Constraint:
    """(constraint.py:17) callable support check -> bool Tensor."""

    def __call__(self, value):
        raise NotImplementedError


class Real(Constraint):
    def __call__(self, value):
        v = _arr(value)
        return Tensor(v == v)  # finite-domain reals: NaN excluded


class Range(Constraint):
    def __init__(self, lower, upper):
        self._lower = lower
        self._upper = upper

    def __call__(self, value):
        v = _arr(value)
        return Tensor((jnp.asarray(self._lower) <= v)
                      & (v <= jnp.asarray(self._upper)))


class Positive(Constraint):
    def __call__(self, value):
        return Tensor(_arr(value) > 0)


class Simplex(Constraint):
    def __call__(self, value):
        v = _arr(value)
        ok = jnp.all(v >= 0, -1) & (
            jnp.abs(jnp.sum(v, -1) - 1) < 1e-6)
        return Tensor(ok)


real = Real()
positive = Positive()
