"""Distribution family long tail.

TPU-native equivalents of the reference's per-file distributions
(reference: python/paddle/distribution/beta.py:20, dirichlet.py:22,
gumbel.py, laplace.py, lognormal.py, multinomial.py,
multivariate_normal.py, poisson.py, binomial.py, geometric.py,
cauchy.py, continuous_bernoulli.py, independent.py,
exponential_family.py). Sampling draws from the framework Generator
(paddle.seed-governed); densities are pure jnp, differentiable through
the tape.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import jax.scipy.special as jsp

from ..core.generator import next_rng_key
from ..core.tensor import Tensor
from . import Distribution, Normal, register_kl, _arr

__all__ = [
    "ExponentialFamily", "Beta", "Dirichlet", "Gamma", "Laplace",
    "LogNormal", "Gumbel", "Multinomial", "MultivariateNormal",
    "Poisson", "Binomial", "Geometric", "Cauchy", "ContinuousBernoulli",
    "Independent",
]

_EULER = 0.5772156649015329


class ExponentialFamily(Distribution):
    """Exponential-family base (reference: exponential_family.py).

    Subclasses expose natural parameters + log-normalizer; the generic
    cross-family entropy/KL via Bregman divergences of the log-normalizer
    is realized with jax.grad instead of the reference's static autograd
    graph.
    """

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError


class Beta(ExponentialFamily):
    """Beta(alpha, beta) on (0,1) (reference: beta.py:20)."""

    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.alpha), jnp.shape(self.beta)))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(
            self.alpha / (self.alpha + self.beta), self.batch_shape))

    @property
    def variance(self):
        t = self.alpha + self.beta
        return Tensor(jnp.broadcast_to(
            self.alpha * self.beta / (t * t * (t + 1)), self.batch_shape))

    def sample(self, shape=()):
        a = jnp.broadcast_to(self.alpha, self.batch_shape)
        b = jnp.broadcast_to(self.beta, self.batch_shape)
        return Tensor(jax.random.beta(
            next_rng_key(), a, b, tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v)
                      - (jsp.betaln(self.alpha, self.beta)))

    def entropy(self):
        a, b = self.alpha, self.beta
        return Tensor(jnp.broadcast_to(
            jsp.betaln(a, b)
            - (a - 1) * jsp.digamma(a) - (b - 1) * jsp.digamma(b)
            + (a + b - 2) * jsp.digamma(a + b), self.batch_shape))


class Dirichlet(ExponentialFamily):
    """Dirichlet(concentration) on the simplex (reference: dirichlet.py:22)."""

    def __init__(self, concentration):
        self.concentration = _arr(concentration)
        super().__init__(jnp.shape(self.concentration)[:-1],
                         jnp.shape(self.concentration)[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration
                      / jnp.sum(self.concentration, -1, keepdims=True))

    @property
    def variance(self):
        a0 = jnp.sum(self.concentration, -1, keepdims=True)
        m = self.concentration / a0
        return Tensor(m * (1 - m) / (a0 + 1))

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(
            next_rng_key(), self.concentration,
            tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        v = _arr(value)
        a = self.concentration
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1)
                      + jsp.gammaln(jnp.sum(a, -1))
                      - jnp.sum(jsp.gammaln(a), -1))

    def entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        lnB = jnp.sum(jsp.gammaln(a), -1) - jsp.gammaln(a0)
        return Tensor(lnB + (a0 - k) * jsp.digamma(a0)
                      - jnp.sum((a - 1) * jsp.digamma(a), -1))


class Gamma(ExponentialFamily):
    """Gamma(concentration, rate) (paddle-compatible extension; the
    reference reaches Gamma through kl.py's expfamily machinery)."""

    def __init__(self, concentration, rate):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.concentration), jnp.shape(self.rate)))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(
            self.concentration / self.rate, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            self.concentration / self.rate ** 2, self.batch_shape))

    def sample(self, shape=()):
        a = jnp.broadcast_to(self.concentration, self.batch_shape)
        g = jax.random.gamma(next_rng_key(), a,
                             tuple(shape) + self.batch_shape)
        return Tensor(g / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        a, r = self.concentration, self.rate
        return Tensor(a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v
                      - jsp.gammaln(a))

    def entropy(self):
        a, r = self.concentration, self.rate
        return Tensor(jnp.broadcast_to(
            a - jnp.log(r) + jsp.gammaln(a) + (1 - a) * jsp.digamma(a),
            self.batch_shape))


class Laplace(Distribution):
    """Laplace(loc, scale) (reference: laplace.py)."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(2 * self.scale ** 2,
                                       self.batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.broadcast_to(math.sqrt(2.0) * self.scale,
                                       self.batch_shape))

    def sample(self, shape=()):
        e = jax.random.laplace(next_rng_key(),
                               tuple(shape) + self.batch_shape)
        return Tensor(self.loc + self.scale * e)

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(jnp.broadcast_to(1 + jnp.log(2 * self.scale),
                                       self.batch_shape))

    def cdf(self, value):
        v = _arr(value)
        z = (v - self.loc) / self.scale
        return Tensor(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, value):
        p = _arr(value)
        t = p - 0.5
        return Tensor(self.loc - self.scale * jnp.sign(t)
                      * jnp.log1p(-2 * jnp.abs(t)))


class LogNormal(Distribution):
    """exp(Normal(loc, scale)) (reference: lognormal.py)."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        self._base = Normal(loc, scale)
        super().__init__(self._base.batch_shape)

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(
            jnp.exp(self.loc + self.scale ** 2 / 2), self.batch_shape))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return Tensor(jnp.broadcast_to(
            jnp.expm1(s2) * jnp.exp(2 * self.loc + s2), self.batch_shape))

    def sample(self, shape=()):
        return Tensor(jnp.exp(self._base.sample(shape)._data))

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(self._base.log_prob(jnp.log(v))._data - jnp.log(v))

    def entropy(self):
        return Tensor(self._base.entropy()._data + self.loc)


class Gumbel(Distribution):
    """Gumbel(loc, scale) (reference: gumbel.py)."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc + self.scale * _EULER,
                                       self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            (jnp.pi ** 2 / 6) * self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.sqrt(self.variance._data))

    def sample(self, shape=()):
        g = jax.random.gumbel(next_rng_key(),
                              tuple(shape) + self.batch_shape)
        return Tensor(self.loc + self.scale * g)

    rsample = sample

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            jnp.log(self.scale) + 1 + _EULER, self.batch_shape))


class Multinomial(Distribution):
    """Multinomial(total_count, probs) (reference: multinomial.py)."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = _arr(probs)
        self.probs = self.probs / jnp.sum(self.probs, -1, keepdims=True)
        super().__init__(jnp.shape(self.probs)[:-1],
                         jnp.shape(self.probs)[-1:])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        logits = jnp.log(self.probs)
        n = self.total_count
        draws = jax.random.categorical(
            next_rng_key(), logits,
            shape=(n,) + tuple(shape) + self.batch_shape, axis=-1)
        k = self.probs.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(0)
        return Tensor(counts)

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(jsp.gammaln(jnp.asarray(self.total_count + 1.0))
                      - jnp.sum(jsp.gammaln(v + 1), -1)
                      + jnp.sum(v * jnp.log(self.probs), -1))

    def entropy(self):
        # no closed form: use the classic second-order Stirling
        # approximation 0.5*log((2*pi*e*n)^(k-1) * prod p) for large n,
        # exact per-component correction for the rest
        n, p = self.total_count, self.probs
        k = p.shape[-1]
        approx = 0.5 * ((k - 1) * jnp.log(2 * jnp.pi * jnp.e * n)
                        + jnp.sum(jnp.log(p), -1))
        return Tensor(jnp.broadcast_to(approx, self.batch_shape))


class MultivariateNormal(Distribution):
    """MVN(loc, covariance_matrix) (reference: multivariate_normal.py)."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None):
        self.loc = _arr(loc)
        if (covariance_matrix is None) == (scale_tril is None):
            raise ValueError(
                "exactly one of covariance_matrix / scale_tril required")
        if covariance_matrix is not None:
            self.covariance_matrix = _arr(covariance_matrix)
            self._tril = jnp.linalg.cholesky(self.covariance_matrix)
        else:
            self._tril = _arr(scale_tril)
            self.covariance_matrix = self._tril @ jnp.swapaxes(
                self._tril, -1, -2)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc)[:-1],
            jnp.shape(self.covariance_matrix)[:-2]),
            jnp.shape(self.loc)[-1:])

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(
            self.loc, self.batch_shape + self.event_shape))

    @property
    def variance(self):
        d = jnp.diagonal(self.covariance_matrix, axis1=-2, axis2=-1)
        return Tensor(jnp.broadcast_to(
            d, self.batch_shape + self.event_shape))

    def sample(self, shape=()):
        k = self.loc.shape[-1]
        eps = jax.random.normal(
            next_rng_key(),
            tuple(shape) + self.batch_shape + (k,))
        return Tensor(self.loc + jnp.einsum(
            "...ij,...j->...i", self._tril, eps))

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        k = self.loc.shape[-1]
        diff = v - self.loc
        sol = jax.scipy.linalg.solve_triangular(
            jnp.broadcast_to(self._tril, jnp.broadcast_shapes(
                self._tril.shape, diff.shape[:-1] + self._tril.shape[-2:])),
            diff[..., None], lower=True)[..., 0]
        m = jnp.sum(sol ** 2, -1)
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self._tril, axis1=-2, axis2=-1)), -1)
        return Tensor(-0.5 * (k * jnp.log(2 * jnp.pi) + m) - half_logdet)

    def entropy(self):
        k = self.loc.shape[-1]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self._tril, axis1=-2, axis2=-1)), -1)
        ent = 0.5 * k * (1 + jnp.log(2 * jnp.pi)) + half_logdet
        return Tensor(jnp.broadcast_to(ent, self.batch_shape))


class Poisson(ExponentialFamily):
    """Poisson(rate) (reference: poisson.py)."""

    def __init__(self, rate):
        self.rate = _arr(rate)
        super().__init__(jnp.shape(self.rate))

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def sample(self, shape=()):
        return Tensor(jax.random.poisson(
            next_rng_key(), self.rate,
            tuple(shape) + self.batch_shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(v * jnp.log(self.rate) - self.rate
                      - jsp.gammaln(v + 1))

    def entropy(self):
        # truncated-support exact sum (reference poisson.py computes the
        # same way): support bounded at rate + 30*sqrt(rate) + 20
        r = jnp.asarray(self.rate, jnp.float32)
        top = int(jnp.max(jnp.ceil(r + 30 * jnp.sqrt(r) + 20)))
        ks = jnp.arange(top, dtype=jnp.float32)
        lp = (ks[:, None] * jnp.log(r.reshape(-1)) - r.reshape(-1)
              - jsp.gammaln(ks[:, None] + 1))
        ent = -jnp.sum(jnp.exp(lp) * lp, 0)
        return Tensor(ent.reshape(self.batch_shape))


class Binomial(Distribution):
    """Binomial(total_count, probs) (reference: binomial.py)."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = jnp.clip(_arr(probs), 1e-7, 1 - 1e-7)
        super().__init__(jnp.shape(self.probs))

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        u = jax.random.uniform(
            next_rng_key(),
            (self.total_count,) + tuple(shape) + self.batch_shape)
        return Tensor(jnp.sum((u < self.probs).astype(jnp.float32), 0))

    def log_prob(self, value):
        v = _arr(value)
        n, p = float(self.total_count), self.probs
        return Tensor(jsp.gammaln(jnp.asarray(n + 1.0))
                      - jsp.gammaln(v + 1) - jsp.gammaln(n - v + 1)
                      + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

    def entropy(self):
        # exact sum over the (finite) support
        n, p = self.total_count, self.probs
        ks = jnp.arange(n + 1, dtype=jnp.float32)
        flat = p.reshape(-1)
        lp = (jsp.gammaln(jnp.asarray(n + 1.0))
              - jsp.gammaln(ks[:, None] + 1)
              - jsp.gammaln(n - ks[:, None] + 1)
              + ks[:, None] * jnp.log(flat)
              + (n - ks[:, None]) * jnp.log1p(-flat))
        ent = -jnp.sum(jnp.exp(lp) * lp, 0)
        return Tensor(ent.reshape(self.batch_shape))


class Geometric(Distribution):
    """Geometric(probs): #failures before first success, support {0,1,...}
    (reference: geometric.py)."""

    def __init__(self, probs):
        self.probs = jnp.clip(_arr(probs), 1e-7, 1 - 1e-7)
        super().__init__(jnp.shape(self.probs))

    @property
    def mean(self):
        return Tensor((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return Tensor((1 - self.probs) / self.probs ** 2)

    @property
    def stddev(self):
        return Tensor(jnp.sqrt(self.variance._data))

    def sample(self, shape=()):
        u = jax.random.uniform(next_rng_key(),
                               tuple(shape) + self.batch_shape,
                               minval=1e-12, maxval=1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(v * jnp.log1p(-self.probs) + jnp.log(self.probs))

    def pmf(self, k):
        return Tensor(jnp.exp(self.log_prob(k)._data))

    def entropy(self):
        p = self.probs
        return Tensor(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)

    def cdf(self, value):
        v = _arr(value)
        return Tensor(1 - jnp.power(1 - self.probs, v + 1))


class Cauchy(Distribution):
    """Cauchy(loc, scale) (reference: cauchy.py)."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    def sample(self, shape=()):
        c = jax.random.cauchy(next_rng_key(),
                              tuple(shape) + self.batch_shape)
        return Tensor(self.loc + self.scale * c)

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        z = (v - self.loc) / self.scale
        return Tensor(-jnp.log(jnp.pi * self.scale) - jnp.log1p(z ** 2))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            jnp.log(4 * jnp.pi * self.scale), self.batch_shape))

    def cdf(self, value):
        v = _arr(value)
        return Tensor(jnp.arctan((v - self.loc) / self.scale) / jnp.pi
                      + 0.5)


class ContinuousBernoulli(Distribution):
    """CB(lambda) on [0,1] (reference: continuous_bernoulli.py)."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = jnp.clip(_arr(probs), 1e-4, 1 - 1e-4)
        self._lims = lims
        super().__init__(jnp.shape(self.probs))

    def _cont_bern_log_norm(self):
        lam = self.probs
        lo, hi = self._lims
        safe = jnp.where((lam < lo) | (lam > hi), lam, 0.25)
        # C(lam) = 2*artanh(1-2lam)/(1-2lam)
        log_norm = math.log(2.0) \
            + jnp.log(jnp.abs(jnp.arctanh(1 - 2 * safe))) \
            - jnp.log(jnp.abs(1 - 2 * safe))
        taylor = math.log(2.0) + 4.0 / 3.0 * (lam - 0.5) ** 2 \
            + 104.0 / 45.0 * (lam - 0.5) ** 4
        return jnp.where((lam < lo) | (lam > hi), log_norm, taylor)

    @property
    def mean(self):
        lam = self.probs
        lo, hi = self._lims
        safe = jnp.where((lam < lo) | (lam > hi), lam, 0.25)
        m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        taylor = 0.5 + (lam - 0.5) / 3.0
        return Tensor(jnp.where((lam < lo) | (lam > hi), m, taylor))

    def sample(self, shape=()):
        u = jax.random.uniform(next_rng_key(),
                               tuple(shape) + self.batch_shape)
        return Tensor(self.icdf(u)._data)

    rsample = sample

    def icdf(self, value):
        u = _arr(value)
        lam = self.probs
        lo, hi = self._lims
        safe = jnp.where((lam < lo) | (lam > hi), lam, 0.25)
        x = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
             / (jnp.log(safe) - jnp.log1p(-safe)))
        return Tensor(jnp.where((lam < lo) | (lam > hi), x, u))

    def log_prob(self, value):
        v = _arr(value)
        lam = self.probs
        return Tensor(v * jnp.log(lam) + (1 - v) * jnp.log1p(-lam)
                      + self._cont_bern_log_norm())

    def entropy(self):
        # E[-log p(x)] with the CB mean in closed form
        m = self.mean._data
        lam = self.probs
        return Tensor(-(m * jnp.log(lam) + (1 - m) * jnp.log1p(-lam)
                        + self._cont_bern_log_norm()))


class Independent(Distribution):
    """Reinterpret batch dims as event dims (reference: independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._rank = int(reinterpreted_batch_rank)
        bshape = base.batch_shape
        if self._rank > len(bshape):
            raise ValueError("reinterpreted_batch_rank too large")
        split = len(bshape) - self._rank
        super().__init__(bshape[:split],
                         bshape[split:] + tuple(base.event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def _sum_rightmost(self, x):
        n = self._rank
        return jnp.sum(x, axis=tuple(range(x.ndim - n, x.ndim))) \
            if n else x

    def log_prob(self, value):
        return Tensor(self._sum_rightmost(self.base.log_prob(value)._data))

    def entropy(self):
        return Tensor(self._sum_rightmost(self.base.entropy()._data))


# ---------------- KL rules (reference: distribution/kl.py) ----------------

@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def dig(x):
        return jsp.digamma(x)

    pa, pb, qa, qb = p.alpha, p.beta, q.alpha, q.beta
    return Tensor(jsp.betaln(qa, qb) - jsp.betaln(pa, pb)
                  + (pa - qa) * dig(pa) + (pb - qb) * dig(pb)
                  + (qa - pa + qb - pb) * dig(pa + pb))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    pa, qa = p.concentration, q.concentration
    pa0 = jnp.sum(pa, -1, keepdims=True)
    t = jnp.sum((pa - qa) * (jsp.digamma(pa) - jsp.digamma(pa0)), -1)
    return Tensor(t + jsp.gammaln(pa0[..., 0])
                  - jsp.gammaln(jnp.sum(qa, -1))
                  + jnp.sum(jsp.gammaln(qa), -1)
                  - jnp.sum(jsp.gammaln(pa), -1))


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    pa, pr, qa, qr = p.concentration, p.rate, q.concentration, q.rate
    return Tensor((pa - qa) * jsp.digamma(pa) - jsp.gammaln(pa)
                  + jsp.gammaln(qa) + qa * (jnp.log(pr) - jnp.log(qr))
                  + pa * (qr - pr) / pr)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    d = jnp.abs(p.loc - q.loc)
    return Tensor(jnp.log(q.scale / p.scale) + d / q.scale
                  + (p.scale / q.scale) * jnp.exp(-d / p.scale) - 1)


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    from . import _kl_normal_normal

    return _kl_normal_normal(p._base, q._base)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    # E_p[ln p - ln q] with the Gumbel MGF E[e^{-t z}] = Gamma(1 + t)
    b1, b2 = p.scale, q.scale
    return Tensor(jnp.log(b2 / b1) - _EULER - 1
                  + (p.loc - q.loc + b1 * _EULER) / b2
                  + jnp.exp((q.loc - p.loc) / b2
                            + jsp.gammaln(1 + b1 / b2)))


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    return Tensor(p.rate * (jnp.log(p.rate) - jnp.log(q.rate))
                  + q.rate - p.rate)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    # KL = ln(p/q) + E[k]*(ln(1-p) - ln(1-q)), E[k] = (1-p)/p
    pp, qq = p.probs, q.probs
    return Tensor(jnp.log(pp) - jnp.log(qq)
                  + (1 - pp) / pp * (jnp.log1p(-pp) - jnp.log1p(-qq)))


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    # closed form (Chyzak & Nielsen 2019)
    num = (p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2
    return Tensor(jnp.log(num / (4 * p.scale * q.scale)))


@register_kl(Binomial, Binomial)
def _kl_binomial_binomial(p, q):
    if p.total_count != q.total_count:
        raise NotImplementedError(
            "KL(Binomial||Binomial) requires equal total_count")
    pp, qq = p.probs, q.probs
    per = pp * (jnp.log(pp) - jnp.log(qq)) \
        + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq))
    return Tensor(p.total_count * per)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    k = p.loc.shape[-1]
    q_tril = q._tril
    diff = (q.loc - p.loc)[..., None]
    sol_m = jax.scipy.linalg.solve_triangular(q_tril, diff, lower=True)
    m = jnp.sum(sol_m[..., 0] ** 2, -1)
    sol_c = jax.scipy.linalg.solve_triangular(q_tril, p._tril, lower=True)
    tr = jnp.sum(sol_c ** 2, (-2, -1))
    logdet_p = jnp.sum(jnp.log(jnp.diagonal(p._tril, axis1=-2, axis2=-1)),
                       -1)
    logdet_q = jnp.sum(jnp.log(jnp.diagonal(q_tril, axis1=-2, axis2=-1)),
                       -1)
    return Tensor(0.5 * (tr + m - k) + logdet_q - logdet_p)


@register_kl(Independent, Independent)
def _kl_independent_independent(p, q):
    if p._rank != q._rank:
        raise NotImplementedError("mismatched reinterpreted ranks")
    from . import kl_divergence

    inner = kl_divergence(p.base, q.base)._data
    return Tensor(p._sum_rightmost(inner))
