"""Distribution transforms (reference: python/paddle/distribution/
transform.py — Transform base with forward/inverse/log_det_jacobian,
Affine/Exp/Sigmoid/Chain; transformed_distribution.py
TransformedDistribution)."""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from . import Distribution, _arr

__all__ = ["Transform", "AffineTransform", "ExpTransform",
           "SigmoidTransform", "ChainTransform",
           "TransformedDistribution"]


class Transform:
    """Invertible map with tractable log|det J| (transform.py:Transform)."""

    def forward(self, x):
        return Tensor(self._forward(_arr(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._fldj(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        return Tensor(-self._fldj(self._inverse(_arr(y))))

    # raw-array hooks
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    """y = loc + scale * x (transform.py:AffineTransform)."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        # two-sided broadcast: scale/loc may be wider than x (matches
        # forward()'s output shape) — shape-only, no forward compute
        shape = jnp.broadcast_shapes(jnp.shape(self.scale),
                                     jnp.shape(self.loc), jnp.shape(x))
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), shape)


class ExpTransform(Transform):
    """y = exp(x) (transform.py:ExpTransform)."""

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class SigmoidTransform(Transform):
    """y = sigmoid(x) (transform.py:SigmoidTransform)."""

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class ChainTransform(Transform):
    """Composition, applied first-to-last (transform.py:ChainTransform)."""

    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = jnp.zeros(jnp.shape(x))
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class TransformedDistribution(Distribution):
    """Pushforward of a base distribution through transforms
    (reference: transformed_distribution.py)."""

    def __init__(self, base: Distribution,
                 transforms: List[Transform]):
        self.base = base
        self.transform = ChainTransform(list(transforms)) \
            if not isinstance(transforms, Transform) else transforms
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self.transform.forward(x)

    rsample = sample

    def log_prob(self, value):
        y = _arr(value)
        x = self.transform._inverse(y)
        base_lp = self.base.log_prob(Tensor(x))._data
        return Tensor(base_lp - self.transform._fldj(x))
