"""Random-variable descriptors (reference:
python/paddle/distribution/variable.py — Variable/Real/Positive/
Independent/Stack: event metadata + support constraint per variable)."""
from __future__ import annotations

from . import constraint as _c

__all__ = ["Variable", "Real", "Positive", "Independent", "Stack",
           "real", "positive"]


class Variable:
    """(variable.py:19) is_discrete + event_rank + support check."""

    def __init__(self, is_discrete=False, event_rank=0, constraint=None):
        self._is_discrete = is_discrete
        self._event_rank = event_rank
        self._constraint = constraint

    @property
    def is_discrete(self):
        return self._is_discrete

    @property
    def event_rank(self):
        return self._event_rank

    def constraint(self, value):
        return self._constraint(value)


class Real(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, _c.real)


class Positive(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, _c.positive)


class Independent(Variable):
    """(variable.py:56) reinterpret rightmost batch dims as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._reinterpreted_batch_rank = reinterpreted_batch_rank
        super().__init__(base.is_discrete,
                         base.event_rank + reinterpreted_batch_rank,
                         base._constraint)


class Stack(Variable):
    """(variable.py:85) stack of variables along an axis."""

    def __init__(self, vars_, axis=0):
        if not vars_:
            raise ValueError("Stack requires a non-empty variable list")
        self._vars = vars_
        self._axis = axis
        super().__init__(any(v.is_discrete for v in vars_),
                         max(v.event_rank for v in vars_),
                         vars_[0]._constraint)

    @property
    def stacked_vars(self):
        return self._vars


real = Real()
positive = Positive()
