"""paddle.fft — discrete Fourier transforms.

TPU-native equivalent of the reference's fft module (reference:
python/paddle/fft.py over phi fft kernels/cuFFT). Lowered via jnp.fft —
XLA's FFT HLO; norm conventions match the reference ("backward" default,
"ortho", "forward").
"""
from __future__ import annotations

import jax.numpy as jnp

from .ops.dispatch import as_tensor_args, eager_apply

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
    "fftn", "ifftn", "rfft2", "irfft2", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def to_cpu_op(t):
    """Move a tensor to the host CPU device AS A DISPATCHED OP, so the
    transfer is on the tape and its vjp (jax transposes device_put as a
    transfer back) returns cotangents to the producer's device. Used by
    every op whose result is complex (no TPU support): fft family,
    audio.Spectrogram."""
    import jax

    if t._data.device.platform == "cpu":
        return t
    cpu = jax.devices("cpu")[0]
    return eager_apply("to_cpu", lambda a: jax.device_put(a, cpu), [t])


def _op(name, raw, x):
    import jax

    (t,) = as_tensor_args(x)
    t = to_cpu_op(t)
    # default_device: jnp.fft internals create norm scalars on the
    # DEFAULT device — those must land on CPU too
    with jax.default_device(jax.devices("cpu")[0]):
        return eager_apply(name, raw, [t])


def _mk1d(jfn, opname):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return _op(opname, lambda a: jfn(a, n=n, axis=axis, norm=norm), x)

    op.__name__ = opname
    return op


fft = _mk1d(jnp.fft.fft, "fft")
ifft = _mk1d(jnp.fft.ifft, "ifft")
rfft = _mk1d(jnp.fft.rfft, "rfft")
irfft = _mk1d(jnp.fft.irfft, "irfft")
hfft = _mk1d(jnp.fft.hfft, "hfft")
ihfft = _mk1d(jnp.fft.ihfft, "ihfft")


def _mk2d(jfn, opname):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return _op(opname, lambda a: jfn(a, s=s, axes=axes, norm=norm), x)

    op.__name__ = opname
    return op


fft2 = _mk2d(jnp.fft.fft2, "fft2")
ifft2 = _mk2d(jnp.fft.ifft2, "ifft2")
rfft2 = _mk2d(jnp.fft.rfft2, "rfft2")
irfft2 = _mk2d(jnp.fft.irfft2, "irfft2")


def _mkn(jfn, opname):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return _op(opname, lambda a: jfn(a, s=s, axes=axes, norm=norm), x)

    op.__name__ = opname
    return op


fftn = _mkn(jnp.fft.fftn, "fftn")
ifftn = _mkn(jnp.fft.ifftn, "ifftn")
rfftn = _mkn(jnp.fft.rfftn, "rfftn")
irfftn = _mkn(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.dtype import convert_dtype
    from .core.tensor import Tensor

    out = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        out = out.astype(convert_dtype(dtype).np_dtype)
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.dtype import convert_dtype
    from .core.tensor import Tensor

    out = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        out = out.astype(convert_dtype(dtype).np_dtype)
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    return _op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return _op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)
