from . import io  # noqa: F401
from . import unique_name  # noqa: F401
from .io import load, save  # noqa: F401
