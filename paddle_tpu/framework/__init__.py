from . import io  # noqa: F401
from .io import load, save  # noqa: F401
