"""save/load — filled in with full checkpoint support (framework/io.py)."""
import pickle


def save(obj, path, protocol=4):
    import numpy as np

    from ..core.tensor import Tensor

    def conv(o):
        if isinstance(o, Tensor):
            return {"__tensor__": True, "data": np.asarray(o._data)}
        if isinstance(o, dict):
            return {k: conv(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return type(o)(conv(v) for v in o)
        return o

    with open(path, "wb") as f:
        pickle.dump(conv(obj), f, protocol=protocol)


def load(path, **kwargs):
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    def conv(o):
        if isinstance(o, dict):
            if o.get("__tensor__"):
                return Tensor(jnp.asarray(o["data"]))
            return {k: conv(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return type(o)(conv(v) for v in o)
        return o

    with open(path, "rb") as f:
        return conv(pickle.load(f))
