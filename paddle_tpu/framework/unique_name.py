"""Unique-name generation + reset guard.

TPU-native equivalent of the reference's unique_name module (reference:
python/paddle/base/unique_name.py — per-key counters and ``guard()``
context resetting them). Structured parameter names
("linear_0.weight") come from per-class construction counters in
``nn.layer_base``; ``guard()`` resets those counters so a checkpoint
written by one process can be restored by another that constructs extra
layers first (wrap model construction in ``guard()`` on both sides).
"""
from __future__ import annotations

import contextlib
import itertools
from typing import Dict, Iterator

_generators: Dict[str, "itertools.count"] = {}


def generate(key: str = "tmp") -> str:
    c = _generators.setdefault(key, itertools.count())
    return f"{key}_{next(c)}"


@contextlib.contextmanager
def guard(new_generator=None) -> Iterator[None]:
    """Reset naming counters for the enclosed scope (reference:
    unique_name.guard). Layers constructed inside two separate
    ``guard()`` scopes get identical structured names, making
    optimizer/checkpoint state keys reproducible across processes."""
    from ..nn import layer_base

    saved_layers = dict(layer_base._layer_instance_counters)
    saved_gens = {k: v for k, v in _generators.items()}
    layer_base._layer_instance_counters.clear()
    _generators.clear()
    try:
        yield
    finally:
        layer_base._layer_instance_counters.clear()
        layer_base._layer_instance_counters.update(saved_layers)
        _generators.clear()
        _generators.update(saved_gens)
