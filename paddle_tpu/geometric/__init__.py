"""paddle_tpu.geometric — graph-learning primitives.

TPU-native equivalent of the reference's geometric package (reference:
python/paddle/geometric — math.py segment_sum/mean/max/min,
message_passing/send_recv.py send_u_recv:36 / send_ue_recv / send_uv;
CUDA kernels paddle/phi/kernels/gpu/graph_send_recv_*). The scatter
reductions map directly onto ``jax.ops.segment_*`` — XLA lowers them to
sorted-segment scatters that tile onto the VPU; no hash tables needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import as_tensor_args, eager_apply

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv",
]


def _num_segments(ids, out_size):
    if out_size is not None:
        return int(out_size)
    arr = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
    return int(jnp.max(arr)) + 1 if arr.size else 0


def _segment(kind, data, ids, n):
    f = {"sum": jax.ops.segment_sum, "mean": None,
         "max": jax.ops.segment_max, "min": jax.ops.segment_min}[kind]
    if kind == "mean":
        s = jax.ops.segment_sum(data, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                                  ids, num_segments=n)
        return s / jnp.maximum(cnt, 1.0).reshape(
            (-1,) + (1,) * (data.ndim - 1))
    out = f(data, ids, num_segments=n)
    if kind in ("max", "min"):
        # empty segments: paddle returns 0, jax returns -inf/+inf (or
        # int min/max); zero must keep the input dtype — a weak 0.0
        # would silently promote integer data to float
        cnt = jax.ops.segment_sum(
            jnp.ones((data.shape[0],), jnp.int32), ids, num_segments=n)
        empty = (cnt == 0).reshape((-1,) + (1,) * (data.ndim - 1))
        out = jnp.where(empty, jnp.zeros((), out.dtype), out)
    return out


def segment_sum(data, segment_ids, name=None):
    """(reference geometric/math.py segment_sum)"""
    ts = as_tensor_args(data, segment_ids)
    n = _num_segments(ts[1], None)
    return eager_apply("segment_sum",
                       lambda d, i: _segment("sum", d,
                                             i.astype(jnp.int32), n), ts)


def segment_mean(data, segment_ids, name=None):
    ts = as_tensor_args(data, segment_ids)
    n = _num_segments(ts[1], None)
    return eager_apply("segment_mean",
                       lambda d, i: _segment("mean", d,
                                             i.astype(jnp.int32), n), ts)


def segment_max(data, segment_ids, name=None):
    ts = as_tensor_args(data, segment_ids)
    n = _num_segments(ts[1], None)
    return eager_apply("segment_max",
                       lambda d, i: _segment("max", d,
                                             i.astype(jnp.int32), n), ts)


def segment_min(data, segment_ids, name=None):
    ts = as_tensor_args(data, segment_ids)
    n = _num_segments(ts[1], None)
    return eager_apply("segment_min",
                       lambda d, i: _segment("min", d,
                                             i.astype(jnp.int32), n), ts)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """(reference send_recv.py:36) gather x[src] then scatter-reduce to
    dst: one fused gather+segment reduction, no materialized messages."""
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unsupported reduce_op {reduce_op!r}")
    ts = as_tensor_args(x, src_index, dst_index)
    n = _num_segments(ts[2], out_size) if out_size is not None else \
        max(_num_segments(ts[2], None), ts[0]._data.shape[0])

    def raw(xd, src, dst):
        msgs = xd[src.astype(jnp.int32)]
        return _segment(reduce_op, msgs, dst.astype(jnp.int32), n)

    return eager_apply("send_u_recv", raw, ts)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """(reference send_recv.py send_ue_recv) node features combined with
    edge features via message_op, then scatter-reduced."""
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(f"unsupported message_op {message_op!r}")
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unsupported reduce_op {reduce_op!r}")
    ts = as_tensor_args(x, y, src_index, dst_index)
    n = _num_segments(ts[3], out_size) if out_size is not None else \
        max(_num_segments(ts[3], None), ts[0]._data.shape[0])

    def raw(xd, yd, src, dst):
        msgs = ops[message_op](xd[src.astype(jnp.int32)], yd)
        return _segment(reduce_op, msgs, dst.astype(jnp.int32), n)

    return eager_apply("send_ue_recv", raw, ts)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """(reference send_recv.py send_uv) per-edge message from both
    endpoints' features; no reduction."""
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(f"unsupported message_op {message_op!r}")
    ts = as_tensor_args(x, y, src_index, dst_index)

    def raw(xd, yd, src, dst):
        return ops[message_op](xd[src.astype(jnp.int32)],
                               yd[dst.astype(jnp.int32)])

    return eager_apply("send_uv", raw, ts)
