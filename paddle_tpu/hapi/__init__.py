from . import callbacks  # noqa: F401
from .model import Model  # noqa: F401
