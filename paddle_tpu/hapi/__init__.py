from .model import Model  # noqa: F401
