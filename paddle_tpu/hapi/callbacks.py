"""hapi callbacks.

TPU-native equivalent of the reference's callback suite (reference:
python/paddle/hapi/callbacks.py — Callback base, ProgBarLogger,
ModelCheckpoint, LRScheduler, EarlyStopping, History/VisualDL).
"""
from __future__ import annotations

import numbers
import os
import time
from typing import Dict, List, Optional

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "History", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return call


class ProgBarLogger(Callback):
    """Per-epoch stdout logging (reference ProgBarLogger)."""

    def __init__(self, log_freq: int = 10, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.seen = 0
        self._t0 = time.time()
        if self.verbose:
            total = self.params.get("epochs")
            print(f"Epoch {epoch + 1}/{total}")

    def _fmt(self, logs):
        return " - ".join(
            f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else f"{k}: {v}"
            for k, v in (logs or {}).items())

    def on_train_batch_end(self, step, logs=None):
        self.seen += 1
        if self.verbose and self.seen % self.log_freq == 0:
            steps = self.params.get("steps")
            print(f"step {self.seen}/{steps} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"epoch {epoch + 1} done in {dt:.1f}s - "
                  f"{self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Periodic save (reference ModelCheckpoint)."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LR scheduler (reference LRSchedulerCallback)."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    """(reference EarlyStopping): stop when a monitored metric stops
    improving."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.monitor_op = lambda cur, best: cur > best + self.min_delta
            self.best = float("-inf")
        else:
            self.monitor_op = lambda cur, best: cur < best - self.min_delta
            self.best = float("inf")
        self.wait = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        if self.baseline is not None:
            self.best = self.baseline

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.monitor_op(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"],
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: {self.monitor} did not "
                          f"improve for {self.wait} evals")


class History(Callback):
    def on_train_begin(self, logs=None):
        self.history: Dict[str, list] = {}

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=10, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, History) for c in cbks):
        cbks = cbks + [History()]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                    "metrics": metrics or [], "save_dir": save_dir})
    return lst
