"""paddle.flops / paddle.summary — model cost inspection.

TPU-native equivalent of the reference's dynamic flops counter
(reference: python/paddle/hapi/dynamic_flops.py ``flops``— forward
hooks per leaf layer accumulating multiply-accumulate counts;
hapi/model_summary.py ``summary``). Counts follow the reference's
convention (MACs-style: conv = kernel_ops * out_elems, linear = in*out).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["flops", "summary"]


def _prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


def _count_layer(layer: Layer, x: Tensor, y) -> Optional[int]:
    from ..nn.layers.common import Linear
    from ..nn.layers.conv import _ConvNd
    from ..nn.layers.norm import _BatchNormBase, LayerNorm

    out = y[0] if isinstance(y, (tuple, list)) else y
    if isinstance(layer, _ConvNd):  # every rank incl. transpose
        # the layer's own attr, not out.shape[1] — NHWC data_format puts
        # a spatial dim there
        out_channels = layer._out_channels
        # MACs per output element = weight elems per output channel
        # (= kernel_elems * in_channels/groups for plain convs; the
        # weight-derived form also covers transpose layouts)
        kernel_ops = _prod(layer.weight.shape) // max(out_channels, 1)
        bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
        return _prod(out.shape) * (kernel_ops + bias_ops)
    if isinstance(layer, Linear):
        return _prod(out.shape[:-1]) * layer._in_features \
            * layer._out_features
    if isinstance(layer, (_BatchNormBase, LayerNorm)):
        return 2 * _prod(x.shape)
    return None


def flops(net: Layer, input_size, custom_ops: Optional[Dict] = None,
          print_detail: bool = False) -> int:
    """Total FLOPs (MACs convention) of one forward at ``input_size``
    (reference: hapi/dynamic_flops.py:flops). ``custom_ops`` maps layer
    type -> fn(layer, x, y) -> count."""
    import jax.numpy as jnp

    from ..core import engine

    custom_ops = custom_ops or {}
    records = []
    handles = []

    def make_hook(layer):
        def hook(lyr, inputs, outputs):
            x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
            fn = custom_ops.get(type(lyr))
            cnt = fn(lyr, x, outputs) if fn is not None \
                else _count_layer(lyr, x, outputs)
            if cnt:
                records.append((lyr.full_name()
                                if hasattr(lyr, "full_name")
                                else type(lyr).__name__, int(cnt)))

        return hook

    for sub in net.sublayers(include_self=True):
        if not list(sub.children()):  # leaves only
            handles.append(sub.register_forward_post_hook(
                make_hook(sub)))
    # remember per-sublayer training flags: a blanket net.train() on
    # restore would un-freeze individually eval()'d sublayers
    modes = [(sub, sub.training) for sub in net.sublayers(include_self=True)]
    net.eval()
    try:
        x = Tensor(jnp.zeros(tuple(int(s) for s in input_size),
                             jnp.float32))
        with engine.no_grad():
            net(x)
    finally:
        for h in handles:
            h.remove()
        for sub, mode in modes:
            sub.training = mode
    total = sum(c for _, c in records)
    if print_detail:
        for name, c in records:
            print(f"{name:<40}{c:>16,}")
        print(f"{'Total FLOPs:':<40}{total:>16,}")
    return total


def summary(net: Layer, input_size=None, dtypes=None) -> Dict:
    """Standalone layer/param summary (reference:
    hapi/model_summary.py:summary). With ``input_size`` a forward runs
    under hooks and per-layer OUTPUT shapes are reported, like the
    reference; without it only the parameter table prints."""
    out_shapes = {}
    if input_size is not None:
        import jax.numpy as jnp

        from ..core import engine

        handles = []

        def make_hook(name):
            def hook(lyr, inputs, outputs):
                o = outputs[0] if isinstance(outputs, (tuple, list)) \
                    else outputs
                out_shapes[name] = tuple(o.shape)

            return hook

        for name, sub in net.named_sublayers(include_self=False):
            if not list(sub.children()):
                handles.append(sub.register_forward_post_hook(
                    make_hook(name)))
        modes = [(sub, sub.training)
                 for sub in net.sublayers(include_self=True)]
        net.eval()
        try:
            np_dtype = jnp.float32 if not dtypes else \
                jnp.dtype(dtypes[0] if isinstance(dtypes, (list, tuple))
                          else dtypes)
            x = Tensor(jnp.zeros(tuple(int(s) for s in input_size),
                                 np_dtype))
            with engine.no_grad():
                net(x)
        finally:
            for h in handles:
                h.remove()
            for sub, mode in modes:
                sub.training = mode

    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=12) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<22}{'Params':>14}"]
    lines += [f"{n:<{width}}{str(s):<22}{c:>14,}" for n, s, c in rows]
    if out_shapes:
        lines.append("-" * (width + 36))
        owidth = max(len(k) for k in out_shapes) + 2
        lines.append(f"{'Layer':<{owidth}}{'Output shape':<24}")
        lines += [f"{k:<{owidth}}{str(v):<24}"
                  for k, v in out_shapes.items()]
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable,
            "output_shapes": out_shapes}
