"""paddle.hub — load models from a local repo directory (reference:
python/paddle/hapi/hub.py — hub.list/help/load over a hubconf.py;
github/gitee sources need egress, so the local-dir source is the
supported path here and remote sources raise with guidance)."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    # register BEFORE exec (standard importlib recipe): objects defined
    # in hubconf.py must resolve __module__ through sys.modules so they
    # stay picklable (e.g. through incubate.multiprocessing)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source not in ("local",):
        raise ValueError(
            "zero-egress environment: only source='local' is supported "
            "(clone the hub repo and pass its directory)")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """(hub.py list) Entrypoint names exported by the repo's hubconf."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    """(hub.py help) The entrypoint's docstring."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"model {model!r} not found in {repo_dir}")
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """(hub.py load) Call the entrypoint."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"model {model!r} not found in {repo_dir}")
    return getattr(mod, model)(**kwargs)
