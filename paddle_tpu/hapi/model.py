class Model:  # placeholder — replaced by full hapi
    def __init__(self, *a, **k):
        raise NotImplementedError("hapi.Model lands with the hapi module")
