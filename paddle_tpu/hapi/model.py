"""hapi.Model — the Keras-style high-level training API.

TPU-native equivalent of the reference's ``paddle.Model`` (reference:
python/paddle/hapi/model.py:1054 — ``fit:1756``, ``evaluate``,
``predict``, ``save/load``, callbacks). The TPU twist: ``fit`` drives
``paddle.jit.TrainStep`` — the whole train step (forward + backward +
optimizer) is ONE compiled XLA program, so the python loop only feeds
batches and reads the scalar loss.
"""
from __future__ import annotations

import os
from typing import List

import numpy as np

from ..core import engine
from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from ..metric import Metric
from .callbacks import config_callbacks

__all__ = ["Model"]


def _to_tensor(x):
    import jax.numpy as jnp

    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(np.asarray(x)))


class Model:
    """(model.py:1054 parity)"""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self.stop_training = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        """(model.py prepare) In a launched multi-process run this also
        wires data parallelism automatically — the reference's
        DynamicGraphAdapter wraps the network in paddle.DataParallel
        when ParallelEnv().nranks > 1 (reference hapi/model.py:1054);
        here prepare() detects an initialized parallel env, wraps the
        network (param broadcast + bucketed grad allreduce on the tape)
        and fit() shards batches with DistributedBatchSampler."""
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        self._train_step = None
        import paddle_tpu.distributed as dist

        if (dist.is_initialized() and dist.get_world_size() > 1
                and not isinstance(self.network, dist.DataParallel)):
            self.network = dist.DataParallel(self.network)
            self._distributed = True
        self._amp_level = None
        self._amp_dtype = "bfloat16"
        if isinstance(amp_configs, (str, dict)):
            level = amp_configs if isinstance(amp_configs, str) \
                else amp_configs.get("level", "O1")
            self._amp_level = level if level in ("O1", "O2") else None
            if isinstance(amp_configs, dict):
                self._amp_dtype = amp_configs.get("dtype", "bfloat16")
            if level == "O2" and optimizer is not None:
                from ..amp import decorate

                decorate(self.network, optimizer, level="O2",
                         dtype=self._amp_dtype)
        return self

    def _ensure_step(self):
        if self._train_step is None:
            if self._optimizer is None or self._loss is None:
                raise RuntimeError("call Model.prepare(optimizer, loss) "
                                   "before fit()")
            if getattr(self, "_distributed", False):
                # DP runs on the eager tape: the DataParallel backward-
                # final hook performs the bucketed grad allreduce (the
                # reference dygraph adapter's reducer path)
                from ..amp import auto_cast

                def eager_step(inputs, labels):
                    # honor prepare(amp_configs=...) on the DP eager
                    # path too (ADVICE r4: it used to silently run
                    # fp32 under the launcher); O2 additionally had
                    # its params cast by decorate() in prepare()
                    level = getattr(self, "_amp_level", None)
                    with auto_cast(enable=level in ("O1", "O2"),
                                   level=level or "O1",
                                   dtype=self._amp_dtype):
                        out = self.network(*inputs)
                        outs = out if isinstance(out, (list, tuple)) \
                            else (out,)
                        loss = self._loss(*outs, *labels)
                    loss.backward()
                    self._optimizer.step()
                    self._optimizer.clear_grad()
                    return loss

                self._train_step = eager_step
            else:
                from ..jit.train_step import TrainStep

                self._train_step = TrainStep(
                    self.network, self._loss, self._optimizer,
                    amp_level=getattr(self, "_amp_level", None),
                    amp_dtype=getattr(self, "_amp_dtype", "bfloat16"))
        return self._train_step

    def _loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            if getattr(self, "_distributed", False):
                from ..io import DistributedBatchSampler

                bs = DistributedBatchSampler(
                    data, batch_size=batch_size, shuffle=shuffle,
                    drop_last=drop_last)
                return DataLoader(data, batch_sampler=bs,
                                  num_workers=num_workers)
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers)
        return data  # any iterable of batches

    @staticmethod
    def _split_batch(batch):
        """(inputs, labels) from a DataLoader batch: last element is the
        label (reference feed convention)."""
        if isinstance(batch, (list, tuple)):
            bs = [_to_tensor(b) for b in batch]
            if len(bs) == 1:
                return bs, []
            return bs[:-1], bs[-1:]
        return [_to_tensor(batch)], []

    # ------------------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        step = self._ensure_step()
        inputs = [_to_tensor(i) for i in (
            inputs if isinstance(inputs, (list, tuple)) else [inputs])]
        labels = [_to_tensor(l) for l in (
            labels if isinstance(labels, (list, tuple)) else
            ([labels] if labels is not None else []))]
        loss = step(inputs, labels)
        return [float(loss.numpy())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = [_to_tensor(i) for i in (
            inputs if isinstance(inputs, (list, tuple)) else [inputs])]
        labels = [_to_tensor(l) for l in (
            labels if isinstance(labels, (list, tuple)) else
            ([labels] if labels is not None else []))]
        with engine.no_grad():
            out = self.network(*inputs)
            outs = out if isinstance(out, (list, tuple)) else (out,)
            loss = self._loss(*outs, *labels) if self._loss else None
            for m in self._metrics:
                m.update(np.asarray(m.compute(outs[0], *labels)._data))
        self.network.train()
        res = [float(loss.numpy())] if loss is not None else []
        return res, [m.accumulate() for m in self._metrics]

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = [_to_tensor(i) for i in (
            inputs if isinstance(inputs, (list, tuple)) else [inputs])]
        with engine.no_grad():
            out = self.network(*inputs)
        self.network.train()
        outs = out if isinstance(out, (list, tuple)) else (out,)
        return [np.asarray(o._data) for o in outs]

    # ------------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None):
        """(model.py fit:1756)"""
        loader = self._loader(train_data, batch_size, shuffle, drop_last,
                              num_workers)
        eval_loader = self._loader(eval_data, batch_size, False, False,
                                   num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=[m.name() for m in self._metrics])
        self._ensure_step()
        self.stop_training = False
        self.network.train()

        cbks.on_train_begin()
        history_logs = {}
        for epoch in range(epochs):
            if self.stop_training:
                break
            sampler = getattr(loader, "batch_sampler", None)
            if sampler is not None and hasattr(sampler, "set_epoch"):
                # distributed sampler reshuffles per epoch (the
                # reference's fit calls set_epoch the same way)
                sampler.set_epoch(epoch)
            cbks.on_epoch_begin(epoch)
            losses = []
            for step_i, batch in enumerate(loader):
                cbks.on_train_batch_begin(step_i)
                inputs, labels = self._split_batch(batch)
                loss = self.train_batch(inputs, labels)
                losses.append(loss[0])
                cbks.on_train_batch_end(step_i, {"loss": loss[0]})
            history_logs = {"loss": float(np.mean(losses))
                            if losses else 0.0}
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(
                    eval_loader, batch_size=batch_size, verbose=0,
                    num_workers=num_workers, _cbks=cbks)
                history_logs.update(
                    {f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, history_logs)
            if self.stop_training:
                break
        cbks.on_train_end(history_logs)
        hist = [c for c in cbks.callbacks if type(c).__name__ == "History"]
        return hist[0].history if hist else {}

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _cbks=None):
        """(model.py evaluate)"""
        loader = self._loader(eval_data, batch_size, False, False,
                              num_workers)
        cbks = _cbks or config_callbacks(
            callbacks, model=self, epochs=1,
            steps=len(loader) if hasattr(loader, "__len__") else None,
            verbose=verbose)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        losses = []
        for step_i, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step_i)
            inputs, labels = self._split_batch(batch)
            res, _ = self.eval_batch(inputs, labels)
            if res:
                losses.append(res[0])
            cbks.on_eval_batch_end(step_i,
                                   {"loss": res[0] if res else None})
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        """(model.py predict)"""
        loader = self._loader(test_data, batch_size, False, False,
                              num_workers)
        outputs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(inputs))
        if not outputs:
            return []
        n_out = len(outputs[0])
        grouped = [[o[i] for o in outputs] for i in range(n_out)]
        if stack_outputs:
            return [np.concatenate(g, axis=0) for g in grouped]
        return grouped

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        """(model.py save): '<path>.pdparams' + '<path>.pdopt'."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        from ..framework.io import save as fsave

        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload

        self.network.set_state_dict(fload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        """(hapi summary) — delegates to the standalone report so both
        entry points stay consistent."""
        from .dynamic_flops import summary as _summary

        return _summary(self.network, input_size=input_size,
                        dtypes=dtype)
