"""paddle_tpu.incubate — experimental features (reference:
python/paddle/incubate: MoE, fused ops, autotune)."""
from . import moe  # noqa: F401
from . import nn  # noqa: F401
from .moe import MoELayer  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from . import autotune  # noqa: F401
from . import multiprocessing  # noqa: F401
