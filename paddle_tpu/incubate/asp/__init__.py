"""incubate.asp — automatic structured (2:4) sparsity.

TPU-native equivalent of the reference's ASP package (reference:
python/paddle/incubate/asp — prune_model, decorate, ASPHelper,
calculate_density, check_mask_1d/2d; utils.py mask algorithms). The
reference targets Ampere sparse tensor cores; on TPU 2:4 sparsity is a
model-compression technique (the MXU has no sparse mode), so masks are
applied as weight multiplications that XLA folds into the matmul.
Mask semantics match the reference's ``mask_1d``: best-magnitude
n-of-m groups along the LAST axis, per row.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.layer_base import Layer

__all__ = ["calculate_density", "check_mask_1d", "check_mask_2d",
           "create_mask", "prune_model", "decorate", "ASPHelper"]

_MASK_BUFFER = "_asp_mask"


def calculate_density(x) -> float:
    """Fraction of nonzeros (reference: asp/utils.py calculate_density)."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _row_groups(arr: np.ndarray, m: int):
    """[rows, ceil(cols/m), m] zero-padded groups along the last axis —
    groups never straddle rows (reference mask_1d grouping)."""
    rows = arr.reshape(-1, arr.shape[-1])
    pad = (-rows.shape[1]) % m
    padded = np.pad(rows, ((0, 0), (0, pad)))
    return padded.reshape(rows.shape[0], -1, m), pad


def create_mask(weight, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d") -> np.ndarray:
    """Best-magnitude n-of-m mask per last-axis group (reference:
    asp/utils.py create_mask with mask_1d)."""
    if mask_algo != "mask_1d":
        raise NotImplementedError(
            f"mask_algo {mask_algo!r}: only 'mask_1d' is implemented "
            "(the reference's 2-D block algorithms target sparse tensor "
            "cores the TPU doesn't have)")
    arr = np.asarray(weight._data if isinstance(weight, Tensor)
                     else weight)
    groups, pad = _row_groups(np.abs(arr), m)
    idx = np.argsort(groups, axis=-1)[..., m - n:]
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, idx, 1.0, axis=-1)
    rows = mask.reshape(mask.shape[0], -1)
    if pad:
        rows = rows[:, :-pad]
    return rows.reshape(arr.shape).astype(arr.dtype)


def check_mask_1d(mat, n: int = 2, m: int = 4) -> bool:
    """Every last-axis m-group (per row) has ≤ n nonzeros (reference:
    asp/utils.py check_mask_1d)."""
    arr = np.asarray(mat._data if isinstance(mat, Tensor) else mat)
    groups, _ = _row_groups(np.abs(arr), m)
    return bool((np.count_nonzero(groups, axis=-1) <= n).all())


def check_mask_2d(mat, n: int = 2, m: int = 4) -> bool:
    """Every m×m block has ≤ n nonzeros per row AND per column
    (reference: asp/utils.py check_mask_2d)."""
    arr = np.asarray(mat._data if isinstance(mat, Tensor) else mat)
    a = arr.reshape(-1, arr.shape[-1])
    pad_r = (-a.shape[0]) % m
    pad_c = (-a.shape[1]) % m
    a = np.pad(np.abs(a), ((0, pad_r), (0, pad_c)))
    blocks = a.reshape(a.shape[0] // m, m, a.shape[1] // m, m)
    blocks = blocks.transpose(0, 2, 1, 3)  # [br, bc, m, m]
    row_ok = (np.count_nonzero(blocks, axis=-1) <= n).all()
    col_ok = (np.count_nonzero(blocks, axis=-2) <= n).all()
    return bool(row_ok and col_ok)


class ASPHelper:
    """Pruning driver (reference: asp/asp.py ASPHelper). Masks are
    stored as non-persistable DEVICE buffers on the pruned layer — no
    global registry (no id-reuse hazard, no per-step host transfer,
    lifetime tied to the layer)."""

    @classmethod
    def supported(cls, layer: Layer) -> bool:
        from ...nn.layers.common import Linear

        return isinstance(layer, Linear)

    @classmethod
    def prune_model(cls, model: Layer, n: int = 2, m: int = 4,
                    mask_algo: str = "mask_1d") -> Dict[str, float]:
        """Apply n:m masks to every supported layer's weight in place;
        returns per-param density (reference: asp.py prune_model)."""
        report = {}
        for name, sub in model.named_sublayers(include_self=True):
            if not cls.supported(sub):
                continue
            w = sub.weight
            mask = jnp.asarray(create_mask(w, n=n, m=m,
                                           mask_algo=mask_algo))
            w._rebind(w._data * mask)
            sub.register_buffer(_MASK_BUFFER, Tensor(mask),
                                persistable=False)
            report[f"{name}.weight" if name else "weight"] = \
                calculate_density(w)
        return report

    @classmethod
    def reapply_masks(cls, model: Layer) -> None:
        """Re-zero pruned positions (wrapped around optimizer updates
        by ``decorate``)."""
        for _, sub in model.named_sublayers(include_self=True):
            mask = sub._buffers.get(_MASK_BUFFER) \
                if cls.supported(sub) else None
            if mask is not None:
                sub.weight._rebind(sub.weight._data * mask._data)


def prune_model(model: Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d"):
    return ASPHelper.prune_model(model, n=n, m=m, mask_algo=mask_algo)


class _ASPOptimizerWrapper:
    """Optimizer wrapper re-applying masks after each update (reference:
    asp.py decorate → OptimizerWithSparsityGuarantee, which intercepts
    BOTH step and minimize)."""

    def __init__(self, optimizer, model: Layer):
        self._inner = optimizer
        self._model = model

    def step(self):
        out = self._inner.step()
        ASPHelper.reapply_masks(self._model)
        return out

    def minimize(self, loss, *args, **kwargs):
        out = self._inner.minimize(loss, *args, **kwargs)
        ASPHelper.reapply_masks(self._model)
        return out

    def __getattr__(self, item):
        return getattr(self._inner, item)


def decorate(model: Layer, optimizer):
    """Wrap (model, optimizer) so sparsity survives training updates
    (reference: asp.py decorate)."""
    return model, _ASPOptimizerWrapper(optimizer, model)
