"""incubate.autograd — functional differentiation transforms.

TPU-native equivalent of the reference's functional autograd (reference:
python/paddle/incubate/autograd — jvp/vjp primitives, Jacobian/Hessian
lazy matrices, forward_grad over the primitive program). Here the
transforms delegate to jax's (the decomposition/primitive machinery the
reference builds by hand IS jax's trace-and-transform core); inputs and
outputs stay paddle Tensors.
"""
from __future__ import annotations

from typing import Callable, List, Sequence, Union

import jax
import jax.numpy as jnp

from ...core import engine
from ...core.tensor import Tensor

__all__ = ["jvp", "vjp", "jacobian", "hessian", "Jacobian", "Hessian",
           "grad_fn"]


def _tensorize(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _listify(xs):
    if isinstance(xs, Tensor) or not isinstance(xs, (list, tuple)):
        return [_tensorize(xs)]  # single Tensor or raw array/scalar
    return [_tensorize(x) for x in xs]


def _functionalize(func: Callable, xs: List[Tensor]):
    """func over Tensors -> pure fn over raw arrays (no_grad inside:
    the transform owns differentiation, the tape must not record)."""

    def raw(*arrays):
        with engine.no_grad():
            out = func(*[Tensor(a) for a in arrays])
        outs = out if isinstance(out, (tuple, list)) else (out,)
        res = tuple(o._data for o in outs)
        return res if len(res) > 1 else res[0]

    return raw


def vjp(func: Callable, xs, v=None):
    """(outputs, vjp_result) — reference: incubate/autograd/primapi.py
    vjp. v defaults to ones like the output."""
    xs = _listify(xs)
    raw = _functionalize(func, xs)
    primals, vjp_fn = jax.vjp(raw, *[x._data for x in xs])
    outs = primals if isinstance(primals, tuple) else (primals,)
    if v is None:
        cots = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
    else:
        vs = _listify(v)
        cots = tuple(t._data for t in vs)
    grads = vjp_fn(cots if len(outs) > 1 else cots[0])
    out_t = tuple(Tensor(o) for o in outs)
    grad_t = [Tensor(g) for g in grads]
    return (out_t[0] if len(out_t) == 1 else out_t,
            grad_t[0] if len(grad_t) == 1 else grad_t)


def jvp(func: Callable, xs, v=None):
    """(outputs, jvp_result) — forward-mode (reference: primapi.py jvp,
    forward_grad)."""
    xs = _listify(xs)
    raw = _functionalize(func, xs)
    prim = [x._data for x in xs]
    if v is None:
        tans = [jnp.ones(p.shape, p.dtype) for p in prim]
    else:
        tans = [t._data for t in _listify(v)]
    primals, tangents = jax.jvp(raw, tuple(prim), tuple(tans))
    outs = primals if isinstance(primals, tuple) else (primals,)
    touts = tangents if isinstance(tangents, tuple) else (tangents,)
    o = tuple(Tensor(x) for x in outs)
    t = tuple(Tensor(x) for x in touts)
    return (o[0] if len(o) == 1 else o, t[0] if len(t) == 1 else t)


def jacobian(func: Callable, xs) -> Union[Tensor, List]:
    """Dense Jacobian(s) of func at xs (reference: functional Jacobian).

    Single input + single output -> Tensor [*out_shape, *in_shape];
    multiple inputs -> list over inputs; multiple outputs -> list over
    outputs (nested [output][input] when both are multiple)."""
    xs = _listify(xs)
    raw = _functionalize(func, xs)
    # probe output arity via an abstract trace (no FLOPs)
    probe = jax.eval_shape(raw, *[x._data for x in xs])
    multi_out = isinstance(probe, tuple)
    jac = jax.jacrev(raw, argnums=tuple(range(len(xs))))(
        *[x._data for x in xs])
    # jacrev mirrors f's output structure; per output there is a tuple
    # over argnums
    if not multi_out:
        per_in = jac
        if len(xs) == 1:
            return Tensor(per_in[0])
        return [Tensor(j) for j in per_in]
    rows = []
    for per_in in jac:  # one entry per output
        if len(xs) == 1:
            rows.append(Tensor(per_in[0]))
        else:
            rows.append([Tensor(j) for j in per_in])
    return rows


def hessian(func: Callable, xs) -> Tensor:
    """Dense Hessian of a scalar-output func (reference: functional
    Hessian)."""
    xs = _listify(xs)
    if len(xs) != 1:
        raise NotImplementedError("hessian supports a single input")
    raw = _functionalize(func, xs)
    h = jax.hessian(raw)(xs[0]._data)
    return Tensor(h)


# lazy-matrix API parity (reference returns lazily-evaluated objects)
class Jacobian:
    def __init__(self, func, xs, is_batched=False):
        if is_batched:
            raise NotImplementedError(
                "is_batched=True (per-batch Jacobian) is not supported; "
                "vmap the function over the batch dim instead")
        if isinstance(xs, (list, tuple)) and len(xs) > 1:
            raise NotImplementedError(
                "the lazy-matrix API supports a single input; use "
                "jacobian() for the multi-input list form")
        val = jacobian(func, xs)
        if isinstance(val, list):
            raise NotImplementedError(
                "the lazy-matrix API supports a single output; use "
                "jacobian() for the multi-output form")
        self._val = val

    def __getitem__(self, idx):
        return Tensor(self._val._data[idx])

    @property
    def shape(self):
        return self._val.shape


class Hessian(Jacobian):
    def __init__(self, func, xs, is_batched=False):
        if is_batched:
            raise NotImplementedError(
                "is_batched=True is not supported; vmap over the batch "
                "dim instead")
        self._val = hessian(func, xs)


def grad_fn(func: Callable):
    """Convenience: df/dx as a callable (jax.grad over Tensor fns)."""

    def g(*xs):
        xs_t = [_tensorize(x) for x in xs]
        raw = _functionalize(func, xs_t)
        if isinstance(jax.eval_shape(raw, *[x._data for x in xs_t]),
                      tuple):  # abstract trace: no extra forward
            raise NotImplementedError(
                "grad_fn supports single-output functions; sum or "
                "select one output, or use vjp() for multi-output")
        grads = jax.grad(lambda *a: jnp.sum(raw(*a)),
                         argnums=tuple(range(len(xs_t))))(
            *[x._data for x in xs_t])
        out = [Tensor(g_) for g_ in grads]
        return out[0] if len(out) == 1 else out

    return g
