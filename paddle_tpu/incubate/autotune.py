"""incubate.autotune — kernel/layout/dataloader auto-tuning config.

TPU-native equivalent of the reference's autotune surface (reference:
python/paddle/incubate/autotune.py set_config:24 — kernel exhaustive
search, layout NCHW/NHWC selection, dataloader num_workers tuning).
On TPU the kernel-level exhaustive search is XLA's own autotuner
(latency-hiding scheduler + Triton-free matmul tiling), so the kernel
knob maps to XLA autotune level; layout tuning maps to letting XLA pick
layouts (it always does); dataloader tuning is implemented in
``paddle_tpu.io`` the reference's way (probe num_workers over warmup
steps and keep the fastest).
"""
from __future__ import annotations

import json
import warnings

__all__ = ["set_config", "get_config"]

_CONFIG = {
    "kernel": {"enable": True, "tuning_range": [1, 10]},
    "layout": {"enable": True},
    "dataloader": {"enable": False, "tuning_steps": 500},
}


def set_config(config=None):
    """(reference autotune.py:24) Accepts a dict or a json file path;
    None enables everything."""
    global _CONFIG
    if config is None:
        for sec in _CONFIG.values():
            sec["enable"] = True
        _apply()
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError("config must be None, a dict or a json path")
    for key in ("kernel", "layout", "dataloader"):
        if key in config:
            sec = config[key]
            if not isinstance(sec, dict):
                warnings.warn(f"autotune config [{key}] must be a dict")
                continue
            _CONFIG[key].update(sec)
    _apply()


def get_config():
    return {k: dict(v) for k, v in _CONFIG.items()}


def _apply():
    """Map the knobs onto the XLA/runtime equivalents."""
    import os

    if _CONFIG["kernel"]["enable"]:
        # XLA autotune level 4 = exhaustive candidate search (the
        # reference's cudnn exhaustive-search counterpart)
        os.environ.setdefault("XLA_FLAGS", "")
        if "--xla_gpu_autotune_level" not in os.environ["XLA_FLAGS"]:
            pass  # TPU backend autotunes unconditionally; nothing to set
    from ..io import dataloader as _dl

    _dl.AUTOTUNE_NUM_WORKERS = bool(_CONFIG["dataloader"]["enable"])
    _dl.AUTOTUNE_STEPS = int(_CONFIG["dataloader"].get(
        "tuning_steps", 500))
