from .moe_layer import ExpertFFN, GShardGate, MoELayer, NaiveGate, SwitchGate  # noqa: F401
