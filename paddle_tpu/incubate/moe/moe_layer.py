"""Mixture-of-Experts layer.

TPU-native equivalent of the reference's MoE (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py:263 MoELayer,
gates gshard_gate.py/switch_gate.py/naive_gate.py; expert-parallel
dispatch via global_scatter/global_gather all-to-all
fluid/operators/collective/global_scatter_op.cu; cutlass grouped-GEMM
moe_kernel.cu). Two formulations live here:

- **capacity-factor (GShard einsum)**: top-k gate → capacity-bounded
  one-hot dispatch/combine tensors → einsum dispatch → per-expert FFN
  (stacked weights; one batched matmul on the MXU) → einsum combine.
  Over-capacity assignments DROP (counted in ``moe.dropped_tokens``).
- **no-drop ragged (``capacity_factor=None``, ISSUE 15)**: the stacked
  path routes through ``nn.functional.grouped_gemm.moe_ffn_nodrop`` —
  fp32 router → tokens stable-sorted by expert → two ragged grouped
  GEMMs → scatter-combine. ZERO capacity padding, ZERO dropped tokens,
  and no ``[T, E, capacity]`` intermediate anywhere in the trace.

Gate routing (softmax, top-k, top-k renormalization) runs in fp32 on
EVERY path regardless of AMP dtype: bf16 router probs make top-k ties
and the combine normalization unstable (pinned by the bf16-vs-fp32
routing-parity test). Expert parallelism = shard the expert dim of the
stacked weights over the mesh's ep/mp axis; GSPMD emits the all-to-all
the reference launches by hand.
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer_base import Layer, LayerList
from ...ops.dispatch import as_tensor_args, eager_apply

__all__ = ["MoELayer", "NaiveGate", "GShardGate", "SwitchGate", "ExpertFFN"]


def _count_dropped(drop):
    """Surface capacity-overflow drops on the EAGER path: bump the
    ``moe.dropped_tokens`` stats counter with this forward's dropped
    token->expert assignment count. The count is data-dependent (it
    comes off the device), so it is only fetched while the registry is
    enabled; inside a fully jit-compiled step the counter is not
    updated (the traced body runs once per compile) — silent-drop
    debugging is an eager/profiling activity."""
    from ...profiler import stats as _stats

    if not _stats.is_enabled():
        return
    arr = drop._data if isinstance(drop, Tensor) else drop
    if isinstance(arr, jax.core.Tracer):
        return  # under trace (TrainStep/jit): no per-execution count
    _stats.inc("moe.dropped_tokens", int(float(np.asarray(arr))))


def _stamp_moe_stats(counts):
    """Per-forward routing telemetry on the EAGER path: observe each
    expert's assignment count into the ``moe.tokens_per_expert``
    histogram and stamp the ``moe.imbalance`` gauge (max/mean expert
    load; 1.0 = perfectly balanced). Like ``_count_dropped``, this is
    data-dependent and therefore eager/profiling-only — inside a
    jit-compiled step the traced body runs once per compile."""
    from ...profiler import stats as _stats

    if not _stats.is_enabled():
        return
    arr = counts._data if isinstance(counts, Tensor) else counts
    if isinstance(arr, jax.core.Tracer):
        return
    c = np.asarray(arr, np.float64).reshape(-1)
    if not c.size:
        return
    for v in c:
        _stats.observe("moe.tokens_per_expert", float(v))
    mean = float(c.mean())
    _stats.set_gauge("moe.imbalance",
                     float(c.max()) / mean if mean > 0 else 0.0)


class BaseGate(Layer):
    def __init__(self, d_model: int, num_experts: int, top_k: int):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.weight = self.create_parameter(
            shape=[d_model, num_experts],
            default_initializer=I.XavierUniform())


class NaiveGate(BaseGate):
    """top-k softmax gate, no auxiliary loss (naive_gate.py)."""

    aux_loss_weight = 0.0


class GShardGate(BaseGate):
    """GShard gate: top-2 + load-balancing aux loss (gshard_gate.py)."""

    def __init__(self, d_model, num_experts, top_k=2, capacity_factor=1.25):
        super().__init__(d_model, num_experts, top_k)
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = 1e-2


class SwitchGate(BaseGate):
    """Switch Transformer gate: top-1 (switch_gate.py)."""

    def __init__(self, d_model, num_experts, top_k=1, capacity_factor=1.25):
        super().__init__(d_model, num_experts, top_k)
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = 1e-2


class ExpertFFN(Layer):
    """Stacked-expert FFN: weights [E, d, d_ff] / [E, d_ff, d] so the whole
    expert bank is two batched matmuls (the grouped-GEMM form)."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.w1 = self.create_parameter(
            shape=[num_experts, d_model, d_hidden],
            default_initializer=I.XavierUniform())
        self.b1 = self.create_parameter(
            shape=[num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter(
            shape=[num_experts, d_hidden, d_model],
            default_initializer=I.XavierUniform())
        self.b2 = self.create_parameter(
            shape=[num_experts, 1, d_model], is_bias=True)
        self.activation = activation


class MoELayer(Layer):
    """(moe_layer.py:263 parity, GShard algebra)

    Args follow the reference loosely: ``experts`` may be an ExpertFFN
    (fast stacked path) or a list of per-expert Layers (generic path).
    """

    def __init__(self, d_model: int, experts=None, gate="gshard",
                 num_experts: Optional[int] = None, top_k: int = 2,
                 d_hidden: Optional[int] = None, capacity_factor=1.25,
                 moe_group=None, mp_group=None, recompute_interval=0,
                 ep_mesh=None, name=None):
        super().__init__()
        # ep_mesh=(mesh, axis_name): explicit expert parallelism via the
        # all-to-all dispatch the reference's MoE stack uses (reference:
        # incubate/distributed/models/moe/global_scatter → all-to-all;
        # moe/gate communication in moe_layer.py). Tokens stay sharded on
        # `axis`, experts live sharded on `axis`, and the dispatch /
        # combine are two lax.all_to_all inside a shard_map — O(tokens)
        # comm instead of the dense one-hot partial-sum reduce that the
        # GSPMD lowering of the einsum form produces.
        self._ep_mesh = ep_mesh
        if isinstance(experts, (list, LayerList)):
            if ep_mesh is not None:
                raise ValueError(
                    "ep_mesh expert parallelism needs the stacked "
                    "ExpertFFN form (pass num_experts/d_hidden or an "
                    "ExpertFFN, not a list of per-expert Layers)")
            self.experts = LayerList(list(experts))
            num_experts = len(self.experts)
            self.stacked = None
        else:
            assert num_experts is not None
            self.stacked = experts if isinstance(experts, ExpertFFN) else \
                ExpertFFN(num_experts, d_model,
                          d_hidden or 4 * d_model)
            self.experts = None
        self.num_experts = num_experts
        self.d_model = d_model

        if isinstance(gate, str):
            gate_cls = {"naive": NaiveGate, "gshard": GShardGate,
                        "switch": SwitchGate}[gate]
            if gate_cls is SwitchGate:
                top_k = 1
            self.gate = gate_cls(d_model, num_experts, top_k) \
                if gate_cls is NaiveGate else \
                gate_cls(d_model, num_experts, top_k=top_k,
                         capacity_factor=capacity_factor)
        else:
            self.gate = gate
        self.top_k = self.gate.top_k
        self.capacity_factor = getattr(self.gate, "capacity_factor",
                                       capacity_factor)
        self.aux_loss: Optional[Tensor] = None

    def _ep_forward(self, x):
        """Expert-parallel stacked path: shard_map over the ep axis with
        all-to-all dispatch/combine (see __init__ ep_mesh note)."""
        from functools import partial

        from jax.sharding import PartitionSpec as P

        try:
            from jax.experimental.shard_map import shard_map
        except ImportError:  # jax >= 0.7 moved it
            from jax import shard_map

        mesh, axis = self._ep_mesh
        jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
        ep = jmesh.shape[axis]
        E, K, d = self.num_experts, self.top_k, self.d_model
        if E % ep:
            raise ValueError(f"num_experts {E} not divisible by "
                             f"ep degree {ep}")
        orig_shape = x.shape
        # shard_map shards the LEADING dim — that is the divisibility
        # that matters, not the flattened token count
        if orig_shape[0] % ep:
            raise ValueError(f"batch dim {orig_shape[0]} not divisible "
                             f"by ep degree {ep}")
        tokens = int(np.prod(orig_shape[:-1]))
        # capacity is per (expert, shard): receive buffers CONCAT across
        # shards (no cross-shard sum), which is what makes the exchange
        # an all-to-all instead of a reduce. No-drop mode
        # (capacity_factor=None) sizes the buffers for the worst case
        # (every local assignment to one expert) so nothing can drop.
        if self.capacity_factor is None:
            capacity = max((tokens // ep) * K, 1)
        else:
            capacity = max(int(math.ceil((tokens // ep) * K *
                                         self.capacity_factor / E)), 1)
        st = self.stacked
        act = jax.nn.gelu if st.activation == "gelu" else jax.nn.relu
        aux_w = getattr(self.gate, "aux_loss_weight", 0.0)
        nd = len(orig_shape)
        x_spec = P(*([axis] + [None] * (nd - 1)))
        w_spec = P(axis)

        def raw(xa, wg, w1, b1, w2, b2):
            def body(x_loc, wg_, w1_loc, b1_loc, w2_loc, b2_loc):
                xt = x_loc.reshape(-1, d)
                # tpu-lint: ok(X-PROMOTE) -- fp32 gate routing by design
                probs = jax.nn.softmax(
                    xt.astype(jnp.float32) @ wg_.astype(jnp.float32),
                    -1)
                combine, dispatch, aux, drop, cnt = _gshard_dispatch(
                    probs, E, K, capacity)
                combine = combine.astype(xt.dtype)
                dispatch = dispatch.astype(xt.dtype)
                exp_in = jnp.einsum("tec,td->ecd", dispatch, xt)
                # [E, c, d] -> [E/ep, ep*c, d]: rows for MY experts from
                # every shard land here, capacities concatenated
                recv = jax.lax.all_to_all(exp_in, axis, split_axis=0,
                                          concat_axis=1, tiled=True)
                h = act(jnp.einsum("ecd,edf->ecf", recv, w1_loc) + b1_loc)
                out = jnp.einsum("ecf,efd->ecd", h, w2_loc) + b2_loc
                # reverse exchange: [E/ep, ep*c, d] -> [E, c, d]
                back = jax.lax.all_to_all(out, axis, split_axis=1,
                                          concat_axis=0, tiled=True)
                y = jnp.einsum("tec,ecd->td", combine, back)
                return (y.reshape(x_loc.shape),
                        jax.lax.pmean(aux, axis),
                        jax.lax.psum(drop, axis),
                        jax.lax.psum(cnt, axis))

            y, aux, drop, cnt = shard_map(
                body, mesh=jmesh,
                in_specs=(x_spec, P(), w_spec, w_spec, w_spec, w_spec),
                out_specs=(x_spec, P(), P(), P()))(xa, wg, w1, b1, w2,
                                                   b2)
            # zero-weight edge tying aux into the differentiated
            # output: when a whole-step AD (TrainStep) never consumes
            # aux, shard_map's transpose would otherwise receive a
            # symbolic-Zero cotangent for it and psum can't transpose
            # that (drop is int32 — non-differentiable by dtype — so
            # it needs no edge); XLA folds the multiply away
            y = y + (jnp.zeros((), y.dtype) * aux.astype(y.dtype))
            return y, aux, drop, cnt

        tensors = as_tensor_args(x, self.gate.weight, st.w1, st.b1,
                                 st.w2, st.b2)
        out, aux, drop, cnt = eager_apply("moe_layer_ep", raw, tensors,
                                          n_outputs=4)
        self.aux_loss = aux * aux_w if aux_w else aux
        _count_dropped(drop)
        _stamp_moe_stats(cnt)
        return out

    def _nodrop_forward(self, x):
        """No-drop stacked path (``capacity_factor=None``): fp32 router
        → stable sort by expert → ragged grouped-GEMM FFN →
        scatter-combine. Zero capacity padding, zero drops, no
        ``[T, E, C]`` intermediate in the traced program."""
        from ...core.flags import flag
        from ...nn.functional.grouped_gemm import moe_ffn_nodrop

        orig_shape = x.shape
        d = self.d_model
        tokens = int(np.prod(orig_shape[:-1]))
        E, K = self.num_experts, self.top_k
        aux_w = getattr(self.gate, "aux_loss_weight", 0.0)
        st = self.stacked
        act = st.activation
        backend = flag("moe_grouped_backend")
        tensors = as_tensor_args(x, self.gate.weight, st.w1, st.b1,
                                 st.w2, st.b2)

        def raw(xa, wg, w1, b1, w2, b2):
            xt = xa.reshape(tokens, d)
            y, probs, topk_idx, counts = moe_ffn_nodrop(
                xt, wg, w1, b1.reshape(E, -1), w2, b2.reshape(E, -1),
                top_k=K, activation=act, backend=backend)
            # load-balance aux loss: the same GShard formula as the
            # capacity path (fp32 probs)
            me = jnp.mean(probs, axis=0)
            ce = jnp.mean(jax.nn.one_hot(topk_idx[:, 0], E,
                                         dtype=probs.dtype), axis=0)
            aux = jnp.sum(me * ce) * E
            return y.reshape(xa.shape), aux, counts

        out, aux, cnt = eager_apply("moe_layer_nodrop", raw, tensors,
                                    n_outputs=3)
        self.aux_loss = aux * aux_w if aux_w else aux
        # no-drop by construction — the counter moves by exactly 0, so
        # drop-rate dashboards see the mode switch, not a gap
        _count_dropped(jnp.zeros((), jnp.int32))
        _stamp_moe_stats(cnt)
        return out

    def forward(self, x):
        orig_shape = x.shape
        d = self.d_model
        tokens = int(np.prod(orig_shape[:-1]))
        E, K = self.num_experts, self.top_k
        if self.capacity_factor is None and self._ep_mesh is None:
            if self.stacked is None:
                raise ValueError(
                    "no-drop MoE (capacity_factor=None) needs the "
                    "stacked ExpertFFN form — heterogeneous per-expert "
                    "Layers still route through the capacity-bounded "
                    "dispatch")
            return self._nodrop_forward(x)
        capacity = None if self.capacity_factor is None else max(
            int(math.ceil(tokens * K * self.capacity_factor / E)), 1)
        aux_w = getattr(self.gate, "aux_loss_weight", 0.0)

        if self._ep_mesh is not None and self.stacked is not None:
            return self._ep_forward(x)

        if self.stacked is not None:
            st = self.stacked
            act = st.activation
            tensors = as_tensor_args(x, self.gate.weight, st.w1, st.b1,
                                     st.w2, st.b2)

            def raw(xa, wg, w1, b1, w2, b2):
                xt = xa.reshape(tokens, d)
                # tpu-lint: ok(X-PROMOTE) -- fp32 gate routing by design
                logits = xt.astype(jnp.float32) \
                    @ wg.astype(jnp.float32)                   # [T, E]
                probs = jax.nn.softmax(logits, -1)
                combine, dispatch, aux, drop, cnt = _gshard_dispatch(
                    probs, E, K, capacity)
                combine = combine.astype(xt.dtype)
                dispatch = dispatch.astype(xt.dtype)
                # dispatch: [T, E, C] → expert inputs [E, C, d]
                exp_in = jnp.einsum("tec,td->ecd", dispatch, xt)
                h = exp_in @ w1 + b1                           # [E, C, ff]
                h = jax.nn.gelu(h) if act == "gelu" else jax.nn.relu(h)
                exp_out = h @ w2 + b2                          # [E, C, d]
                out = jnp.einsum("tec,ecd->td", combine, exp_out)
                return out.reshape(xa.shape), aux, drop, cnt

            out, aux, drop, cnt = eager_apply("moe_layer", raw, tensors,
                                              n_outputs=4)
            self.aux_loss = aux * aux_w if aux_w else aux
            _count_dropped(drop)
            _stamp_moe_stats(cnt)
            return out

        # generic per-expert path (heterogeneous experts); gate grads flow
        # through the combine weights produced by the dispatch op
        xt = x.reshape([tokens, d])

        def raw_dispatch(xa, wg):
            # tpu-lint: ok(X-PROMOTE) -- fp32 gate routing by design
            logits = xa.astype(jnp.float32) @ wg.astype(jnp.float32)
            probs = jax.nn.softmax(logits, -1)
            combine, dispatch, aux, drop, cnt = _gshard_dispatch(
                probs, E, K, capacity)
            combine = combine.astype(xa.dtype)
            dispatch = dispatch.astype(xa.dtype)
            exp_in = jnp.einsum("tec,td->ecd", dispatch, xa)
            return exp_in, combine, aux, drop, cnt

        exp_in_all, combine_t, aux, drop, cnt = eager_apply(
            "moe_dispatch", raw_dispatch,
            as_tensor_args(xt, self.gate.weight), n_outputs=5)
        _count_dropped(drop)
        _stamp_moe_stats(cnt)
        outs = []
        for e, expert in enumerate(self.experts):
            outs.append(expert(exp_in_all[e]))
        import paddle_tpu as paddle

        exp_out = paddle.stack(outs, axis=0)
        out = eager_apply(
            "moe_combine",
            lambda c, eo: jnp.einsum("tec,ecd->td", c, eo),
            as_tensor_args(combine_t, exp_out))
        self.aux_loss = aux * aux_w if aux_w else aux
        return out.reshape(orig_shape)


def _gshard_dispatch(probs, E, K, capacity):
    """GShard top-K dispatch with capacity (pure jnp; differentiable
    through the combine weights).

    Returns (combine, dispatch, aux, dropped, counts): ``dropped``
    (int32 scalar) is the number of token->expert assignments discarded
    by the capacity bound this batch, counted exactly per top-k pass —
    the eager MoELayer forward surfaces it as the
    ``moe.dropped_tokens`` stats counter so capacity-overflow drops
    are observable instead of silent. ``counts`` (int32 [E]) is the
    per-expert ROUTED assignment count (before the capacity bound) —
    the ``moe.tokens_per_expert`` / ``moe.imbalance`` telemetry."""
    T = probs.shape[0]
    topk_val, topk_idx = jax.lax.top_k(probs, K)              # [T, K]
    # normalize selected probabilities
    topk_val = topk_val / jnp.sum(topk_val, -1, keepdims=True)

    combine = jnp.zeros((T, E, capacity), probs.dtype)
    dispatch = jnp.zeros((T, E, capacity), probs.dtype)
    # running per-expert slot base across the K passes: k=0 assignments
    # claim the leading slots, k=1 continues after them (GShard's
    # priority ordering) — WITHOUT this, pass k's counts restart at 0
    # and two different tokens share a slot, so the expert sees the SUM
    # of their activations (r5 fix; pinned by the identity-property test)
    # slot bookkeeping runs in fp32 regardless of probs.dtype: under AMP
    # O2 probs are bf16, which represents integers exactly only up to
    # 256 — a bf16 cumsum over more tokens rounds increments away and
    # two tokens silently share a slot (the exact corruption the `base`
    # fix prevents)
    base = jnp.zeros((E,), jnp.float32)
    dropped = jnp.zeros((), jnp.int32)
    for k in range(K):
        idx = topk_idx[:, k]                                  # [T]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)    # [T, E]
        # position within expert buffer (running count per expert)
        pos_in_e = (jnp.cumsum(onehot, axis=0) - 1
                    + base[None, :]) * onehot                 # [T, E]
        pos = jnp.sum(pos_in_e, axis=-1).astype(jnp.int32)    # [T]
        keep = pos < capacity
        dropped = dropped + (T - jnp.sum(keep.astype(jnp.int32)))
        pos_cap = jnp.clip(pos, 0, capacity - 1)
        cap_onehot = jax.nn.one_hot(pos_cap, capacity,
                                    dtype=probs.dtype)        # [T, C]
        mask = (onehot.astype(probs.dtype)
                * keep[:, None].astype(probs.dtype))
        disp_k = mask[:, :, None] * cap_onehot[:, None, :]    # [T, E, C]
        dispatch = dispatch + disp_k
        combine = combine + disp_k * topk_val[:, k][:, None, None]
        base = base + jnp.sum(onehot, axis=0)

    # load-balance aux loss (gshard): E * sum_e(frac_tokens_e * mean_prob_e)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(
        jax.nn.one_hot(topk_idx[:, 0], E, dtype=probs.dtype), axis=0)
    aux = jnp.sum(me * ce) * E
    # int32 on purpose: exact under AMP (a bf16 dispatch.sum() rounds
    # past 256), and non-differentiable by dtype so the ep path's
    # shard_map psum never sees a symbolic-zero cotangent for it
    return combine, dispatch, aux, dropped, base.astype(jnp.int32)
