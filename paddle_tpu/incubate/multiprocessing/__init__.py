"""incubate.multiprocessing — Tensor-aware multiprocessing.

TPU-native equivalent of the reference's incubate.multiprocessing
(reference: python/paddle/incubate/multiprocessing/__init__.py +
reductions.py — registers pickle reducers so paddle Tensors cross
process boundaries via shared memory). Device memory on TPU is
process-private (PJRT), so tensors are reduced to host numpy buffers —
the same contract the reference's CPU path provides: the receiving
process gets an equal-valued Tensor, re-uploaded on first device use.
"""
from __future__ import annotations

import multiprocessing as _std_mp
from multiprocessing import *  # noqa: F401,F403  (Process, Queue, ...)

import numpy as np

from ...core.tensor import Tensor

__all__ = list(getattr(_std_mp, "__all__", [])) + ["reductions"]


def _reduce_tensor(t: Tensor):
    # host round-trip: the only portable cross-process form under PJRT.
    # The CLASS rides along: copyreg dispatch is also what copy.deepcopy
    # consults, so reducing a Parameter to a plain Tensor would demote
    # params in deepcopied Layers (e.g. TransformerEncoder's per-layer
    # deepcopy) and break optimizers downstream.
    return _rebuild_tensor, (type(t), np.asarray(t._data),
                             t.stop_gradient)


def _rebuild_tensor(cls, arr, stop_gradient):
    out = cls(arr)
    out.stop_gradient = stop_gradient
    return out


class reductions:
    """(reference reductions.py) — ``init_reductions`` registers the
    Tensor reducer with copyreg so every stdlib-multiprocessing channel
    (Queue, Pipe, Pool) can carry Tensors."""

    _installed = False

    @classmethod
    def init_reductions(cls):
        if cls._installed:
            return
        import copyreg

        copyreg.pickle(Tensor, _reduce_tensor)
        from ...core.tensor import Parameter

        copyreg.pickle(Parameter, _reduce_tensor)
        cls._installed = True


reductions.init_reductions()
