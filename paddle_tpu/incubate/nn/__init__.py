"""incubate.nn — fused layers (reference: python/paddle/incubate/nn)."""
from . import functional  # noqa: F401
from .fused_transformer import (  # noqa: F401
    FusedMultiTransformer, PagedKV, qkv_split_rope_fused, rope_table)
from .layers import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd, FusedEcMoe,
    FusedFeedForward, FusedLinear, FusedMultiHeadAttention,
    FusedTransformerEncoderLayer)

__all__ = ["FusedMultiTransformer", "PagedKV", "qkv_split_rope_fused",
           "rope_table", "FusedLinear", "FusedDropoutAdd",
           "FusedBiasDropoutResidualLayerNorm", "FusedMultiHeadAttention",
           "FusedFeedForward", "FusedTransformerEncoderLayer",
           "FusedEcMoe"]
