"""incubate.nn.functional — fused-op functional APIs.

TPU-native equivalent of the reference's fused functional surface
(reference: python/paddle/incubate/nn/functional — fused_rotary_
position_embedding, fused_layer_norm, fused_linear,
fused_multi_head_attention; plus the fork's qkv_split_rope_fused op,
ops.yaml:8-25). "Fused" here means expressed as one dispatched op so XLA
compiles a single fusion; the hand-scheduling the CUDA kernels do is
XLA's job on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.dispatch import as_tensor_args, eager_apply
from .fused_transformer import _apply_rope, qkv_split_rope_fused  # noqa: F401

__all__ = [
    "fused_rotary_position_embedding", "fused_layer_norm",
    "fused_linear", "fused_multi_head_attention",
    "fused_bias_dropout_residual_layer_norm",
    "qkv_split_rope_fused",
]


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """Rotary embedding over q/k (reference: incubate/nn/functional/
    fused_rotary_position_embedding.py; fork kernel qkv_split_rope_
    fused_op). Layout [batch, seq, heads, head_dim]; sin/cos
    [seq, head_dim/2] or [1, seq, 1, head_dim/2]; position_ids [b, s]."""
    if sin is None or cos is None:
        raise ValueError("pass precomputed sin/cos tables (rope_table)")
    if not use_neox_rotary_style:
        raise NotImplementedError("interleaved (GPT-J) style rope is not "
                                  "supported; use neox half-rotation")
    inputs = [(name, t) for name, t in (("q", q), ("k", k), ("v", v))
              if t is not None]
    ts = as_tensor_args(*[t for _, t in inputs])
    rotate = [name != "v" for name, _ in inputs]  # v passes through
    cos_a = cos._data if hasattr(cos, "_data") else jnp.asarray(cos)
    sin_a = sin._data if hasattr(sin, "_data") else jnp.asarray(sin)
    pos = None if position_ids is None else jnp.asarray(
        position_ids._data if hasattr(position_ids, "_data")
        else position_ids)

    def raw(*arrs):
        s = arrs[0].shape[1]
        c2 = cos_a.reshape(-1, cos_a.shape[-1])
        s2 = sin_a.reshape(-1, sin_a.shape[-1])
        if pos is not None:
            c = c2[pos][:, :, None, :]
            s_ = s2[pos][:, :, None, :]
        else:
            c = c2[None, :s, None, :]
            s_ = s2[None, :s, None, :]
        outs = [(_apply_rope(a, c, s_) if rot else a)
                for a, rot in zip(arrs, rotate)]
        return tuple(outs) if len(outs) > 1 else outs[0]

    out = eager_apply("fused_rotary_position_embedding", raw, ts,
                      n_outputs=len(ts))
    out = out if isinstance(out, tuple) else (out,)
    res = []
    it = iter(out)
    for t in (q, k, v):
        res.append(next(it) if t is not None else None)
    return tuple(res)


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     residual=None, bias=None):
    """LN with optional residual+bias pre-add, one fusion (reference:
    incubate fused_layer_norm / phi fused_layernorm kernels). Returns
    (out, residual_out) when residual is given, else out."""
    tensors = [x] + [t for t in (residual, bias, norm_weight, norm_bias)
                     if t is not None]
    ts = as_tensor_args(*tensors)
    has_res = residual is not None
    has_bias = bias is not None
    has_w = norm_weight is not None
    has_b = norm_bias is not None

    def raw(*arrs):
        it = iter(arrs)
        h = next(it)
        res = next(it) if has_res else None
        bs = next(it) if has_bias else None
        w = next(it) if has_w else None
        b = next(it) if has_b else None
        if bs is not None:
            h = h + bs
        if res is not None:
            h = h + res
        residual_out = h
        mu = jnp.mean(h, -1, keepdims=True)
        var = jnp.var(h, -1, keepdims=True)
        out = (h - mu) * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return (out, residual_out) if has_res else out

    return eager_apply("fused_layer_norm", raw, ts,
                       n_outputs=2 if has_res else 1)


def fused_linear(x, weight, bias=None, transpose_weight=False):
    """matmul+bias in one fusion (reference: incubate fused_linear)."""
    tensors = [x, weight] + ([bias] if bias is not None else [])
    ts = as_tensor_args(*tensors)
    has_bias = bias is not None

    def raw(a, w, *mb):
        if transpose_weight:
            w = jnp.swapaxes(w, -1, -2)
        out = a @ w
        if has_bias:
            out = out + mb[0]
        return out

    return eager_apply("fused_linear", raw, ts)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               qkv_bias=None, linear_bias=None,
                               num_heads=None, attn_mask=None,
                               dropout_rate=0.0, out_dropout_rate=0.0,
                               causal=False,
                               pre_layer_norm=False, ln_scale=None,
                               ln_bias=None, epsilon=1e-5, training=True):
    """Whole MHA block as one fusion: [pre-LN] → qkv → SDPA (flash path
    on TPU) → out-proj → residual (reference: incubate
    fused_multi_head_attention / fused_attention_op.cu)."""
    import paddle_tpu.nn.functional as F

    (xt,) = as_tensor_args(x)
    b, s, d = xt.shape
    if num_heads is None:
        raise ValueError("num_heads is required")
    h = xt
    if pre_layer_norm:
        h = fused_layer_norm(h, ln_scale, ln_bias, epsilon)
    qkv = fused_linear(h, qkv_weight, qkv_bias)
    qkv = qkv.reshape([b, s, 3, num_heads, d // num_heads])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    att = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=dropout_rate,
        is_causal=causal, training=training)
    att = att.reshape([b, s, d])
    out = fused_linear(att, linear_weight, linear_bias)
    if out_dropout_rate:
        out = F.dropout(out, p=out_dropout_rate, training=training)
    res = xt + out  # residual (reference adds the input back)
    if not pre_layer_norm and (ln_scale is not None
                               or ln_bias is not None):
        # post-LN mode: LN applies to the residual sum (reference
        # fused_attention post_layer_norm path)
        return fused_layer_norm(res, ln_scale, ln_bias, epsilon)
    return res


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5,
                                           epsilon=1e-5, training=True,
                                           mode="upscale_in_train",
                                           name=None):
    """LN(residual + dropout(x + bias)) in one fused region (reference:
    incubate/nn/functional/fused_transformer.py
    fused_bias_dropout_residual_layer_norm over the CUDA fused op)."""
    import paddle_tpu.nn.functional as F

    (xt, rt) = as_tensor_args(x, residual)
    h = xt if bias is None else xt + bias
    h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
    return fused_layer_norm(rt + h, ln_scale, ln_bias, epsilon)
