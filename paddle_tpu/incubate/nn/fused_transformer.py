"""Fused transformer decode stack — the LLM-serving compute path.

TPU-native equivalent of the reference's fused inference ops:
  - paddle/fluid/operators/fused/fused_multi_transformer_op.cu — a whole
    pre-LN transformer stack with KV cache as ONE op;
  - the fork's flagship fused ops qkv_split_rope_fused_op /
    kv_split_fused_op (reference ops.yaml:8-25) — fused QKV projection,
    head split and rotary embedding.

The TPU-first design differs deliberately from the CUDA one: instead of a
hand-scheduled megakernel, layer weights are **stacked along a leading
layer axis and the stack is a single `lax.scan`** — XLA compiles one
layer body, fuses LN + bias + residual + activation into the matmuls
(MXU), and reuses it L times; the paged-KV attention inside is the Pallas
kernel from ``nn.functional.paged_attention``. One compiled program per
(batch, phase) — no per-layer dispatch, no concat-growing cache.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.layer_base import Layer
from ...nn.functional.paged_attention import (
    paged_attention, paged_decode_attention_inplace, write_kv_pages,
    write_prefill_kv_pages)

__all__ = ["qkv_split_rope_fused", "rope_table", "FusedMultiTransformer"]


def rope_table(max_pos: int, head_dim: int, theta: float = 10000.0):
    """Precomputed rotary cos/sin, [max_pos, head_dim//2] each."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                      dtype=jnp.float32) / head_dim))
    ang = jnp.arange(max_pos, dtype=jnp.float32)[:, None] * inv[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def _apply_rope(x, cos, sin):
    """x: [..., head_dim]; cos/sin broadcastable [..., head_dim//2].
    Half-rotation (GPT-NeoX) convention."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


def _split_rope(proj, positions, num_heads, num_kv_heads, head_dim,
                cos_table, sin_table):
    """Head split + rotary embedding over a computed QKV projection."""
    lead = proj.shape[:-1]
    nq, nkv = num_heads, num_kv_heads
    q, k, v = jnp.split(
        proj.reshape(*lead, (nq + 2 * nkv), head_dim), [nq, nq + nkv],
        axis=-2)
    cos = cos_table[positions][..., None, :]   # [.., 1, hd/2]
    sin = sin_table[positions][..., None, :]
    return _apply_rope(q, cos, sin), _apply_rope(k, cos, sin), v


def qkv_split_rope_fused(x, qkv_w, qkv_b, positions, num_heads,
                         num_kv_heads, head_dim, cos_table, sin_table):
    """Fused QKV projection + head split + rotary embedding.

    Raw-array op equivalent of the fork's qkv_split_rope_fused_op
    (reference ops.yaml:8; CUDA kernel
    phi/kernels/gpu/qkv_split_rope_fused_op_kernel.cu). x may be
    [b, d_model] (decode) or [b, s, d_model] (prefill); positions
    matches x's token dims. Returns q [.., n_q, hd], k/v [.., n_kv, hd].
    """
    proj = x @ qkv_w
    if qkv_b is not None:
        proj = proj + qkv_b
    return _split_rope(proj, positions, num_heads, num_kv_heads,
                       head_dim, cos_table, sin_table)


class PagedKV(NamedTuple):
    """Layer-folded PAGE-MAJOR paged KV pool (the decode-loop carry).

    Layers are FOLDED into the page dimension — layer ``l``'s logical
    page ``p`` lives at physical page ``l * num_pages + p`` — so one
    decode step updates the pool **in place** (XLA aliases loop-carry
    buffers; the scatter writes only the new token's rows). The round-3
    layout ([L, n_kv, pages, ...] shuttled through scan xs→ys) copied
    the whole pool every token: measured 10.8ms/step of pure copy on
    the 1.3B config vs 0.7ms for this carry design (tools/decode_profile
    cache_copy vs carry_cache). Page-major ([P, n_kv, ps, d], heads
    outer within the page — r5) makes each page one contiguous block
    whose per-head slices are contiguous too: the scatter's indexed page
    dim leads and the stream decode kernel consumes whole [C, d] head
    runs with zero relayout.
    """
    k: jax.Array   # [num_layers * num_pages, n_kv, page_size, head_dim]
    v: jax.Array


class FusedMultiTransformer(Layer):
    """Pre-LN GPT-style transformer stack with paged-KV incremental decode.

    API parity target: paddle.incubate.nn.FusedMultiTransformer
    (reference python/paddle/incubate/nn/layer/fused_transformer.py,
    backed by fused_multi_transformer_op.cu). Weights are stacked
    [num_layers, ...] Parameters, executed as one lax.scan.
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, num_layers,
                 num_kv_heads=None, activation="gelu", epsilon=1e-5,
                 rope_theta=10000.0, max_position=32768, dtype=None,
                 moe_num_experts=None, moe_top_k=2):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        self.head_dim = embed_dim // num_heads
        self.dim_feedforward = dim_feedforward
        self.num_layers = num_layers
        self.activation = activation
        self.epsilon = epsilon
        self.rope_theta = rope_theta
        self.max_position = max_position
        # MoE serving stack (ISSUE 15): moe_num_experts replaces the
        # dense FFN with a per-layer expert bank routed through the
        # no-drop ragged grouped-GEMM FFN (nn/functional/grouped_gemm)
        # — and, under an ep-axis TPContext, the expert-parallel
        # all-to-all exchange with the bank sharded 1/ep per chip.
        self.moe_num_experts = moe_num_experts
        self.moe_top_k = moe_top_k

        L, d, dff = num_layers, embed_dim, dim_feedforward
        qkv_out = (self.num_heads + 2 * self.num_kv_heads) * self.head_dim
        ones = lambda *s: jnp.ones(s, jnp.float32)  # noqa: E731
        zeros = lambda *s: jnp.zeros(s, jnp.float32)  # noqa: E731

        def normal(*s):
            from ...core.generator import default_generator

            return jax.random.normal(default_generator().next_key(), s,
                                     jnp.float32) * 0.02

        self.ln1_scale = self._mk(ones(L, d))
        self.ln1_bias = self._mk(zeros(L, d))
        self.qkv_weight = self._mk(normal(L, d, qkv_out))
        self.qkv_bias = self._mk(zeros(L, qkv_out))
        self.out_weight = self._mk(
            normal(L, self.num_heads * self.head_dim, d))
        self.out_bias = self._mk(zeros(L, d))
        self.ln2_scale = self._mk(ones(L, d))
        self.ln2_bias = self._mk(zeros(L, d))
        if moe_num_experts:
            E = int(moe_num_experts)
            self.gate_weight = self._mk(normal(L, d, E))
            self.moe_w1 = self._mk(normal(L, E, d, dff))
            self.moe_b1 = self._mk(zeros(L, E, dff))
            self.moe_w2 = self._mk(normal(L, E, dff, d))
            self.moe_b2 = self._mk(zeros(L, E, d))
        else:
            self.ffn1_weight = self._mk(normal(L, d, dff))
            self.ffn1_bias = self._mk(zeros(L, dff))
            self.ffn2_weight = self._mk(normal(L, dff, d))
            self.ffn2_bias = self._mk(zeros(L, d))

    def _mk(self, arr):
        from ...core.tensor import Parameter

        return Parameter(arr)

    # ---------- functional core (raw arrays; jit-able) ----------

    def _stack(self):
        names = ["ln1_scale", "ln1_bias", "qkv_weight", "qkv_bias",
                 "out_weight", "out_bias", "ln2_scale", "ln2_bias"]
        if self.moe_num_experts:
            names += ["gate_weight", "moe_w1", "moe_b1", "moe_w2",
                      "moe_b2"]
        else:
            names += ["ffn1_weight", "ffn1_bias", "ffn2_weight",
                      "ffn2_bias"]
        out = {n: getattr(self, n)._data for n in names}
        for n in ("qkv", "out", "ffn1", "ffn2"):
            s = getattr(self, f"{n}_scale_woq", None)
            if s is not None:
                out[f"{n}_scale"] = s._data
        return out

    def quantize_weight_only_int8(self):
        """In-place weight-only int8 quantization of the four matmul
        stacks (serving counterpart of the reference's
        weight_only_linear / weight_quantize ops, ops.yaml): symmetric
        per-output-channel scales; biases/LN stay full precision. The
        decode program applies scales on matmul OUTPUTS so weight HBM
        reads halve (see ``_mm``)."""
        if self.moe_num_experts:
            raise NotImplementedError(
                "int8 weight-only quantization of the MoE expert bank "
                "is not supported yet — serve MoE stacks in bf16/f32")
        from ...core.tensor import Parameter

        for n in ("qkv", "out", "ffn1", "ffn2"):
            p = getattr(self, f"{n}_weight")
            w = p._data.astype(jnp.float32)
            scale = jnp.max(jnp.abs(w), axis=1, keepdims=True) / 127.0
            scale = jnp.maximum(scale, 1e-8)          # [L, 1, out]
            q = jnp.clip(jnp.round(w / scale), -127, 127) \
                .astype(jnp.int8)
            p._rebind(q)
            setattr(self, f"{n}_scale_woq",
                    Parameter(scale[:, 0, :]))        # [L, out]
        return self

    def _act(self, x):
        return (jax.nn.gelu(x) if self.activation == "gelu"
                else jax.nn.relu(x))

    @staticmethod
    def _ln(x, scale, bias, eps):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias

    @staticmethod
    def _mm(x, w, scale):
        """x @ w, honoring int8 weight-only quantization: with
        per-OUTPUT-channel scales, dequant commutes with the matmul —
        ``(x @ w_q) * scale`` — so the int8→bf16 convert fuses into the
        dot's weight read and HBM weight traffic halves (the decode
        path is weight-bandwidth bound; reference comparator:
        weight_only_linear, phi/kernels/fusion/gpu/)."""
        if w.dtype == jnp.int8:
            return (x @ w.astype(x.dtype)) * scale.astype(x.dtype)
        return x @ w

    @staticmethod
    def _mm_a8w8(x, w_q, scale):
        """A8W8 matmul: per-token dynamic activation quant into an
        int8 x int8 dot with int32 accumulation, dequantized once by
        ``act_scale (x) weight_scale`` (the reference's
        fused_multi_transformer_int8 quantize/GEMM/dequant round).
        Returns f32 — call sites cast back to the compute dtype."""
        from ...quantization.dynamic import (dynamic_act_quant,
                                             int8_dot_dequant)

        xq, xs = dynamic_act_quant(x)
        return int8_dot_dequant(xq, xs, w_q, scale)

    def _moe_ffn(self, w, hn, ep_axis=None, ep_size=1):
        """The MoE FFN of one layer over normalized hidden ``hn`` (any
        leading dims): flatten to tokens, route through the no-drop
        ragged grouped-GEMM FFN — or, inside an ep shard_map body, the
        expert-parallel all-to-all exchange against this shard's 1/ep
        expert slice (``nn/functional/grouped_gemm.moe_ffn_ep``)."""
        from ...core.flags import flag
        from ...nn.functional.grouped_gemm import (moe_ffn_ep,
                                                   moe_ffn_nodrop)

        lead = hn.shape[:-1]
        x2 = hn.reshape(-1, self.embed_dim)
        if ep_axis is not None:
            y = moe_ffn_ep(
                x2, w["gate_weight"], w["moe_w1"], w["moe_b1"],
                w["moe_w2"], w["moe_b2"], top_k=self.moe_top_k,
                axis=ep_axis, ep=ep_size, activation=self.activation)
        else:
            y, _probs, _idx, _cnt = moe_ffn_nodrop(
                x2, w["gate_weight"], w["moe_w1"], w["moe_b1"],
                w["moe_w2"], w["moe_b2"], top_k=self.moe_top_k,
                activation=self.activation,
                backend=flag("moe_grouped_backend"))
        return y.reshape(*lead, self.embed_dim)

    @staticmethod
    def _lora_delta_fn(adapters):
        """Per-projection LoRA delta closure over ONE layer's adapter
        view (``{proj}_a [S, K, R]`` / ``{proj}_b`` banks plus the
        chunk's shared ``order``/``inv``/``offsets`` from
        ``sort_by_adapter``). Returns f32 ``[.., N]`` or None when the
        projection has no adapter target — base-model tokens sorted
        past ``offsets[-1]`` get exact-zero rows from the work map."""
        from ...core.flags import flag
        from ...nn.functional.lora import lora_delta

        backend = flag("lora_delta_backend")

        def delta(x, kind):
            a = adapters.get(f"{kind}_a")
            if a is None:
                return None
            b = adapters[f"{kind}_b"]
            x2 = x.reshape(-1, x.shape[-1])
            xs = jnp.take(x2, adapters["order"], axis=0)
            d = lora_delta(xs, a, b, adapters["offsets"],
                           backend=backend)
            d = jnp.take(d, adapters["inv"], axis=0)
            return d.reshape(*x.shape[:-1], d.shape[-1])

        return delta

    def _layer_body(self, w, h, positions, kv_write, attend, cos_t,
                    sin_t, linear=None, a8w8=False, psum_axis=None,
                    ep_axis=None, ep_size=1, adapters=None,
                    overlap=None):
        """One pre-LN transformer layer over hidden ``h`` (any leading
        dims). Compute dtype FOLLOWS h (bf16 weights + bf16 h → pure
        bf16 MXU dots; LN statistics promote to fp32 internally and are
        cast back). ``attend`` may return (att, ck, cv) — the fused
        append+attend kernel path, where kv_write is skipped.
        ``linear(x, kind)`` computes x @ W_kind + bias (int8 scales
        applied) — the decode loop overrides it with the weight-
        streaming kernel over UNSLICED stacked weights.

        ``psum_axis``: tensor-parallel shard body (inside shard_map) —
        the row-parallel O-proj and FFN2 partial sums meet in one
        ``psum`` per projection pair BEFORE the (replicated) bias adds,
        the two per-layer allreduce points of the reference
        (fused_multi_transformer_op.cu:220,529). Per-output-channel
        int8 scales commute with the sum, so dequant stays per-shard."""
        eps = self.epsilon
        if adapters is not None and linear is not None:
            raise ValueError(
                "_layer_body: adapters compose with the default linear "
                "only (the decode loop has its own adaptered branch)")
        if linear is None:
            if a8w8:
                def raw(x, kind):
                    return self._mm_a8w8(x, w[f"{kind}_weight"],
                                         w[f"{kind}_scale"])
            else:
                def raw(x, kind):
                    return self._mm(x, w[f"{kind}_weight"],
                                    w.get(f"{kind}_scale"))

            lora = None if adapters is None \
                else self._lora_delta_fn(adapters)

            def linear(x, kind):
                y = raw(x, kind)
                if lora is not None:
                    # the delta joins the per-shard partial BEFORE the
                    # row-parallel psum (x·A = Σ_shards x_s·A_s), so TP
                    # keeps exactly its two collectives per layer
                    d = lora(x, kind)
                    if d is not None:
                        y = y + d
                if psum_axis is not None and kind in ("out", "ffn2"):
                    from ...distributed.tp import reduce_over_axis
                    y = reduce_over_axis(y, psum_axis,
                                         overlap or "psum")
                return y + w[f"{kind}_bias"]
        hn = self._ln(h, w["ln1_scale"], w["ln1_bias"], eps) \
            .astype(h.dtype)
        proj = linear(hn, "qkv")
        q, k, v = _split_rope(proj.astype(h.dtype), positions,
                              self.num_heads, self.num_kv_heads,
                              self.head_dim, cos_t, sin_t)
        if kv_write is None:
            att, ck, cv = attend(q, k, v, None, None)
        else:
            ck, cv = kv_write(k, v)
            att = attend(q, k, v, ck, cv)
        att = att.reshape(*h.shape[:-1],
                          self.num_heads * self.head_dim).astype(h.dtype)
        h = (h + linear(att, "out")).astype(h.dtype)
        hn = self._ln(h, w["ln2_scale"], w["ln2_bias"], eps) \
            .astype(h.dtype)
        if self.moe_num_experts:
            h = (h + self._moe_ffn(w, hn, ep_axis, ep_size)) \
                .astype(h.dtype)
            return h, ck, cv
        ff = self._act(linear(hn, "ffn1").astype(h.dtype))
        h = (h + linear(ff, "ffn2")).astype(h.dtype)
        return h, ck, cv

    @staticmethod
    def _weights_dtype(weights):
        """Matmul-stack dtype for either weight form (stacked dict or
        list of per-layer dicts)."""
        w = weights[0] if isinstance(weights, (list, tuple)) else weights
        return w["qkv_weight"].dtype

    @staticmethod
    def _pool_data(side):
        """Raw page array of a cache side (quantized sides are
        (int8_rows, f32_scale_plane) tuples)."""
        return side[0] if isinstance(side, tuple) else side

    def _pages_per_layer(self, cache: PagedKV) -> int:
        return self._pool_data(cache.k).shape[0] // self.num_layers

    def _pool_page_size(self, cache: PagedKV) -> int:
        return self._pool_data(cache.k).shape[2]

    # ---------- tensor parallelism (mp mesh axis) ----------

    def _tp_view(self, tp) -> "FusedMultiTransformer":
        """Per-shard view for the shard_map body: the same stack config
        with PER-SHARD head counts (query heads partition with the QKV
        columns; kv heads shard — or replicate one head per shard in
        the GQA fallback). No parameters are attached: the raw methods
        only read config attrs and the weights they are handed."""
        v = object.__new__(FusedMultiTransformer)
        for n in ("embed_dim", "head_dim", "dim_feedforward",
                  "num_layers", "activation", "epsilon", "rope_theta",
                  "max_position", "moe_num_experts", "moe_top_k"):
            object.__setattr__(v, n, getattr(self, n))
        object.__setattr__(v, "num_heads", tp.heads_per_shard)
        object.__setattr__(v, "num_kv_heads", tp.kv_heads_per_shard)
        return v

    def _tp_wrap(self, tp, method: str, weights, x, cache, tables,
                 rep_args, cos_t, sin_t, a8w8, adapters=None,
                 overlap=None):
        """shard_map a raw phase over the ``mp`` and/or ``ep`` mesh
        axes: weights enter pre-sharded (TPContext.shard_stack specs —
        column/row slices over ``mp``, the MoE expert bank 1/ep over
        ``ep``), the KV pool sharded by kv-head (``mp``) or replicated
        (ep-only), everything else — hidden state, block tables,
        seq_lens/positions, rope tables — replicated. The body is the
        SAME raw method on the per-shard view with ``psum_axis`` set
        when mp > 1 (each column→row projection pair contributes
        exactly one psum) and ``ep_axis`` set when ep > 1 (each MoE
        layer contributes exactly the all_to_all dispatch/combine pair
        plus the replicated-hidden all_gather)."""
        from ...distributed.tp import resolve_overlap, shard_map_fn

        overlap = resolve_overlap(overlap)
        if cache is None:
            raise ValueError(
                "tensor-parallel prefill needs a paged cache (the "
                "dense training/eval path is single-chip)")
        if isinstance(weights, (list, tuple)):
            raise ValueError(
                "tensor-parallel decode takes the stacked weight dict "
                "(per-layer lists do not carry shard specs)")
        if isinstance(cache.k, tuple):
            raise NotImplementedError(
                "int8 cache-KV is not supported under tensor "
                "parallelism yet — serve TP with a bf16/f32 pool")
        if self.moe_num_experts and tp.mp > 1:
            raise NotImplementedError(
                "MoE serving composes with expert parallelism "
                "(ep_degree) — tensor-parallel (mp) sharding of the "
                "attention stack around an MoE FFN is not wired yet")
        view = self._tp_view(tp)
        rep = tp.pspec()
        wspecs = {n: tp.stack_spec(n) for n in weights}
        kv = tp.kv_spec()
        psum_axis = tp.axis if tp.mp > 1 else None
        ep_axis = tp.ep_axis if tp.ep > 1 else None
        adaptered = adapters is not None
        aspecs = None
        if adaptered:
            # adapter banks shard alongside the base stacks
            # (_ADAPTER_LAYOUT): B column-split for col-parallel
            # projections, A row-split for row-parallel ones, the
            # per-token slot ids replicated
            aspecs = {n: (rep if n == "slots" else tp.adapter_spec(n))
                      for n in adapters}

        def body(w, xb, ck, cv, tbl, cos, sin, *extras):
            kw = dict(a8w8=a8w8, psum_axis=psum_axis, ep_axis=ep_axis,
                      ep_size=tp.ep, overlap=overlap)
            if adaptered:
                kw["adapters"] = extras[-1]
                extras = extras[:-1]
            h, cache2 = getattr(view, method)(
                w, xb, PagedKV(ck, cv), tbl, *extras, cos, sin, **kw)
            return h, cache2.k, cache2.v

        fn = shard_map_fn()(
            body, mesh=tp.mesh,
            in_specs=(wspecs, rep, kv, kv, rep, rep, rep)
            + (rep,) * len(rep_args)
            + ((aspecs,) if adaptered else ()),
            out_specs=(rep, kv, kv), check_rep=False)
        h, nk, nv = fn(weights, x, cache.k, cache.v, tables,
                       cos_t, sin_t, *rep_args,
                       *((adapters,) if adaptered else ()))
        return h, PagedKV(nk, nv)

    def prefill_raw(self, weights, x, cache, block_tables, cos_t, sin_t,
                    a8w8=False, tp=None, psum_axis=None,
                    ep_axis=None, ep_size=1, overlap=None):
        """Prompt pass: x [b, s, d] → (hidden [b, s, d], filled cache).

        Causal dense attention (flash-fusable by XLA/Pallas); each
        layer's K/V written into its layer-offset pages of the folded
        pool. ``cache=None`` runs the pure dense forward (training/eval
        parity path) with no KV writes. Ragged batches are NOT masked
        here — pad prompts to a common length (dense attention over
        padding is causal-safe for the suffix tokens actually decoded).
        ``a8w8``: run the four matmuls with per-token dynamic int8
        activations against the int8 weight stack (``_mm_a8w8``).

        ``tp``: a distributed.tp.TPContext — shard the whole pass over
        the ``mp`` mesh axis (weights from TPContext.shard_stack, pool
        kv-head-sharded). ``psum_axis`` is the internal per-shard form
        (set by the shard_map wrapper, not callers).
        """
        if a8w8 and self._weights_dtype(weights) != jnp.int8:
            raise ValueError("a8w8 prefill needs an int8 weight stack "
                             "(quantize_weight_only_int8 first)")
        if tp is not None:
            return self._tp_wrap(tp, "prefill_raw", weights, x, cache,
                                 block_tables, (), cos_t, sin_t, a8w8,
                                 overlap=overlap)
        b, s, d = x.shape
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        group = self.num_heads // self.num_kv_heads

        def attend(q, k, v, ck, cv):
            kq = jnp.repeat(k, group, axis=-2)
            vq = jnp.repeat(v, group, axis=-2)
            return jax.nn.dot_product_attention(
                q, kq, vq, is_causal=True, scale=self.head_dim ** -0.5)

        if cache is None:
            def body(h, w):
                h, _, _ = self._layer_body(
                    w, h, positions, lambda k, v: (None, None), attend,
                    cos_t, sin_t, a8w8=a8w8, psum_axis=psum_axis,
                    ep_axis=ep_axis, ep_size=ep_size, overlap=overlap)
                return h, None

            h, _ = jax.lax.scan(body, x, weights)
            return h, None

        npages = self._pages_per_layer(cache)

        def body(l, carry):
            h, ck, cv = carry
            w = {n: jax.lax.dynamic_index_in_dim(a, l, 0, False)
                 for n, a in weights.items()}
            tbl = block_tables + l * npages
            h, ck, cv = self._layer_body(
                w, h, positions,
                lambda k, v: write_prefill_kv_pages(ck, cv, k, v, tbl),
                attend, cos_t, sin_t, a8w8=a8w8, psum_axis=psum_axis,
                ep_axis=ep_axis, ep_size=ep_size, overlap=overlap)
            return h, ck, cv

        h, nk, nv = jax.lax.fori_loop(
            0, self.num_layers, body, (x, cache.k, cache.v))
        return h, PagedKV(nk, nv)

    def prefill_chunk_raw(self, weights, x, cache, block_tables, start,
                          chunk_lens, cos_t, sin_t, a8w8=False,
                          tp=None, psum_axis=None, ep_axis=None,
                          ep_size=1, adapters=None, overlap=None):
        """CHUNKED prompt pass: x [b, c, d] embeds tokens at positions
        ``start[b] .. start[b]+c-1`` of sequences whose earlier tokens
        (previous chunks, or a shared prefix mapped by the prefix
        cache) are ALREADY in the paged pool. Queries attend to the
        cached pages plus causally within the chunk, so a long prompt
        prefills in fixed-size chunks interleaved with decode steps
        (the serving scheduler's stall bound) instead of one monolithic
        program that blocks the decode batch.

        ``start``/``chunk_lens``: [b] int32 traced arrays — position
        offset and VALID row count (rows ``>= chunk_lens[b]`` are
        right-padding; their KV writes go to the scratch page and their
        hidden rows are garbage the caller discards). Returns
        (hidden [b, c, d], cache').
        """
        if a8w8 and self._weights_dtype(weights) != jnp.int8:
            raise ValueError("a8w8 prefill needs an int8 weight stack "
                             "(quantize_weight_only_int8 first)")
        if tp is not None:
            return self._tp_wrap(tp, "prefill_chunk_raw", weights, x,
                                 cache, block_tables,
                                 (start, chunk_lens), cos_t, sin_t,
                                 a8w8, adapters=adapters,
                                 overlap=overlap)
        from ...core.flags import flag
        from ...nn.functional.flash_varlen import paged_prefill_attention
        from ...nn.functional.paged_attention import (
            gather_kv_pages, write_prefill_kv_pages)

        b, c, _d = x.shape
        start = start.astype(jnp.int32)
        chunk_lens = chunk_lens.astype(jnp.int32)
        positions = start[:, None] \
            + jnp.arange(c, dtype=jnp.int32)[None, :]      # [b, c]
        n_kv = self.num_kv_heads
        group = self.num_heads // n_kv
        hd = self.head_dim
        npages = self._pages_per_layer(cache)
        scale = hd ** -0.5
        # int8-quantized pools keep the dequantizing gather path; bf16/
        # f32 pools route through the varlen kernel, which reads the
        # pages IN PLACE (no per-chunk dense gather copy)
        use_varlen = (flag("prefill_attention_backend") != "gather"
                      and not isinstance(cache.k, tuple))

        ad_base = None
        if adapters is not None:
            from ...nn.functional.lora import (
                inverse_order, sort_by_adapter)
            # per-row slots broadcast to per-token and sorted ONCE for
            # the whole chunk; the layer loop slices the banks at l.
            # Padding rows inherit their row's slot — their deltas are
            # garbage the caller already discards.
            S_ad = adapters["qkv_a"].shape[1]
            slots_tok = jnp.repeat(
                adapters["slots"].astype(jnp.int32), c)
            order, offsets, _ = sort_by_adapter(slots_tok, S_ad)
            ad_base = {"order": order, "inv": inverse_order(order),
                       "offsets": offsets}

        def body(l, carry):
            h, ck, cv = carry
            w = {n: jax.lax.dynamic_index_in_dim(a, l, 0, False)
                 for n, a in weights.items()}
            tbl = block_tables + l * npages

            def kv_write(k, v):
                return write_prefill_kv_pages(
                    ck, cv, k, v, tbl, start=start,
                    valid_lens=chunk_lens)

            def attend(q, k, v, nck, ncv):
                # the sequence's whole cached span (the chunk's own KV
                # was just written): key position <= query position
                # covers both the prefix pages and the in-chunk
                # triangle
                if use_varlen:
                    fb = flag("prefill_attention_backend")
                    return paged_prefill_attention(
                        q, nck, ncv, tbl, start, n_kv=n_kv,
                        scale=scale,
                        backend="auto" if fb in ("auto", "varlen")
                        else fb)
                kg = gather_kv_pages(nck, tbl)
                vg = gather_kv_pages(ncv, tbl)
                S = kg.shape[1]
                qh = q.reshape(b, c, n_kv, group, hd)
                # fp32 scores by design (softmax stability; KV-bound)
                # tpu-lint: ok(X-PROMOTE) -- attention scores fp32 by design
                logits = jnp.einsum(
                    "btngd,bsnd->bngts",
                    qh.astype(jnp.float32) * scale,
                    kg.astype(jnp.float32))
                mask = jnp.arange(S, dtype=jnp.int32)[None, None, :] \
                    <= positions[:, :, None]               # [b, t, s]
                logits = jnp.where(mask[:, None, None], logits,
                                   jnp.finfo(jnp.float32).min)
                wts = jax.nn.softmax(logits, axis=-1)
                # tpu-lint: ok(X-PROMOTE) -- fp32 PV accumulation pairs with scores
                out = jnp.einsum("bngts,bsnd->btngd", wts,
                                 vg.astype(jnp.float32))
                return out.reshape(b, c, n_kv * group, hd) \
                    .astype(q.dtype)

            ad = None
            if ad_base is not None:
                ad = dict(ad_base)
                for n, a in adapters.items():
                    if n.endswith("_a") or n.endswith("_b"):
                        ad[n] = jax.lax.dynamic_index_in_dim(
                            a, l, 0, False)
            h, ck, cv = self._layer_body(
                w, h, positions, kv_write, attend, cos_t, sin_t,
                a8w8=a8w8, psum_axis=psum_axis, ep_axis=ep_axis,
                ep_size=ep_size, adapters=ad, overlap=overlap)
            return h, ck, cv

        h, nk, nv = jax.lax.fori_loop(
            0, self.num_layers, body, (x, cache.k, cache.v))
        return h, PagedKV(nk, nv)

    def unstack_weights(self, weights=None):
        """Per-layer weight dicts for the UNROLLED decode path
        (experimental). Measured on the 1.3B b32 decode (r4): the
        unrolled program was SLOWER end-to-end than the stacked
        fori_loop (1859 vs 2583 tok/s) — XLA already schedules the
        loop-indexed weight slices efficiently, and the 24-layer
        unrolled body lost the while-loop's buffer reuse. Kept for
        per-config experimentation via decode_raw's list form."""
        weights = weights or self._stack()
        return [{n: a[l] for n, a in weights.items()}
                for l in range(self.num_layers)]

    def decode_raw(self, weights, x, cache: PagedKV, block_tables,
                   seq_lens, cos_t, sin_t, a8w8=False, tp=None,
                   psum_axis=None, ep_axis=None, ep_size=1,
                   adapters=None, overlap=None):
        """One decode step: x [b, d] token embeddings, seq_lens [b] =
        tokens already cached (the new token's position). Returns
        (hidden [b, d], cache').

        ``weights`` may be the stacked dict (fori_loop layer loop —
        the DEFAULT and measured-fastest serving path) or a LIST of
        per-layer dicts from ``unstack_weights`` (Python-unrolled —
        experimental, measured slower end-to-end; see that method's
        docstring). Either way the pool is carried through the loop and
        only scatter-written/gather-read — never copied.

        GROUPED streaming (``FLAGS_decode_grouped``, default auto):
        the four per-layer matmuls issue as at most TWO streamed calls
        — one QKV stream and one fused O+LN2+FFN tail
        (``stream_layer_tail``) — and with ``FLAGS_decode_prefetch``
        the tail's last grid phase computes layer l+1's LN1+QKV so its
        weight DMA overlaps layer l's FFN compute: ONE fused streamed
        call per layer in steady state. ``auto`` groups bf16/f32/
        weight-only-int8 stacks; A8W8 keeps the ungrouped int8 x int8
        act-quant kernel (grouped would forgo its int8 MXU math).

        ``a8w8``: activations dynamically quantized per token into the
        int8 x int8 streamed matmuls (stream_linear act_quant path) —
        requires the int8 weight stack.

        TENSOR PARALLELISM (``tp``, a distributed.tp.TPContext): the
        whole step runs under shard_map over the ``mp`` mesh axis —
        per-shard query/kv heads, a kv-head-sharded pool, and each
        column→row projection pair meeting in exactly one ``psum``
        (two per layer: after the row-parallel O-proj and FFN2, the
        reference's fused_multi_transformer_op.cu:220,529 ring_id
        allreduce points). The per-shard matmuls go through
        ``stream_linear`` so every chip streams only its [K, N/mp] /
        [K/mp, N] weight slice — TP decode keeps the per-chip
        weight-bandwidth roofline; the fused grouped tail is split at
        the psum boundaries (a collective cannot live inside one
        Pallas grid). ``psum_axis`` is the internal per-shard form.
        """
        if a8w8 and self._weights_dtype(weights) != jnp.int8:
            raise ValueError("a8w8 decode needs an int8 weight stack "
                             "(quantize_weight_only_int8 first)")
        if tp is not None:
            return self._tp_wrap(tp, "decode_raw", weights, x, cache,
                                 block_tables, (seq_lens,), cos_t,
                                 sin_t, a8w8, adapters=adapters,
                                 overlap=overlap)
        npages = self._pages_per_layer(cache)
        lens1 = (seq_lens + 1).astype(jnp.int32)
        # token-level pool ownership (the stream kernels' mask) is
        # layer-independent: compute ONCE per decode step, share across
        # the 24-layer loop
        from ...core.flags import flag
        from ...nn.functional.paged_attention import (
            _on_tpu, build_pool_ownership,
            paged_decode_attention_inplace_q)

        quantized_kv = isinstance(cache.k, tuple)
        fused_stream = False
        if quantized_kv:
            # int8 cache-KV mode: always the fused quantized kernel
            # (interpret off-TPU); the pools never touch a non-Pallas op
            ownership = build_pool_ownership(
                block_tables, seq_lens.astype(jnp.int32), npages,
                self._pool_page_size(cache))
        else:
            backend = flag("paged_attention_backend")
            fused_stream = (backend in ("auto", "stream") and _on_tpu()
                            and self.head_dim % 128 == 0)
            if fused_stream:
                # fused append+attend kernel masks with seq_lens
                # (current token joins from the operands)
                ownership = build_pool_ownership(
                    block_tables, seq_lens.astype(jnp.int32), npages,
                    cache.k.shape[2])
            else:
                ownership = build_pool_ownership(
                    block_tables, lens1, npages, cache.k.shape[2])

        def attend_fn(q, k, v, ck, cv, tbl, base):
            """One decode-attention step for the active backend:
            returns (att, ck', cv') with the new token's K/V in the
            pool — the shared core of the ungrouped _layer_body path
            and the grouped carried-QKV loop."""
            if quantized_kv:
                att, kq2, ks2, vq2, vs2 = \
                    paged_decode_attention_inplace_q(
                        q, k, v, ck[0], ck[1], cv[0], cv[1],
                        seq_lens, tbl, pool_base=base,
                        pool_pages=npages, ownership=ownership)
                return att, (kq2, ks2), (vq2, vs2)
            if fused_stream:
                return paged_decode_attention_inplace(
                    q, k, v, ck, cv, seq_lens, tbl,
                    pool_base=base, pool_pages=npages,
                    ownership=ownership)
            ck, cv = write_kv_pages(ck, cv, k, v, seq_lens, tbl + base)
            att = paged_attention(q, ck, cv, lens1, tbl,
                                  pool_base=base, pool_pages=npages,
                                  ownership=ownership)
            return att, ck, cv

        def run_layer(w, h, ck, cv, tbl, base, linear=None):
            def attend(q, k, v, _ck, _cv):
                return attend_fn(q, k, v, ck, cv, tbl, base)
            return self._layer_body(w, h, seq_lens, None, attend,
                                    cos_t, sin_t, linear=linear,
                                    ep_axis=ep_axis, ep_size=ep_size)

        from ...nn.functional.stream_linear import (stream_layer_tail,
                                                    stream_linear)

        is_moe = bool(self.moe_num_experts)
        if is_moe and isinstance(weights, (list, tuple)):
            raise NotImplementedError(
                "MoE decode takes the stacked weight dict (the "
                "unstacked experimental path has no expert bank form)")
        g_flag = flag("decode_grouped")
        use_grouped = (not is_moe) and (
            g_flag == "on" or (g_flag == "auto" and not a8w8))
        prefetch = bool(flag("decode_prefetch"))
        d_att = self.num_heads * self.head_dim

        def split_rope(qkv, h):
            return _split_rope(qkv.astype(h.dtype), seq_lens,
                               self.num_heads, self.num_kv_heads,
                               self.head_dim, cos_t, sin_t)

        if adapters is not None:
            # ADAPTERED decode: per-projection streamed base matmul
            # plus ONE ragged grouped delta launch per target
            # projection — tokens sorted by adapter slot once per step,
            # membership riding the traced work map so the compiled
            # program is independent of which adapters are loaded. The
            # fused grouped tail is base-only (a delta join point
            # cannot live inside its Pallas grid), so this branch runs
            # the four-call per-layer form. Under TP the delta partial
            # joins the base partial BEFORE the row-parallel psum
            # (x·A = Σ_shards x_s·A_s with B replicated), keeping
            # exactly two collectives per layer.
            if is_moe:
                raise NotImplementedError(
                    "adaptered decode composes with the dense stack "
                    "only (no MoE expert-bank form yet)")
            if isinstance(weights, (list, tuple)):
                raise ValueError(
                    "adaptered decode takes the STACKED weight dict "
                    "(banks are layer-stacked [L, S, ...] arrays)")
            from ...nn.functional.lora import (
                inverse_order, lora_delta, sort_by_adapter)
            from ...nn.functional.stream_linear import _apply_activation

            lora_backend = flag("lora_delta_backend")
            S_ad = adapters["qkv_a"].shape[1]
            order, offsets, _ = sort_by_adapter(
                adapters["slots"].astype(jnp.int32), S_ad)
            inv = inverse_order(order)
            L = self.num_layers

            def small(name, l):
                return jax.lax.dynamic_index_in_dim(
                    weights[name], l, 0, False)

            def delta(xx, kind, l):
                a4 = adapters.get(f"{kind}_a")
                if a4 is None:
                    return None
                a3 = jax.lax.dynamic_index_in_dim(a4, l, 0, False)
                b3 = jax.lax.dynamic_index_in_dim(
                    adapters[f"{kind}_b"], l, 0, False)
                xs = jnp.take(xx, order, axis=0)
                d = lora_delta(xs, a3, b3, offsets,
                               backend=lora_backend)
                return jnp.take(d, inv, axis=0)

            def proj(xx, kind, l, reduce=False, activation=None):
                # f32 partial with bias/activation deferred past the
                # delta join (and past the psum for row-parallel kinds)
                y = stream_linear(
                    xx, weights[f"{kind}_weight"], layer=l,
                    scale=weights.get(f"{kind}_scale"),
                    act_quant=a8w8, out_dtype=jnp.float32)
                d = delta(xx, kind, l)
                if d is not None:
                    y = y + d
                if reduce and psum_axis is not None:
                    from ...distributed.tp import reduce_over_axis
                    y = reduce_over_axis(y, psum_axis,
                                         overlap or "psum")
                y = y + small(f"{kind}_bias", l).astype(jnp.float32)
                if activation is not None:
                    y = _apply_activation(y, activation)
                return y

            def body(l, carry):
                h, ck, cv = carry
                hn = self._ln(h, small("ln1_scale", l),
                              small("ln1_bias", l),
                              self.epsilon).astype(h.dtype)
                qkv = proj(hn, "qkv", l)
                q, k, v = split_rope(qkv, h)
                att, ck, cv = attend_fn(q, k, v, ck, cv, block_tables,
                                        l * npages)
                att = att.reshape(*h.shape[:-1], d_att).astype(h.dtype)
                h = (h + proj(att, "out", l, reduce=True)) \
                    .astype(h.dtype)
                hn = self._ln(h, small("ln2_scale", l),
                              small("ln2_bias", l),
                              self.epsilon).astype(h.dtype)
                ff = proj(hn, "ffn1", l,
                          activation=self.activation).astype(h.dtype)
                h = (h + proj(ff, "ffn2", l, reduce=True)) \
                    .astype(h.dtype)
                return h, ck, cv

            h, nk, nv = jax.lax.fori_loop(
                0, L, body, (x, cache.k, cache.v))
            return h, PagedKV(nk, nv)

        if psum_axis is not None:
            # tensor-parallel shard body: streamed per-shard matmuls
            # (QKV / O / FFN1 / FFN2 slices), the two row-parallel ones
            # reduced over mp INSIDE stream_linear (reduce_axis reduces
            # the f32 partial before the replicated bias + activation —
            # the collective stays fused with the projection instead of
            # breaking the decode stream; ``overlap="ring"`` pipelines
            # the reduce as chunked ppermute phases under the next
            # chunk's GEMM). The fused grouped tail cannot span a
            # collective, so grouped TP runs stream_layer_tail's split
            # form (reduce_axis=) which breaks at the two reduce seams
            # while keeping the carried-QKV prefetch structure.
            L = self.num_layers

            def small(name, l):
                return jax.lax.dynamic_index_in_dim(
                    weights[name], l, 0, False)

            def lin(xx, kind, l, **kw):
                return stream_linear(
                    xx, weights[f"{kind}_weight"], layer=l,
                    scale=weights.get(f"{kind}_scale"),
                    act_quant=a8w8, out_dtype=xx.dtype, **kw)

            def qkv_at(l, hh):
                hn = self._ln(hh, small("ln1_scale", l),
                              small("ln1_bias", l),
                              self.epsilon).astype(hh.dtype)
                return lin(hn, "qkv", l, bias=weights["qkv_bias"])

            if use_grouped:
                def tail(att, h, l):
                    nq = None
                    if prefetch:
                        nq = dict(w=weights["qkv_weight"],
                                  b=weights["qkv_bias"],
                                  s=weights.get("qkv_scale"),
                                  ln_s=weights["ln1_scale"],
                                  ln_b=weights["ln1_bias"],
                                  layer=jnp.minimum(l + 1, L - 1))
                    return stream_layer_tail(
                        att, h, weights["out_weight"],
                        weights["ffn1_weight"], weights["ffn2_weight"],
                        layer=l, bo=weights["out_bias"],
                        b1=weights["ffn1_bias"],
                        b2=weights["ffn2_bias"],
                        ln2_scale=weights["ln2_scale"],
                        ln2_bias=weights["ln2_bias"],
                        epsilon=self.epsilon,
                        activation=self.activation,
                        so=weights.get("out_scale"),
                        s1=weights.get("ffn1_scale"),
                        s2=weights.get("ffn2_scale"),
                        next_qkv=nq, out_dtype=h.dtype,
                        reduce_axis=psum_axis, overlap=overlap)

                def gbody(l, carry):
                    h, qkv, ck, cv = carry
                    q, k, v = split_rope(qkv, h)
                    att, ck, cv = attend_fn(q, k, v, ck, cv,
                                            block_tables, l * npages)
                    att = att.reshape(*h.shape[:-1], d_att) \
                        .astype(h.dtype)
                    if prefetch:
                        h, qkv = tail(att, h, l)
                    else:
                        h = tail(att, h, l)
                        qkv = qkv_at(jnp.minimum(l + 1, L - 1), h)
                    return h, qkv, ck, cv

                qkv0 = qkv_at(0, x)
                h, _q, nk, nv = jax.lax.fori_loop(
                    0, L, gbody, (x, qkv0, cache.k, cache.v))
                return h, PagedKV(nk, nv)

            def body(l, carry):
                h, ck, cv = carry
                qkv = qkv_at(l, h)
                q, k, v = split_rope(qkv, h)
                att, ck, cv = attend_fn(q, k, v, ck, cv, block_tables,
                                        l * npages)
                att = att.reshape(*h.shape[:-1], d_att).astype(h.dtype)
                h = (h + lin(att, "out", l, bias=weights["out_bias"],
                             reduce_axis=psum_axis, overlap=overlap)) \
                    .astype(h.dtype)
                hn = self._ln(h, small("ln2_scale", l),
                              small("ln2_bias", l),
                              self.epsilon).astype(h.dtype)
                ff = lin(hn, "ffn1", l, bias=weights["ffn1_bias"],
                         activation=self.activation)
                h = (h + lin(ff, "ffn2", l, bias=weights["ffn2_bias"],
                             reduce_axis=psum_axis, overlap=overlap)) \
                    .astype(h.dtype)
                return h, ck, cv

            h, nk, nv = jax.lax.fori_loop(
                0, L, body, (x, cache.k, cache.v))
            return h, PagedKV(nk, nv)

        if use_grouped and isinstance(weights, (list, tuple)):
            # unstacked grouped loop: per-layer dicts, python-unrolled
            def qkv_call(wl, hh):
                hn = self._ln(hh, wl["ln1_scale"], wl["ln1_bias"],
                              self.epsilon).astype(hh.dtype)
                return stream_linear(hn, wl["qkv_weight"],
                                     bias=wl["qkv_bias"],
                                     scale=wl.get("qkv_scale"),
                                     out_dtype=hh.dtype)

            h, ck, cv = x, cache.k, cache.v
            qkv = qkv_call(weights[0], h)
            for l, w in enumerate(weights):
                q, k, v = split_rope(qkv, h)
                att, ck, cv = attend_fn(q, k, v, ck, cv, block_tables,
                                        l * npages)
                att = att.reshape(*h.shape[:-1], d_att).astype(h.dtype)
                nxt = weights[l + 1] \
                    if (prefetch and l + 1 < len(weights)) else None
                res = stream_layer_tail(
                    att, h, w["out_weight"], w["ffn1_weight"],
                    w["ffn2_weight"], bo=w["out_bias"],
                    b1=w["ffn1_bias"], b2=w["ffn2_bias"],
                    ln2_scale=w["ln2_scale"], ln2_bias=w["ln2_bias"],
                    epsilon=self.epsilon, activation=self.activation,
                    so=w.get("out_scale"), s1=w.get("ffn1_scale"),
                    s2=w.get("ffn2_scale"),
                    next_qkv=None if nxt is None else dict(
                        w=nxt["qkv_weight"], b=nxt["qkv_bias"],
                        s=nxt.get("qkv_scale"),
                        ln_s=nxt["ln1_scale"], ln_b=nxt["ln1_bias"]),
                    out_dtype=h.dtype)
                if nxt is None:
                    h = res
                    if l + 1 < len(weights):
                        qkv = qkv_call(weights[l + 1], h)
                else:
                    h, qkv = res
            return h, PagedKV(ck, cv)

        if use_grouped:
            # stacked grouped loop: QKV carried through the fori_loop,
            # layer l+1's projection computed by layer l's tail kernel
            L = self.num_layers

            def qkv_at(l, hh):
                ln_s = jax.lax.dynamic_index_in_dim(
                    weights["ln1_scale"], l, 0, False)
                ln_b = jax.lax.dynamic_index_in_dim(
                    weights["ln1_bias"], l, 0, False)
                hn = self._ln(hh, ln_s, ln_b, self.epsilon) \
                    .astype(hh.dtype)
                return stream_linear(hn, weights["qkv_weight"],
                                     layer=l, bias=weights["qkv_bias"],
                                     scale=weights.get("qkv_scale"),
                                     out_dtype=hh.dtype)

            def tail(att, h, l):
                nq = None
                if prefetch:
                    nq = dict(w=weights["qkv_weight"],
                              b=weights["qkv_bias"],
                              s=weights.get("qkv_scale"),
                              ln_s=weights["ln1_scale"],
                              ln_b=weights["ln1_bias"],
                              layer=jnp.minimum(l + 1, L - 1))
                return stream_layer_tail(
                    att, h, weights["out_weight"],
                    weights["ffn1_weight"], weights["ffn2_weight"],
                    layer=l, bo=weights["out_bias"],
                    b1=weights["ffn1_bias"], b2=weights["ffn2_bias"],
                    ln2_scale=weights["ln2_scale"],
                    ln2_bias=weights["ln2_bias"],
                    epsilon=self.epsilon, activation=self.activation,
                    so=weights.get("out_scale"),
                    s1=weights.get("ffn1_scale"),
                    s2=weights.get("ffn2_scale"),
                    next_qkv=nq, out_dtype=h.dtype)

            def body(l, carry):
                h, qkv, ck, cv = carry
                q, k, v = split_rope(qkv, h)
                att, ck, cv = attend_fn(q, k, v, ck, cv, block_tables,
                                        l * npages)
                att = att.reshape(*h.shape[:-1], d_att).astype(h.dtype)
                if prefetch:
                    # steady state: ONE fused streamed call per layer
                    # (the last layer's prefetched QKV is discarded)
                    h, qkv = tail(att, h, l)
                else:
                    h = tail(att, h, l)
                    qkv = qkv_at(jnp.minimum(l + 1, L - 1), h)
                return h, qkv, ck, cv

            qkv0 = qkv_at(0, x)
            h, _q, nk, nv = jax.lax.fori_loop(
                0, L, body, (x, qkv0, cache.k, cache.v))
            return h, PagedKV(nk, nv)

        if isinstance(weights, (list, tuple)):
            h, ck, cv = x, cache.k, cache.v
            for l, w in enumerate(weights):
                linear = None
                if a8w8:
                    def linear(xx, kind, _w=w):
                        return stream_linear(
                            xx, _w[f"{kind}_weight"],
                            bias=_w[f"{kind}_bias"],
                            scale=_w[f"{kind}_scale"],
                            act_quant=True, out_dtype=xx.dtype)
                h, ck, cv = run_layer(w, h, ck, cv, block_tables,
                                      l * npages, linear)
            return h, PagedKV(ck, cv)

        # matmul weights stay STACKED: the weight-streaming kernel reads
        # layer l's block directly via a prefetched index, so the loop
        # never materializes a per-layer [K, N] slice (a dynamic-slice
        # operand to the kernel's custom call would copy ~100MB/layer)

        # dtype-aware auto (r5 1.3B b32 end-to-end): bf16 weights run
        # FASTER through XLA's sliced dots (2916 vs 2749 tok/s — the
        # ~96 kernel dispatches/step eat the DMA gains), int8 weights
        # run faster through the streaming kernel whose dequant fuses
        # into the block DMA (3398 vs 3231). A8W8 always streams: the
        # act-quant path's int8 x int8 dot lives in the same kernel
        # (off-TPU it degrades to the identical-math XLA int32 dot).
        lin_flag = flag("decode_linear")
        is_int8 = weights["qkv_weight"].dtype == jnp.int8
        use_stream_lin = (not is_moe) and (
            a8w8 or (x.shape[0] % 8 == 0 and (
                lin_flag == "stream"
                or (lin_flag == "auto" and is_int8))))
        small = {n: a for n, a in weights.items()
                 if not n.startswith(("qkv_", "out_", "ffn1_", "ffn2_"))}

        def body(l, carry):
            h, ck, cv = carry
            w = {n: jax.lax.dynamic_index_in_dim(a, l, 0, False)
                 for n, a in (small if use_stream_lin else weights)
                 .items()}
            linear = None
            if use_stream_lin:
                def linear(xx, kind):
                    return stream_linear(
                        xx, weights[f"{kind}_weight"], layer=l,
                        bias=weights[f"{kind}_bias"],
                        scale=weights.get(f"{kind}_scale"),
                        act_quant=a8w8, out_dtype=xx.dtype)
            h, ck, cv = run_layer(w, h, ck, cv, block_tables,
                                  l * npages, linear)
            return h, ck, cv

        h, nk, nv = jax.lax.fori_loop(
            0, self.num_layers, body, (x, cache.k, cache.v))
        return h, PagedKV(nk, nv)

    # ---------- eager Layer API ----------

    def forward(self, x, cache=None, block_tables=None, seq_lens=None):
        """Eager wrapper: prefill when x is [b, s, d] (cache=None → pure
        dense forward, no KV writes), decode step when x is [b, d]."""
        cos_t, sin_t = rope_table(self.max_position, self.head_dim,
                                  self.rope_theta)
        w = self._stack()
        xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        if xd.ndim == 3:
            h, cache = self.prefill_raw(
                w, xd, cache,
                None if block_tables is None else jnp.asarray(block_tables),
                cos_t, sin_t)
        else:
            h, cache = self.decode_raw(
                w, xd, cache, jnp.asarray(block_tables),
                jnp.asarray(seq_lens), cos_t, sin_t)
        return Tensor(h), cache
