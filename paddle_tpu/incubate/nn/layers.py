"""incubate.nn layer classes over the fused functionals.

TPU-native equivalents of the reference's incubate fused layers
(reference: python/paddle/incubate/nn/layer/fused_transformer.py —
FusedMultiHeadAttention:196, FusedFeedForward:502,
FusedTransformerEncoderLayer:728, FusedBiasDropoutResidualLayerNorm:83;
fused_linear.py:19 FusedLinear; fused_dropout_add.py:19 FusedDropoutAdd;
fused_ec_moe.py:19 FusedEcMoe). The "fusion" on TPU is XLA's: each
forward traces to one fused region. Parameter layouts are this
framework's 2-D matmul forms (e.g. qkv_weight [d, 3d]) — NOTE the
reference's FusedMultiHeadAttention stores qkv as 4-D
[3, heads, head_dim, d]; reference checkpoints need a reshape on load.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.layer_base import Layer
from ...nn import initializer as I
from . import functional as FF

__all__ = [
    "FusedLinear", "FusedDropoutAdd", "FusedBiasDropoutResidualLayerNorm",
    "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer", "FusedEcMoe",
]


class FusedLinear(Layer):
    """(fused_linear.py:19) Linear through the fused-gemm-epilogue path;
    on TPU the bias add fuses into the matmul under XLA."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose = transpose_weight
        shape = [out_features, in_features] if transpose_weight \
            else [in_features, out_features]
        self.weight = self.create_parameter(
            shape=shape, attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = None if bias_attr is False else \
            self.create_parameter(shape=[out_features], attr=bias_attr,
                                  is_bias=True)

    def forward(self, x):
        return FF.fused_linear(x, self.weight, self.bias,
                               transpose_weight=self._transpose)


class FusedDropoutAdd(Layer):
    """(fused_dropout_add.py:19) dropout(x) + y in one fused region."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        import paddle_tpu.nn.functional as F

        return F.dropout(x, p=self.p, training=self.training,
                         mode=self.mode) + y

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedBiasDropoutResidualLayerNorm(Layer):
    """(fused_transformer.py:83) LN(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self._dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.ln_scale = self.create_parameter(
            shape=[embed_dim], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(shape=[embed_dim],
                                             attr=bias_attr, is_bias=True)
        self.linear_bias = self.create_parameter(
            shape=[embed_dim], attr=None, is_bias=True)

    def forward(self, x, residual):
        return FF.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            dropout_rate=self._dropout_rate, epsilon=self._epsilon,
            training=self.training)


class FusedMultiHeadAttention(Layer):
    """(fused_transformer.py:196) pre/post-LN MHA + residual as one
    fused region (the fused_attention op)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False,
                 name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self._attn_dropout_rate = attn_dropout_rate
        self._dropout_rate = dropout_rate  # out-proj/residual dropout
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            shape=[embed_dim, 3 * embed_dim], attr=qkv_weight_attr,
            default_initializer=I.XavierNormal())
        self.qkv_bias = self.create_parameter(
            shape=[3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            shape=[embed_dim, embed_dim], attr=linear_weight_attr,
            default_initializer=I.XavierNormal())
        self.linear_bias = self.create_parameter(
            shape=[embed_dim], attr=linear_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            shape=[embed_dim], attr=pre_ln_scale_attr
            if normalize_before else ln_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            shape=[embed_dim], attr=pre_ln_bias_attr
            if normalize_before else ln_bias_attr, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if (key is not None and key is not query) or \
                (value is not None and value is not query):
            raise NotImplementedError(
                "FusedMultiHeadAttention: cross-attention (key/value != "
                "query) is not supported by the fused path")
        if cache is not None:
            raise NotImplementedError(
                "FusedMultiHeadAttention: incremental cache decoding is "
                "not supported; use incubate.nn.FusedMultiTransformer")
        return FF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            num_heads=self.num_heads, attn_mask=attn_mask,
            dropout_rate=self._attn_dropout_rate,
            out_dropout_rate=self._dropout_rate,
            pre_layer_norm=self.normalize_before,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            epsilon=self._epsilon, training=self.training)


class FusedFeedForward(Layer):
    """(fused_transformer.py:502) [pre-LN] → fc1 → act → dropout → fc2 →
    dropout → residual [→ post-LN]."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._dropout_rate = dropout_rate
        self._act_dropout = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self._activation = activation
        self._epsilon = epsilon
        self.normalize_before = normalize_before
        self.linear1_weight = self.create_parameter(
            shape=[d_model, dim_feedforward], attr=linear1_weight_attr,
            default_initializer=I.XavierNormal())
        self.linear1_bias = self.create_parameter(
            shape=[dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            shape=[dim_feedforward, d_model], attr=linear2_weight_attr,
            default_initializer=I.XavierNormal())
        self.linear2_bias = self.create_parameter(
            shape=[d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            shape=[d_model], attr=ln1_scale_attr
            if normalize_before else ln2_scale_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            shape=[d_model], attr=ln1_bias_attr
            if normalize_before else ln2_bias_attr, is_bias=True)

    def forward(self, src, cache=None):
        import paddle_tpu.nn.functional as F

        h = src
        if self.normalize_before:
            h = FF.fused_layer_norm(h, self.ln_scale, self.ln_bias,
                                    self._epsilon)
        h = FF.fused_linear(h, self.linear1_weight, self.linear1_bias)
        h = getattr(F, self._activation)(h)
        h = F.dropout(h, p=self._act_dropout, training=self.training)
        h = FF.fused_linear(h, self.linear2_weight, self.linear2_bias)
        h = F.dropout(h, p=self._dropout_rate, training=self.training)
        out = src + h
        if not self.normalize_before:
            out = FF.fused_layer_norm(out, self.ln_scale, self.ln_bias,
                                      self._epsilon)
        return out


class FusedTransformerEncoderLayer(Layer):
    """(fused_transformer.py:728) fused MHA + fused FFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedEcMoe(Layer):
    """(fused_ec_moe.py:19) expert-choice MoE: gate → per-expert FFN via
    one batched einsum pair (the cutlass grouped-GEMM's XLA form)."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if act_type not in ("gelu", "relu"):
            raise ValueError("act_type must be gelu or relu")
        self._act = act_type
        self.gate = self.create_parameter(
            shape=[hidden_size, num_experts], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.w1 = self.create_parameter(
            shape=[num_experts, hidden_size, inter_size],
            attr=weight_attr, default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter(
            shape=[num_experts, 1, inter_size], attr=bias_attr,
            is_bias=True)
        self.w2 = self.create_parameter(
            shape=[num_experts, inter_size, hidden_size],
            attr=weight_attr, default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter(
            shape=[num_experts, 1, hidden_size], attr=bias_attr,
            is_bias=True)

    def forward(self, x, gate_logits=None):
        import jax

        from ...ops.dispatch import as_tensor_args, eager_apply

        act = self._act
        has_logits = gate_logits is not None
        tensors = as_tensor_args(
            *((x, self.gate, self.w1, self.b1, self.w2, self.b2,
               gate_logits) if has_logits else
              (x, self.gate, self.w1, self.b1, self.w2, self.b2)))

        def raw(xd, gate, w1, b1, w2, b2, *maybe_logits):
            logits = maybe_logits[0] if maybe_logits else xd @ gate
            probs = jax.nn.softmax(logits, axis=-1)    # [b, s, E]
            # dense expert-weighted mixture: every expert is one batched
            # GEMM (MXU-shaped); gating weights mix the outputs
            h = jnp.einsum("bsd,edi->ebsi", xd, w1) + b1[:, None]
            h = jax.nn.gelu(h) if act == "gelu" else jax.nn.relu(h)
            y = jnp.einsum("ebsi,eid->ebsd", h, w2) + b2[:, None]
            return jnp.einsum("ebsd,bse->bsd", y, probs)

        return eager_apply("fused_ec_moe", raw, tensors)
