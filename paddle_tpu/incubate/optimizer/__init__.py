"""incubate.optimizer — LookAhead, ModelAverage.

TPU-native equivalent of the reference's incubate optimizers (reference:
python/paddle/incubate/optimizer/lookahead.py LookAhead — slow/fast
weights with k-step interpolation; modelaverage.py ModelAverage —
running parameter average applied at eval via apply()/restore()).
DistributedFusedLamb lives in distributed_fused_lamb.py; the plain Lamb in
paddle_tpu.optimizer covers its math (single fused XLA program).
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["LookAhead", "ModelAverage", "LocalSGD", "DGCMomentum"]


class DGCMomentum:
    """Deep Gradient Compression momentum SGD (reference:
    distributed/fleet/meta_optimizers/dgc_optimizer.py
    DGCMomentumOptimizer; Lin et al., DGC). Each step, per parameter:
    momentum-correct into a local velocity (u = m*u + g), accumulate
    (v += u), select the top-k |v| entries (k = (1-sparsity)*numel,
    STATIC so the whole step stays one compiled shape), zero them out
    of v (the residual stays local), and synchronize ONLY those k
    (value, index) pairs across the data-parallel group — an
    all_gather of 2k floats instead of an all_reduce of the full
    gradient. The synchronized sparse sum updates the parameters with
    plain SGD.

    TPU-native design notes: the reference rewrites the static graph
    with dgc ops + sparse allreduce over NCCL; here sparsification is
    ``jax.lax.top_k`` (static k), the wire format is dense
    [world, 2, k] from the collective facade, and the scatter-add back
    is a ``.at[].add``. With no initialized parallel env (or world 1)
    the "sync" is just the local sparse tensor, so the wrapper is
    usable (and testable) single-process.
    """

    def __init__(self, parameters, learning_rate=0.01, momentum=0.9,
                 sparsity=0.999):
        if not 0.0 <= sparsity < 1.0:
            raise ValueError("sparsity must be in [0, 1)")
        self._parameter_list = list(parameters)
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.sparsity = sparsity
        self._u = [jnp.zeros(p.shape, jnp.float32).reshape(-1)
                   for p in self._parameter_list]
        self._v = [jnp.zeros(p.shape, jnp.float32).reshape(-1)
                   for p in self._parameter_list]

    @staticmethod
    def _k_for(numel: int, sparsity: float) -> int:
        return max(1, int(round(numel * (1.0 - sparsity))))

    def _sync_sparse(self, vals, idxs):
        """All-gather the (values, indices) pairs and scatter-add into
        a dense sum; local no-op outside a >1 world."""
        import paddle_tpu.distributed as dist

        if not (dist.is_initialized() and dist.get_world_size() > 1):
            return vals, idxs.astype(jnp.int32), None
        world = dist.get_world_size()
        pack = Tensor(jnp.stack([vals, idxs.astype(jnp.float32)]))
        outs: List[Tensor] = []
        dist.all_gather(outs, pack)
        allv = jnp.concatenate([o._data[0] for o in outs])
        alli = jnp.concatenate([o._data[1].astype(jnp.int32)
                                for o in outs])
        return allv / world, alli, world

    def step(self):
        lr = float(self.learning_rate() if callable(self.learning_rate)
                   else self.learning_rate)
        for i, p in enumerate(self._parameter_list):
            if p.grad is None:
                continue
            g = p.grad._data.astype(jnp.float32).reshape(-1)
            u = self.momentum * self._u[i] + g
            v = self._v[i] + u
            k = self._k_for(v.shape[0], self.sparsity)
            _topv, idx = jax.lax.top_k(jnp.abs(v), k)
            vals = v[idx]
            # residual stays local; momentum factor masking (DGC §3.2):
            # the communicated entries also clear their velocity
            v = v.at[idx].set(0.0)
            u = u.at[idx].set(0.0)
            self._u[i], self._v[i] = u, v
            allv, alli, _w = self._sync_sparse(vals, idx)
            dense = jnp.zeros_like(v).at[alli].add(allv)
            upd = (p._data.astype(jnp.float32).reshape(-1)
                   - lr * dense).reshape(p.shape)
            p._rebind(upd.astype(p._data.dtype))

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    def clear_grad(self):
        for p in self._parameter_list:
            p.clear_grad()


class LocalSGD:
    """Local SGD (reference:
    distributed/fleet/meta_optimizers/localsgd_optimizer.py
    LocalSGDOptimizer): run ``k_steps`` purely-local inner steps, then
    synchronize by averaging parameters across the data-parallel group
    — trading gradient-every-step communication for param-every-k.
    Wrap any pytree optimizer; with no initialized parallel env (or a
    1-process world) the sync is a no-op and the wrapper is just the
    inner optimizer.

    The reference implements this as a static-graph meta-optimizer
    rewriting the program with snapshot vars + c_allreduce; here the
    sync is one eager collective per param every k steps.
    """

    def __init__(self, inner_optimizer, k_steps: int = 1):
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1")
        self.inner_optimizer = inner_optimizer
        self.k_steps = k_steps
        self._step_count = 0

    @property
    def _params(self) -> List[Tensor]:
        return self.inner_optimizer._parameter_list

    def _sync(self):
        import paddle_tpu.distributed as dist

        if not (dist.is_initialized() and dist.get_world_size() > 1):
            return
        scale = 1.0 / dist.get_world_size()
        for p in self._params:
            t = Tensor(p._data * scale)
            dist.all_reduce(t)
            p._rebind(t._data)

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k_steps == 0:
            self._sync()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def __getattr__(self, item):
        if item == "inner_optimizer":  # pickle/copy before __init__
            raise AttributeError(item)
        return getattr(self.inner_optimizer, item)


class LookAhead:
    """k-step lookahead wrapper (reference: lookahead.py LookAhead:66).

    Every k inner steps: slow += alpha * (fast - slow); fast = slow.
    """

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5,
                 name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        if k < 1:
            raise ValueError("k must be >= 1")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_count = 0
        # slow weights seeded at the CURRENT (pre-training) values —
        # the reference's first sync interpolates back toward these
        # (lookahead.py: slow initialized from the param at decoration)
        self._slow: Dict[int, jnp.ndarray] = {
            id(p): jnp.copy(p._data)
            for p in inner_optimizer._parameter_list}

    @property
    def _params(self) -> List[Tensor]:
        return self.inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k:
            return
        for p in self._params:
            slow = self._slow[id(p)]
            slow = slow + self.alpha * (p._data - slow)
            self._slow[id(p)] = slow
            # the param gets its OWN buffer: the fused update donates
            # (deletes) param buffers, and _slow must survive that
            p._rebind(jnp.copy(slow))

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def __getattr__(self, item):
        if item == "inner_optimizer":  # pickle/copy before __init__
            raise AttributeError(item)
        return getattr(self.inner_optimizer, item)


class ModelAverage:
    """Windowed parameter average (reference: modelaverage.py
    ModelAverage:44): accumulate after each step; ``apply()`` swaps the
    averaged weights in for evaluation, ``restore()`` swaps back.

    Window semantics follow the reference's accumulator rotation: the
    live window is rate-scaled and clamped to
    [min_average_window, max_average_window]; on overflow it rolls into
    an old-window accumulator, so the average spans at most two recent
    windows and stale early-training weights age out."""

    def __init__(self, average_window_rate: float = 0.15,
                 parameters=None, min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        if parameters is None:
            raise ValueError("pass parameters=model.parameters()")
        self._parameters = list(parameters)
        self.average_window_rate = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._sum: Dict[int, jnp.ndarray] = {}
        self._count = 0
        # previous window (the reference's old-sum accumulator): when
        # the live window hits max_average_window it rolls over here,
        # so the average spans at most two windows of recent history
        self._old_sum: Dict[int, jnp.ndarray] = {}
        self._old_count = 0
        self._num_updates = 0
        self._backup: Dict[int, jnp.ndarray] = {}
        self._applied = False
        self._need_restore = True

    def _window(self) -> int:
        """Effective window length (reference modelaverage semantics:
        rate-scaled, clamped to [min, max]_average_window)."""
        target = int(self._num_updates * self.average_window_rate)
        return max(self.min_average_window,
                   min(self.max_average_window, max(target, 1)))

    def step(self):
        """Accumulate the current parameter values (call after the
        inner optimizer's step)."""
        self._num_updates += 1
        if self._count >= self._window():
            # roll the live window into the old accumulator (reference:
            # sum_1/sum_2 rotation) so stale history ages out
            self._old_sum = self._sum
            self._old_count = self._count
            self._sum = {}
            self._count = 0
        for p in self._parameters:
            cur = self._sum.get(id(p))
            # copy on first capture: donated buffers die on next step
            self._sum[id(p)] = jnp.copy(p._data) if cur is None \
                else cur + p._data
        self._count += 1

    def apply(self, executor=None, need_restore: bool = True):
        """Swap averaged weights in (reference: apply:228)."""
        if self._count == 0:
            raise RuntimeError("ModelAverage.apply before any step()")
        if self._applied:
            raise RuntimeError("apply() without restore()")
        total = self._count + self._old_count
        for p in self._parameters:
            self._backup[id(p)] = jnp.copy(p._data)
            s = self._sum[id(p)]
            if self._old_count:
                s = s + self._old_sum[id(p)]
            p._rebind((s / total).astype(p._data.dtype))
        self._applied = True
        self._need_restore = need_restore

    def restore(self, executor=None):
        """Swap the live training weights back (reference: restore:283).
        After apply(need_restore=False) the averaged weights are
        permanent: restore() only clears the applied state."""
        if not self._applied:
            return
        if not self._need_restore:
            self._backup.clear()
            self._applied = False
            return
        for p in self._parameters:
            p._rebind(self._backup[id(p)])
        self._backup.clear()
        self._applied = False

from .distributed_fused_lamb import DistributedFusedLamb  # noqa: F401,E402
__all__ = list(globals().get('__all__', [])) + ['DistributedFusedLamb']
