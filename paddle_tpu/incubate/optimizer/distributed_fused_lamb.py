"""DistributedFusedLamb (reference:
python/paddle/incubate/optimizer/distributed_fused_lamb.py:115 — LAMB
with flattened/aligned param storage, dp-sharded optimizer states,
fused CUDA update, optional gradient accumulation).

TPU-native design: the base ``Lamb`` already runs the whole update as
ONE compiled XLA program over the parameter pytree (the fused
multi-tensor path), so the "fused" half is free. The distributed half
maps the reference's sharded-state allreduce pipeline onto GSPMD:
optimizer states are sharded over the dp mesh axis via
``shard_optimizer_states`` (ZeRO-1), and gradient accumulation keeps a
running sum and applies the update every N steps.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...optimizer.adam import Lamb

__all__ = ["DistributedFusedLamb"]


class DistributedFusedLamb(Lamb):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 nproc_per_node=None, use_hierarchical_allreduce=False,
                 name=None):
        super().__init__(
            learning_rate=learning_rate,
            lamb_weight_decay=lamb_weight_decay, beta1=beta1, beta2=beta2,
            epsilon=epsilon, parameters=parameters, grad_clip=grad_clip,
            exclude_from_weight_decay_fn=exclude_from_weight_decay_fn,
            name=name)
        self._acc_steps = int(gradient_accumulation_steps)
        assert self._acc_steps >= 1
        self._acc_count = 0
        self._acc_grads = {}
        # dp-sharded optimizer states (the reference's sharded LAMB
        # pipeline; ZeRO-1 over the data-parallel axis) when a hybrid
        # group is live
        try:
            from ...distributed import fleet
            from ...distributed.fleet.meta_parallel.sharding \
                .sharding_optimizer import shard_optimizer_states

            hcg = fleet.get_hybrid_communicate_group()
            if hcg is not None and hcg.get_data_parallel_world_size() > 1:
                shard_optimizer_states(self, hcg, axis="dp")
        except Exception:
            pass  # single-process / fleet not initialized

    def step(self):
        """Accumulate for gradient_accumulation_steps, then run the
        fused LAMB update on the mean gradient."""
        if self._acc_steps == 1:
            return super().step()
        self._acc_count += 1
        for p in self._parameter_list:
            if p.stop_gradient or p.grad is None:
                continue
            acc = self._acc_grads.get(id(p))
            g = p.grad._data
            self._acc_grads[id(p)] = g if acc is None else acc + g
        if self._acc_count < self._acc_steps:
            for p in self._parameter_list:
                p.clear_gradient()
            return None
        for p in self._parameter_list:
            if id(p) in self._acc_grads:
                p.grad = Tensor(self._acc_grads[id(p)]
                                / float(self._acc_steps))
        self._acc_grads.clear()
        self._acc_count = 0
        return super().step()
