"""paddle_tpu.inference — deployment predictor API + serving engine.

TPU-native equivalent of the reference's inference stack (reference:
paddle/fluid/inference/api/analysis_predictor.h:100 AnalysisPredictor;
Python wrapper python/paddle/inference). The reference pipeline is
load program+params → IR pass pipeline → optimized executor; here it is
load jit-saved StableHLO + params → XLA compile (XLA *is* the pass
pipeline) → PJRT executable, with device-resident handles standing in
for zero-copy tensors.

Serving extras (paged-KV attention + fused decode) live in
``inference.engine`` / ``inference.kv_cache``.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .engine import (DEFAULT_DECODE_CHUNK, ContinuousBatchingEngine,
                     FusedCausalLM, GenerationEngine, GenRequest)
from .kv_cache import BlockKVCacheManager
from .speculative import (Drafter, DraftModelDrafter, ScheduledDrafter,
                          SelfDraftHeads, SpeculativeDecoder)

__all__ = [
    "Config", "create_predictor", "Predictor", "PredictorTensor",
    "FusedCausalLM", "GenerationEngine", "BlockKVCacheManager",
    "ContinuousBatchingEngine", "GenRequest", "DEFAULT_DECODE_CHUNK",
    "Drafter", "DraftModelDrafter", "SelfDraftHeads",
    "ScheduledDrafter", "SpeculativeDecoder",
]


class Config:
    """Predictor configuration (reference: AnalysisConfig,
    paddle/fluid/inference/api/paddle_analysis_config.h; Python
    paddle.inference.Config). Device/precision toggles are recorded;
    graph-optimization switches are accepted for compatibility — XLA
    always optimizes, there is no unoptimized executor to fall back to."""

    def __init__(self, prog_file: str, params_file: Optional[str] = None):
        # accept either the jit.save prefix or the .pdmodel path
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._use_tpu = True
        self._precision = "float32"
        self._memory_optim = True
        self._ir_optim = True

    def model_path(self) -> str:
        return self._prefix

    # --- device toggles (reference: enable_use_gpu/disable_gpu) ---
    def enable_tpu(self):
        self._use_tpu = True

    def disable_tpu(self):
        self._use_tpu = False

    def enable_use_gpu(self, *a, **k):  # API-compat alias
        self.enable_tpu()

    def disable_gpu(self):
        self.disable_tpu()

    def use_tpu(self) -> bool:
        return self._use_tpu

    # --- precision / optimization toggles ---
    def enable_bf16(self):
        self._precision = "bfloat16"

    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def set_cpu_math_library_num_threads(self, n: int):
        pass  # XLA owns its threadpool

    def summary(self) -> str:
        return (f"Config(model={self._prefix!r}, tpu={self._use_tpu}, "
                f"precision={self._precision})")


class PredictorTensor:
    """Device-resident I/O handle (reference: ZeroCopyTensor,
    paddle/fluid/inference/api/details/zero_copy_tensor.cc). copy_from_cpu
    stages a host array; after run(), copy_to_cpu materializes the output
    without an intermediate framework tensor."""

    def __init__(self, name: str):
        self.name = name
        self._array = None
        self._shape = None

    def reshape(self, shape):
        self._shape = tuple(int(s) for s in shape)
        if self._array is not None:
            self._array = jnp.reshape(self._array, self._shape)

    def copy_from_cpu(self, arr: np.ndarray):
        a = jnp.asarray(arr)
        if self._shape is not None:
            a = jnp.reshape(a, self._shape)  # reshape-then-copy order
        self._array = a

    def copy_to_cpu(self) -> np.ndarray:
        if self._array is None:
            raise RuntimeError(f"output {self.name!r} not computed yet")
        return np.asarray(self._array)

    def shape(self):
        return None if self._array is None else tuple(self._array.shape)


class Predictor:
    """Compiled predictor over a jit.save artifact (reference:
    AnalysisPredictor::Run, analysis_predictor.h:100)."""

    def __init__(self, config: Config):
        from .. import jit

        self._config = config
        self._layer = jit.load(config.model_path())
        n_in = None
        if self._layer._exported is not None:
            # exported signature: (params, buffers, *args)
            n_total = len(self._layer._exported.in_avals)
            n_state = len(self._layer._meta["param_names"])
            n_in = n_total - n_state
        self._input_names = [f"input_{i}" for i in range(n_in or 1)]
        self._inputs: Dict[str, PredictorTensor] = {
            n: PredictorTensor(n) for n in self._input_names}
        self._outputs: Dict[str, PredictorTensor] = {}
        self._output_names: List[str] = []

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> PredictorTensor:
        return self._inputs[name]

    def run(self) -> bool:
        args = []
        for n in self._input_names:
            h = self._inputs[n]
            if h._array is None:
                raise RuntimeError(f"input {n!r} was not set")
            args.append(Tensor(h._array))
        out = self._layer(*args)
        outs = out if isinstance(out, tuple) else (out,)
        self._output_names = [f"output_{i}" for i in range(len(outs))]
        self._outputs = {}
        for n, o in zip(self._output_names, outs):
            h = PredictorTensor(n)
            h._array = o._data
            self._outputs[n] = h
        return True

    def get_output_names(self) -> List[str]:
        return list(self._output_names) or ["output_0"]

    def get_output_handle(self, name: str) -> PredictorTensor:
        return self._outputs[name]


def create_predictor(config: Config) -> Predictor:
    """reference: paddle_infer::CreatePredictor."""
    return Predictor(config)
