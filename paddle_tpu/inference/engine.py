"""Generation engine: compiled prefill + paged-KV decode loop.

TPU-native equivalent of the reference's fused-decode serving spine
(reference: paddle/fluid/operators/fused/fused_multi_transformer_op.cu
driving AnalysisPredictor-run programs, with paged KV via
block_multi_head_attention_kernel.cu). Here both phases are single XLA
programs: prefill(x[b,s]) and decode_step(token[b]) are jit-compiled
once per shape with the cache donated, so steady-state decode is one
device program per token with zero host round-trips in the stack.
"""
from __future__ import annotations

import itertools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..incubate.nn.fused_transformer import (
    FusedMultiTransformer, PagedKV, rope_table)
from ..nn.layer_base import Layer
from ..profiler import roofline as _roofline
from ..profiler import stats as _stats
from .kv_cache import BlockKVCacheManager, gather_rows, restore_scatter_jit

__all__ = ["FusedCausalLM", "GenerationEngine",
           "ContinuousBatchingEngine", "GenRequest",
           "DEFAULT_DECODE_CHUNK"]

#: auto-picked decode scan-chunk: 128 measured best on the 1.3B bench
#: geometry (chunk 64 -> 128: +7% tok/s, bench_profile.json r5 — one
#: scan program covers the whole generation, so chunk-boundary pool
#: relayout + the per-chunk host sync amortize). Callers pass an
#: explicit ``decode_chunk`` to override (small chunks keep
#: continuous-batching admit latency low on interactive traffic).
DEFAULT_DECODE_CHUNK = 128


def _resolve_decode_chunk(decode_chunk) -> int:
    if decode_chunk is None:
        return DEFAULT_DECODE_CHUNK
    return max(int(decode_chunk), 1)


def _round_pool_pages(n: int, page_size: int) -> int:
    """Round a pool size up so a stream-attention chunk size divides it
    — the chunk DMA then never crosses the layer-region boundary.

    The rounding quantum is the FULL chunk (stream_chunk_pages, 1024
    tokens) capped at the next power of two >= n: without the cap, tiny
    pools at small page sizes inflate drastically (page_size=4: 25
    requested pages -> 256, ~10x HBM). With it, the pool stays within
    2x of the request and remains a power-of-two multiple that
    _pick_chunk_pages can divide exactly (the kernels then run with a
    proportionally smaller chunk — fine for pools this small). The
    engines expose the final rounded size via the
    ``inference.pool_pages`` stats gauge."""
    from ..nn.functional.paged_attention import stream_chunk_pages

    chunk = stream_chunk_pages(page_size)
    next_pow2 = 1
    while next_pow2 < n:
        next_pow2 *= 2
    quantum = min(chunk, next_pow2)
    return -(-n // quantum) * quantum


class FusedCausalLM(Layer):
    """Minimal GPT-style causal LM over FusedMultiTransformer:
    token embedding (tied lm head) + stack + final LN."""

    def __init__(self, vocab_size, embed_dim, num_heads, dim_feedforward,
                 num_layers, num_kv_heads=None, max_position=32768,
                 rope_theta=10000.0, moe_num_experts=None, moe_top_k=2):
        super().__init__()
        from ..core.tensor import Parameter

        from ..core.generator import default_generator

        self.vocab_size = vocab_size
        self.embed = Parameter(
            jax.random.normal(default_generator().next_key(),
                              (vocab_size, embed_dim), jnp.float32) * 0.02)
        self.stack = FusedMultiTransformer(
            embed_dim, num_heads, dim_feedforward, num_layers,
            num_kv_heads=num_kv_heads, max_position=max_position,
            rope_theta=rope_theta, moe_num_experts=moe_num_experts,
            moe_top_k=moe_top_k)
        self.lnf_scale = Parameter(jnp.ones((embed_dim,), jnp.float32))
        self.lnf_bias = Parameter(jnp.zeros((embed_dim,), jnp.float32))

    def _final(self, h):
        h = FusedMultiTransformer._ln(
            h, self.lnf_scale._data, self.lnf_bias._data,
            self.stack.epsilon)
        return h @ self.embed._data.T

    def forward(self, ids):
        """Plain full-sequence forward (training/eval parity path):
        logits [b, s, vocab]. No cache involved."""
        ids_d = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
        x = self.embed._data[ids_d]
        cos_t, sin_t = rope_table(self.stack.max_position,
                                  self.stack.head_dim,
                                  self.stack.rope_theta)
        h, _ = self.stack.prefill_raw(
            self.stack._stack(), x, None, None, cos_t, sin_t)
        return Tensor(self._final(h))


class GenerationEngine:
    """Continuous single-batch generation over a FusedCausalLM.

    generate(): prefill the prompt (one compiled program), then a
    compiled decode step per token. The decode program takes and returns
    the paged cache with donated buffers — the cache never leaves HBM.
    """

    def __init__(self, model: FusedCausalLM, page_size: int = 16,
                 max_length: int = 1024, num_pages: Optional[int] = None,
                 decode_chunk: Optional[int] = None, kv_dtype=None,
                 quant: Optional[str] = None, mesh=None,
                 mp_degree: Optional[int] = None,
                 ep_degree: Optional[int] = None):
        self.model = model
        st = model.stack
        self.max_length = max_length
        self.page_size = page_size
        self.decode_chunk = _resolve_decode_chunk(decode_chunk)
        self._cos, self._sin = rope_table(st.max_position, st.head_dim,
                                          st.rope_theta)
        self._init_serving_state(kv_dtype, quant, mesh=mesh,
                                 mp_degree=mp_degree,
                                 ep_degree=ep_degree)
        self._num_pages = num_pages
        self._mgr = None

    def _init_serving_state(self, kv_dtype, quant=None, mesh=None,
                            mp_degree=None, ep_degree=None):
        """Serving dtype discipline + compiled-program holders (shared
        with ContinuousBatchingEngine): the COMPUTE dtype follows the
        stack weights (cast them bf16 for the bandwidth-bound serving
        path; fp32 stacks keep exact dense parity; int8 = weight-only
        quantized → compute bf16), the KV pool follows kv_dtype
        (default: same as compute), and the lm head is a PRE-TRANSPOSED
        [d, vocab] copy in compute dtype with fp32 accumulation in the
        logits dot.

        ``quant``: None | "int8" (weight-only) | "a8w8" (weight-only
        int8 PLUS per-token dynamic int8 activations into int8 x int8
        matmuls). Both quantize the model's stack IN PLACE when it is
        not already int8.

        ``mesh`` / ``mp_degree``: tensor-parallel serving over an
        ``mp`` mesh axis (distributed/tp.py). The stacked weights are
        sharded AT LOAD — column/row slices per chip, the QKV columns
        rearranged so attention heads partition with them — the KV
        pool shards by kv-head, and every decode/prefill program runs
        under shard_map with exactly one psum per column→row
        projection pair. Rungs report with an ``,mp=N`` suffix and
        ``dist.mp_degree`` lands in telemetry."""
        if quant not in (None, "int8", "a8w8"):
            raise ValueError(
                f"quant={quant!r}: expected None, 'int8' or 'a8w8'")
        st = self.model.stack
        from ..distributed.tp import TPContext

        self._tp = TPContext.create(
            st.num_heads, st.num_kv_heads, st.head_dim,
            mp_degree=mp_degree, mesh=mesh, ep_degree=ep_degree)
        if self._tp is not None and self._tp.ep > 1 \
                and not st.moe_num_experts:
            raise ValueError(
                "ep_degree shards the MoE expert bank — the stack has "
                "no experts (pass moe_num_experts to the model, or "
                "use mp_degree for dense tensor parallelism)")
        if quant is not None and \
                st.qkv_weight._data.dtype != jnp.int8:
            st.quantize_weight_only_int8()
        self._a8w8 = quant == "a8w8"
        wd = st.qkv_weight._data.dtype
        self._cdtype = jnp.bfloat16 if wd == jnp.int8 else wd
        self._kv_dtype = kv_dtype or self._cdtype
        self._head_t = jnp.array(self.model.embed._data.T) \
            .astype(self._cdtype)
        if self._tp is not None:
            # shard-at-load: per-chip column/row weight slices; the
            # replicated operands (embed, lm head, final LN) are
            # device_put once so no per-call host transfer (and no
            # mixing of single-device-committed arrays into the
            # mesh-sharded programs)
            tp = self._tp
            self._tp_weights = tp.shard_stack(st._stack())
            self._head_t = tp.replicate(self._head_t)
            self._embed_tp = tp.replicate(self.model.embed._data)
            self._lnf_tp = (tp.replicate(self.model.lnf_scale._data),
                            tp.replicate(self.model.lnf_bias._data))
            _stats.set_gauge("dist.mp_degree", tp.mp)
            if tp.ep > 1:
                _stats.set_gauge("dist.ep_degree", tp.ep)
        # roofline rung names: A8W8 programs report under their own
        # ``decode.a8w8``/``prefill.a8w8`` keys, and the grouped
        # weight-stream path (FLAGS_decode_grouped, the r6 default for
        # non-a8w8 stacks) under ``decode.<dtype>_grouped`` — so the
        # serving modes' achieved-bandwidth rows never mix (bench.py
        # picks these up; the flag is read once at engine init, matching
        # when the decode programs trace)
        from ..core.flags import flag as _flag

        g = _flag("decode_grouped")
        is_moe = bool(st.moe_num_experts)
        self._grouped = (not is_moe) and (
            g == "on" or (g == "auto" and not self._a8w8))
        if is_moe:
            # MoE stacks route the FFN through the ragged grouped-GEMM
            # path (the fused dense tail doesn't apply) — own rung name
            self._decode_tag = "decode.moe"
        elif self._a8w8:
            self._decode_tag = "decode.a8w8"
        elif self._grouped:
            wname = ("int8" if wd == jnp.int8 else
                     "bf16" if self._cdtype == jnp.bfloat16 else "f32")
            self._decode_tag = f"decode.{wname}_grouped"
        else:
            self._decode_tag = "decode"
        # one jitted prefill; decode programs are per-chunk-size (k=1
        # is the single-token step); cache operands are donated. Both
        # dispatch through the explicit-AOT wrapper so each program's
        # XLA cost model (flops, bytes accessed — the decode step's
        # weight+KV traffic) feeds the roofline telemetry
        # (profiler/roofline.py) instead of a hand-derived byte count.
        self._prefill = _roofline.AotProgram(
            ("prefill.a8w8" if self._a8w8 else "prefill")
            + self._mp_suffix(),
            jax.jit(self._prefill_fn, donate_argnums=(7, 8)))
        self._decode_k_jit = {}

    def _dist_coords(self) -> str:
        """``mp=N`` / ``ep=N`` rung coordinates under tensor/expert
        parallelism (README metric conventions)."""
        if self._tp is None:
            return ""
        parts = []
        if self._tp.mp > 1:
            parts.append(f"mp={self._tp.mp}")
        if self._tp.ep > 1:
            parts.append(f"ep={self._tp.ep}")
        return ",".join(parts)

    def _mp_suffix(self) -> str:
        """``[mp=N]``/``[ep=N]`` rung suffix under tensor/expert
        parallelism (composes as ``[k=*,mp=N]`` on decode)."""
        c = self._dist_coords()
        return f"[{c}]" if c else ""

    def _decode_rung(self, k: int, adaptered: bool = False) -> str:
        """Roofline rung name of the k-step decode program —
        ``decode.bf16_grouped[k=8,mp=2]``-shaped under TP. The
        adaptered variant (multi-LoRA delta path) is its own rung:
        it runs the per-projection f32 loop, not the grouped tail."""
        c = self._dist_coords()
        tag = "decode.lora" if adaptered else self._decode_tag
        return f"{tag}[k={k}{',' + c if c else ''}]"

    def _weights(self):
        """The decode/prefill weight-stack operand: the shard-at-load
        TP stacks when a mesh is configured, the model's plain stacked
        dict otherwise (fresh dict of the same arrays — cheap)."""
        return self._tp_weights if self._tp is not None \
            else self.model.stack._stack()

    def _embed(self):
        return self._embed_tp if self._tp is not None \
            else self.model.embed._data

    def _lnf(self):
        if self._tp is not None:
            return self._lnf_tp
        return (self.model.lnf_scale._data, self.model.lnf_bias._data)

    def _get_decode_k(self, k: int, sample_cfg=None,
                      adaptered: bool = False):
        """One compiled program per (chunk size, greedy-vs-sample,
        top_k, adaptered); temperature/top_p flow in as traced
        scalars so per-request values never recompile. ``adaptered``
        adds the multi-LoRA delta operands (slot map + weight banks)
        as TRACED arrays: adapter membership and hot load/unload
        never retrace — the compiled-program count is independent of
        the adapter set (at most 2 programs per chunk size)."""
        key = (k, sample_cfg, adaptered)
        if key not in self._decode_k_jit:
            import functools

            self._decode_k_jit[key] = _roofline.AotProgram(
                self._decode_rung(k, adaptered),
                jax.jit(functools.partial(self._decode_k_fn, k=k,
                                          sample_cfg=sample_cfg),
                        donate_argnums=(7, 8)))
        return self._decode_k_jit[key]

    def _count_a8w8(self, steps: int):
        """Python-side ``quant.*`` accounting for executed A8W8 work
        (inside the traced programs the quant ops run once per compile,
        so the dispatch layer counts per EXECUTED step: 4 matmuls per
        layer per step, each preceded by one dynamic act-quant)."""
        if self._a8w8:
            n = 4 * self.model.stack.num_layers * steps
            _stats.inc("quant.act_quant_calls", n)
            _stats.inc("quant.a8w8_matmuls", n)

    # ---------- pure programs ----------

    def _logits(self, h, head_t, lnf_s, lnf_b):
        """LM head: final LN + pre-transposed [d, vocab] matmul with
        fp32 accumulation (argmax/sampling happen on fp32 logits);
        weight-streamed on TPU (stream_linear) like the stack matmuls."""
        from ..core.flags import flag
        from ..nn.functional.stream_linear import stream_linear

        hl = FusedMultiTransformer._ln(
            h, lnf_s, lnf_b, self.model.stack.epsilon) \
            .astype(head_t.dtype)
        if flag("decode_linear") == "stream" and hl.shape[0] % 8 == 0:
            return stream_linear(hl, head_t, out_dtype=jnp.float32)
        return jax.lax.dot_general(
            hl, head_t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def _prefill_fn(self, weights, embed, head_t, lnf_s, lnf_b, ids,
                    seq_lens, cache_k, cache_v, tables):
        """Prompt pass over a right-padded batch: ``seq_lens[b]`` are the
        real prompt lengths (the reference's per-request seq_lens input,
        block_multi_head_attention_kernel.cu). Logits are gathered at
        each sequence's own last real position; pad-position KV is
        causal-dead and later overwritten by decode writes."""
        st = self.model.stack
        x = embed[ids].astype(self._cdtype)
        h, cache = st.prefill_raw(
            weights, x, PagedKV(cache_k, cache_v), tables,
            self._cos, self._sin, a8w8=self._a8w8, tp=self._tp)
        hl = h[jnp.arange(h.shape[0]), seq_lens - 1]
        logits = self._logits(hl, head_t, lnf_s, lnf_b)
        return logits, cache.k, cache.v

    @staticmethod
    def _argmax(logits):
        """Greedy pick as three lane-friendly passes (max, equality,
        min-index). XLA lowers ``jnp.argmax``'s variadic reduce poorly
        on TPU — measured 1.4ms/step over [32, 51200] f32 (50x the
        bandwidth roofline) vs ~0.1ms for this form (decode ablation
        r5, engine_noargmax knockout)."""
        m = jnp.max(logits, axis=-1, keepdims=True)
        idx = jnp.arange(logits.shape[-1], dtype=jnp.int32)
        cand = jnp.where(logits == m, idx[None, :],
                         jnp.int32(logits.shape[-1]))
        picked = jnp.min(cand, axis=-1).astype(jnp.int32)
        # all-NaN row: NaN != NaN leaves no candidate — return 0 like
        # jnp.argmax rather than an out-of-range id the embedding would
        # silently clamp
        return jnp.where(picked >= logits.shape[-1], 0, picked)

    @staticmethod
    def _pick_token(logits, key, sample_cfg):
        """Greedy argmax, or temperature/top-k/top-p sampling (the
        reference's top_p_sampling serving op, ops.yaml).

        sample_cfg is (temperature, top_k, top_p) with temperature and
        top_p as TRACED scalars — per-request values don't recompile the
        decode program; only top_k (a shape-determining slice) and the
        sampling on/off switch are static."""
        if sample_cfg is None:
            return GenerationEngine._argmax(logits)
        temperature, top_k, top_p = sample_cfg
        logits = logits / jnp.maximum(jnp.asarray(temperature,
                                                  logits.dtype), 1e-6)
        neg = jnp.asarray(-1e30, logits.dtype)
        if top_k and top_k > 0 and top_k < logits.shape[-1]:
            kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
            logits = jnp.where(logits < kth, neg, logits)
        # top_p traced: the mask arithmetic below is a no-op at
        # top_p >= 1.0, so one compiled program serves every value
        sorted_l = jnp.flip(jnp.sort(logits, axis=-1), -1)
        probs = jax.nn.softmax(sorted_l, -1)
        cum = jnp.cumsum(probs, -1)
        keep_sorted = (cum - probs) < jnp.asarray(top_p, probs.dtype)
        thresh = jnp.min(jnp.where(keep_sorted, sorted_l, jnp.inf),
                         -1, keepdims=True)
        logits = jnp.where(logits >= thresh, logits, neg)
        return jax.random.categorical(key, logits, axis=-1) \
            .astype(jnp.int32)

    def _decode_k_fn(self, weights, embed, head_t, lnf_s, lnf_b, tok,
                     seq_lens, cache_k, cache_v, tables, key=None,
                     sample_params=None, adapter_slots=None,
                     adapter_banks=None, *, k, sample_cfg=None):
        """K decode steps as ONE XLA program: the picked token feeds back
        into the next step inside lax.scan, so the host syncs once per
        chunk instead of once per token (the per-token dispatch
        round-trip is what bounds serving latency on a remote/tunneled
        chip). Greedy by default; sample_cfg=(static top_k,) +
        sample_params=(temperature, top_p) traced arrays switch to
        ancestral sampling with a per-step folded key."""
        st = self.model.stack
        if key is None:
            key = jax.random.PRNGKey(0)
        cfg = None
        if sample_cfg is not None:
            (top_k,) = sample_cfg
            temperature, top_p = sample_params
            cfg = (temperature, top_k, top_p)
        adapters = None
        if adapter_banks is not None:
            # multi-LoRA delta operands (ISSUE 18): the per-row bank
            # slot map plus the [L, S, ...] A/B banks, all traced —
            # the stack sorts rows by slot and issues ONE ragged
            # grouped delta launch per target projection per step
            adapters = dict(adapter_banks)
            adapters["slots"] = adapter_slots

        def step(carry, i):
            tok, lens, ck, cv = carry
            x = embed[tok].astype(self._cdtype)
            h, cache = st.decode_raw(
                weights, x, PagedKV(ck, cv), tables, lens,
                self._cos, self._sin, a8w8=self._a8w8, tp=self._tp,
                adapters=adapters)
            logits = self._logits(h, head_t, lnf_s, lnf_b)
            nxt = self._pick_token(logits, jax.random.fold_in(key, i),
                                   cfg)
            return (nxt, lens + 1, cache.k, cache.v), nxt

        (tok, seq_lens, ck, cv), toks = jax.lax.scan(
            step, (tok, seq_lens, cache_k, cache_v), jnp.arange(k))
        return jnp.swapaxes(toks, 0, 1), ck, cv  # [b, k]

    # ---------- serving API ----------

    @staticmethod
    def _pad_prompts(input_ids, seq_lens=None):
        """Normalize prompts to (padded [b, s] int array, lens [b]).
        Accepts a rectangular array (all rows real unless seq_lens
        given) or a ragged list of 1-D sequences (right-padded here)."""
        if isinstance(input_ids, Tensor):
            input_ids = np.asarray(input_ids._data)
        if isinstance(input_ids, (list, tuple)) and not np.isscalar(
                input_ids[0]):
            rows = [np.asarray(r).reshape(-1) for r in input_ids]
            lens = np.array([len(r) for r in rows], np.int32)
            s = int(lens.max())
            ids = np.zeros((len(rows), s), rows[0].dtype)
            for i, r in enumerate(rows):
                ids[i, : len(r)] = r
            return ids, lens
        ids = np.asarray(input_ids)
        if seq_lens is None:
            lens = np.full((ids.shape[0],), ids.shape[1], np.int32)
        else:
            lens = np.asarray(seq_lens, np.int32)
        return ids, lens

    def _grow_tables(self, seq_ids, lens, extra, pages_per_seq):
        """On-demand paging: extend each sequence's pages to cover
        ``lens + extra`` tokens; returns the (constant-shape) table."""
        for i, sid in enumerate(seq_ids):
            need = min(self._mgr.pages_needed(int(lens[i]) + extra),
                       pages_per_seq)
            have = len(self._mgr._owned.get(sid, ()))
            if need > have:
                self._mgr.grow(sid, need - have)
        return self._mgr.block_tables(seq_ids, pages_per_seq)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None, seq_lens=None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0):
        """Greedy decode with per-sequence prompt lengths.

        input_ids: [b, s] array (optionally with ``seq_lens`` marking
        real lengths) or a ragged list of 1-D prompts. Returns
        np.ndarray [b, max(s_i) + max_new_tokens]; row i holds its
        prompt then its generated tokens at columns
        lens[i]..lens[i]+max_new_tokens-1 (tail beyond that is pad/EOS)."""
        ids, lens = self._pad_prompts(input_ids, seq_lens)
        b, s = ids.shape
        if max_new_tokens <= 0:
            return ids.copy()
        st = self.model.stack
        if int(lens.max()) + max_new_tokens > self.max_length:
            raise ValueError(
                f"prompt ({int(lens.max())}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_length "
                f"({self.max_length}); raise max_length (positions past "
                "the page table would silently clamp)")
        # block-table WIDTH always covers max_length (constant shapes →
        # no recompiles), but pages are allocated on demand as sequences
        # grow — short generations leave the pool free for others
        pages_per_seq = -(-self.max_length // self.page_size)
        # +1 for the reserved scratch page 0, whether the pool size is
        # defaulted or caller-specified (a caller's num_pages means
        # usable capacity); rounded up so the stream-attention kernel
        # gets whole chunks (see _round_pool_pages)
        requested = (self._num_pages or b * pages_per_seq) + 1
        self._mgr = BlockKVCacheManager(
            st.num_layers, st.num_kv_heads, st.head_dim, self.page_size,
            num_pages=_round_pool_pages(requested, self.page_size),
            dtype=self._kv_dtype, reserve_scratch=True,
            mp_degree=self._tp.mp if self._tp else 1,
            mesh=self._tp.mesh if self._tp else None)
        _stats.set_gauge("inference.pool_pages_requested", requested)
        _stats.set_gauge("inference.pool_pages", self._mgr.num_pages)
        for i in range(b):
            self._mgr.allocate(i, int(lens[i]))
        tables = self._mgr.block_tables(range(b), pages_per_seq)
        cache = self._mgr.fresh_cache()

        weights = self._weights()
        embed = self._embed()
        lnf_s, lnf_b = self._lnf()

        _stats.inc("inference.prefills")
        self._count_a8w8(1)
        logits, ck, cv = self._prefill(
            weights, embed, self._head_t, lnf_s, lnf_b, jnp.asarray(ids),
            jnp.asarray(lens), cache.k, cache.v, tables)

        from ..core.generator import next_rng_key

        # static part: (top_k,) — temperature/top_p stay traced; greedy
        # decoding must not consume the global RNG stream at all
        static_cfg = (int(top_k),) if do_sample else None
        params = (jnp.asarray(float(temperature), jnp.float32),
                  jnp.asarray(float(top_p), jnp.float32)) \
            if do_sample else None
        pick_cfg = (params[0], int(top_k), params[1]) if do_sample \
            else None

        width = s + max_new_tokens
        out = np.zeros((b, width), ids.dtype)
        out[:, :s] = ids
        finished = np.zeros((b,), bool)

        # first generated token: prefill logits at each row's own last
        # real position
        tok_np = np.asarray(self._pick_token(
            logits, next_rng_key() if do_sample else None,
            pick_cfg)).astype(ids.dtype)
        if eos_token_id is not None:
            finished |= tok_np == eos_token_id
        out[np.arange(b), lens] = tok_np
        emitted = 1

        # remaining tokens in scan-chunks: one device program + ONE host
        # sync per chunk instead of per token (tunnel-latency bound)
        while emitted < max_new_tokens and not (
                eos_token_id is not None and finished.all()):
            k = min(self.decode_chunk, max_new_tokens - emitted)
            # feed each row's last generated token at its own position
            cur = lens + emitted - 1         # per-seq position just fed
            tables = self._grow_tables(range(b), lens + emitted, k,
                                       pages_per_seq)
            _stats.inc("inference.decode_steps", k)
            self._count_a8w8(k)
            _stats.set_gauge("inference.kv_pages_in_use",
                             self._mgr.num_pages - self._mgr.free_pages)
            if self._tp is not None:
                # re-stamped per chunk: benches reset the registry
                # after warmup, and the TP degree must survive into
                # the measured telemetry block
                _stats.set_gauge("dist.mp_degree", self._tp.mp)
            import time as _time

            t0 = _time.perf_counter()
            toks, ck, cv = self._get_decode_k(k, static_cfg)(
                weights, embed, self._head_t, lnf_s, lnf_b,
                jnp.asarray(out[np.arange(b), cur].astype(np.int32)),
                jnp.asarray(cur, dtype=jnp.int32), ck, cv, tables,
                next_rng_key() if do_sample else None, params)
            toks_np = np.asarray(toks)
            # honest wall time: the np.asarray fetch synced the chunk,
            # so this roofline reflects executed work, not dispatch
            _roofline.analyze(self._decode_rung(k),
                              _time.perf_counter() - t0)
            for j in range(k):
                col = toks_np[:, j].astype(ids.dtype)
                if eos_token_id is not None:
                    col = np.where(finished, eos_token_id, col)
                    finished |= col == eos_token_id
                out[np.arange(b), lens + emitted] = col
                emitted += 1
        if eos_token_id is not None:
            for i in range(b):
                if finished[i]:
                    e = int(lens[i]) + emitted
                    out[i, e:] = eos_token_id
        for i in range(b):
            self._mgr.free(i)
        return out


class GenRequest:
    """One serving request (continuous batching unit)."""

    # id allocation must be thread-safe: the serving frontend
    # (paddle_tpu/serving) submits from arbitrary threads. next() on a
    # shared itertools.count is atomic under CPython (single bytecode
    # dispatch into C) — no lock, no duplicate ids.
    _next_id = itertools.count()

    def __init__(self, prompt, max_new_tokens=32, eos_token_id=None):
        self.id = next(GenRequest._next_id)
        self.prompt = np.asarray(prompt).reshape(-1).astype(np.int32)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.generated: list = []
        self.done = False
        # times the admission loop passed this request over for a later
        # one that fit (skip-ahead head-of-line fix; bounded by the
        # engine's starvation_bound)
        self._admit_skips = 0

    @property
    def output(self):
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])


class ContinuousBatchingEngine:
    """Continuous-batching serving loop over a FusedCausalLM.

    TPU-native counterpart of the reference's serving frontend around
    block_multi_head_attention (reference:
    paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu —
    per-request seq_lens + block tables): a fixed pool of ``max_batch``
    decode slots shares one paged KV pool; finished sequences free their
    pages and waiting requests are admitted mid-stream (their prompt is
    prefilled into the shared cache), so decode shapes stay constant and
    nothing recompiles as traffic churns.

    ``speculative=`` (ISSUE 12, inference/speculative.py): replace the
    decode chunk with draft+verify rounds — k drafted tokens verified
    in ONE streamed pass, amortizing the per-token weight stream by
    the accept length, with greedy parity guaranteed whatever the
    drafter proposes.

    Usage::

        eng = ContinuousBatchingEngine(model, max_batch=4)
        eng.submit([1, 2, 3], max_new_tokens=16)
        finished = eng.run()          # or step() repeatedly
    """

    def __init__(self, model: FusedCausalLM, max_batch: int = 4,
                 page_size: int = 16, max_length: int = 1024,
                 num_pages: Optional[int] = None,
                 decode_chunk: Optional[int] = None,
                 prompt_bucket: int = 16, kv_dtype=None,
                 quant: Optional[str] = None, admit_window: int = 8,
                 starvation_bound: int = 16, mesh=None,
                 mp_degree: Optional[int] = None,
                 ep_degree: Optional[int] = None, speculative=None,
                 spec_k: Optional[int] = None):
        self.model = model
        self.max_batch = int(max_batch)
        self.max_length = int(max_length)
        self.page_size = int(page_size)
        self.decode_chunk = _resolve_decode_chunk(decode_chunk)
        self.prompt_bucket = max(int(prompt_bucket), 1)
        # admission skip-ahead: when the queue head's pages don't fit,
        # up to admit_window later requests are tried instead of
        # head-of-line blocking; a head skipped starvation_bound times
        # pins the queue until it fits (bounded unfairness)
        self.admit_window = max(int(admit_window), 1)
        self.starvation_bound = max(int(starvation_bound), 1)
        self._gen = GenerationEngine.__new__(GenerationEngine)  # share
        self._gen.model = model
        self._gen.max_length = self.max_length
        self._gen.page_size = self.page_size
        self._gen.decode_chunk = self.decode_chunk
        self._gen._init_serving_state(kv_dtype, quant, mesh=mesh,
                                      mp_degree=mp_degree,
                                      ep_degree=ep_degree)
        st = model.stack
        self._pages_per_seq = -(-self.max_length // self.page_size)
        requested = (num_pages or self.max_batch * self._pages_per_seq) + 1
        tp = self._gen._tp
        self._mgr = BlockKVCacheManager(
            st.num_layers, st.num_kv_heads, st.head_dim, self.page_size,
            num_pages=_round_pool_pages(requested, self.page_size),
            dtype=self._gen._kv_dtype, reserve_scratch=True,
            mp_degree=tp.mp if tp else 1,
            mesh=tp.mesh if tp else None)
        _stats.set_gauge("serving.pool_pages_requested", requested)
        _stats.set_gauge("serving.pool_pages", self._mgr.num_pages)
        cache = self._mgr.fresh_cache()
        self._ck, self._cv = cache.k, cache.v
        self._cos, self._sin = rope_table(st.max_position, st.head_dim,
                                          st.rope_theta)
        self._gen._cos, self._gen._sin = self._cos, self._sin
        self._gen._mgr = self._mgr

        self.waiting: list = []
        self.finished: list = []
        # serving flight-recorder hook (serving/journal.py): the
        # serving frontend installs its ring journal here so engine-
        # level finish events land on the same per-request timeline;
        # None (the base engine) keeps every hook a no-op
        self._journal = None
        # fault-injection registry (serving/faults.py): the serving
        # frontend installs its injector here so the ``decode.step``
        # site fires once per decode chunk; None = one attribute test
        self._faults = None
        # usage ledger hook (serving/accounting.py): the serving
        # frontend installs its UsageLedger here so engine-level
        # token accounting (wasted chunk tails, spec accepts) charges
        # the owning request; None = one attribute test
        self._usage = None
        # slot state
        self._slots: list = [None] * self.max_batch   # GenRequest or None
        self._lens = np.zeros((self.max_batch,), np.int64)
        self._last_tok = np.zeros((self.max_batch,), np.int64)
        # speculative decoding (inference/speculative.py): when set,
        # step() runs one draft+verify round in place of the decode
        # chunk — the weight stack streams once per ACCEPTED WINDOW
        # instead of once per token. ``speculative`` accepts True
        # (FLAGS_spec_drafter), "self" (Medusa-style self-drafting
        # heads), a Drafter instance, or a small FusedCausalLM draft
        # model; ``spec_k`` defaults to FLAGS_spec_k.
        self._spec = None
        if speculative:
            from .speculative import build_speculative_decoder

            self._spec = build_speculative_decoder(
                self, speculative, spec_k)

    # ---------------- public API ----------------

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None) -> int:
        req = GenRequest(prompt, max_new_tokens, eos_token_id)
        if len(req.prompt) + req.max_new_tokens > self.max_length:
            raise ValueError("request exceeds engine max_length")
        self.waiting.append(req)
        return req.id

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self._slots)

    def step(self):
        """Admit waiting requests into free slots, then run ONE decode
        chunk — or, with ``speculative=`` set, one draft+verify round —
        for the active batch. Returns requests finished this step."""
        self._admit()
        if self.num_active == 0:
            return []
        if self._spec is not None:
            return self._spec_step()
        k = self.decode_chunk
        active = [i for i, r in enumerate(self._slots) if r is not None]
        fi = self._faults
        if fi is not None and active:
            # the decode.step fault site fires BEFORE the grow loop so
            # scheduled pool squeezes exhaust the free list the grows
            # are about to hit — the REAL recovery paths (eviction,
            # preemption-by-recompute) engage on genuine pool state
            fi.fire("decode.step")
        # pages grow on demand, clamped to what the request can still
        # emit — a near-max_length prompt must not over-allocate past
        # the fixed block-table width
        for i in active:
            req = self._slots[i]
            if req is None:
                continue  # preempted by an earlier slot's grow
            remaining = req.max_new_tokens - len(req.generated)
            need = self._mgr.pages_needed(
                int(self._lens[i]) + min(k, max(remaining, 0)))
            need = min(need, self._pages_per_seq)
            have = len(self._mgr._owned.get(("slot", i), ()))
            if need > have and \
                    not self._grow_decode_slot(i, need - have):
                continue  # slot preempted (serving override)
        active = [i for i in active if self._slots[i] is not None]
        if not active:
            return []
        tables = self._mgr.block_tables(
            [("slot", i) for i in range(self.max_batch)],
            self._pages_per_seq, allow_missing=True)
        _stats.inc("serving.decode_steps", k)
        self._gen._count_a8w8(k)
        _stats.set_gauge("serving.kv_pages_in_use",
                         self._mgr.num_pages - self._mgr.free_pages)
        _stats.set_gauge("serving.active_slots", len(active))
        if self._gen._tp is not None:
            # survives post-warmup stats.reset() in the benches
            _stats.set_gauge("dist.mp_degree", self._gen._tp.mp)

        cur = np.where([r is not None for r in self._slots],
                       self._lens - 1, 0).astype(np.int64)
        import time as _time

        lnf_s, lnf_b = self._gen._lnf()
        a_slots, a_banks = self._adapter_operands(active)
        adaptered = a_banks is not None
        extra = (None, None, a_slots, a_banks) if adaptered else ()
        if adaptered:
            # one ragged grouped delta launch per target projection
            # per executed decode step (4 projections x L layers x k)
            _stats.inc("lora.grouped_launches",
                       4 * self.model.stack.num_layers * k)
        t0 = _time.perf_counter()
        toks, self._ck, self._cv = self._gen._get_decode_k(
            k, adaptered=adaptered)(
            self._gen._weights(), self._gen._embed(),
            self._gen._head_t, lnf_s, lnf_b,
            jnp.asarray(self._last_tok, jnp.int32),
            jnp.asarray(cur, jnp.int32),
            self._ck, self._cv, tables, *extra)
        toks_np = np.asarray(toks)
        # synced by the fetch above — an honest per-chunk roofline
        _roofline.analyze(self._gen._decode_rung(k, adaptered),
                          _time.perf_counter() - t0)
        # overridable token filter: runs BEFORE any request mutates,
        # so a validation raise (serving corruption detection) leaves
        # every slot exactly as it was and a chunk re-run is clean
        toks_np = self._postprocess_tokens(toks_np, active)

        done_now = []
        for i in active:
            req = self._slots[i]
            cb = getattr(req, "on_token", None)
            consumed = 0
            for j in range(k):
                if req.done:
                    break
                t = int(toks_np[i, j])
                req.generated.append(t)
                consumed += 1
                if cb is not None:
                    cb(req, t)
                if (req.eos_token_id is not None
                        and t == req.eos_token_id) or \
                        len(req.generated) >= req.max_new_tokens:
                    req.done = True
            if req.done:
                # tokens the chunk decoded PAST req.done are executed-
                # but-discarded device work: the decode_chunk tuning
                # signal (big chunks amortize dispatch, small chunks
                # waste less tail work on eos/max_new finishes)
                _stats.inc("serving.wasted_decode_tokens", k - consumed)
                u = self._usage
                if u is not None and k > consumed:
                    # the tail belongs to the FINISHER — the request
                    # whose eos/max_new ended the chunk early
                    u.add_tokens(req, wasted=k - consumed)
                self._finish_hook(req, i)
                self._release(i)
                done_now.append(req)
            else:
                self._lens[i] += k
                self._last_tok[i] = int(toks_np[i, k - 1])
        self.finished.extend(done_now)
        return done_now

    def _spec_step(self):
        """One SPECULATIVE round in place of the decode chunk: the
        drafter proposes k tokens per active slot, ONE streamed verify
        pass (``prefill_chunk_raw`` over the paged pool) scores every
        window, and the fused accept-prefix emits the accepted drafts
        plus the bonus token — greedy-parity by construction, and a
        rejection costs only a page-table truncation
        (inference/speculative.py)."""
        k = self._spec.k
        active = [i for i, r in enumerate(self._slots) if r is not None]
        fi = self._faults
        if fi is not None and active:
            # same decode.step fault site as the chunk path, fired
            # BEFORE the grows so pool squeezes hit the real recovery
            fi.fire("decode.step")
        # per-slot window, clamped so the verify never writes past what
        # the request can still emit (which also bounds it to the page
        # table: cached + remaining <= max_length by the submit check)
        win = np.zeros((self.max_batch,), np.int64)
        for i in active:
            req = self._slots[i]
            if req is None:
                continue  # preempted by an earlier slot's grow
            remaining = req.max_new_tokens - len(req.generated)
            w = max(1, min(k + 1, remaining,
                           self.max_length - (int(self._lens[i]) - 1)))
            win[i] = w
            need = min(self._mgr.pages_needed(
                int(self._lens[i]) - 1 + w), self._pages_per_seq)
            have = len(self._mgr._owned.get(("slot", i), ()))
            if need > have and \
                    not self._grow_decode_slot(i, need - have):
                continue  # slot preempted (serving override)
        active = [i for i in active if self._slots[i] is not None]
        if not active:
            return []
        return self._spec.run_round(self, active, win)

    def run(self):
        """Drain: step until every submitted request finishes."""
        while self.waiting or self.num_active:
            self.step()
        return self.finished

    # ------------- slot migration (fleet drain, ISSUE 14) -------------

    @staticmethod
    def _pad_pow2(a: np.ndarray, axis: int = 0) -> np.ndarray:
        """Pad ``a`` along ``axis`` to the next power-of-two length
        (min 8) by repeating its last entry, so the KV gather/scatter
        row shapes BUCKET instead of recompiling per page count — a
        per-count XLA compile in the serving hot path wedges a
        replica's stepping thread long enough to trip the fleet
        health checker into hedging its queue away. Duplicate scatter
        indices carry the duplicated (identical) values, so the
        padded writes are no-ops; padded gather rows are sliced off
        by the caller."""
        n = a.shape[axis]
        b = max(8, 1 << max(0, (n - 1).bit_length()))
        if n == 0 or b == n:
            return a
        idx = [slice(None)] * a.ndim
        idx[axis] = slice(n - 1, n)
        pad = np.repeat(a[tuple(idx)], b - n, axis=axis)
        return np.concatenate([a, pad], axis=axis)

    def can_migrate(self) -> bool:
        """Page-granular KV export/import is supported for plain
        (unsharded, non-int8) pools; int8 cache-KV carries scale
        planes and TP pools shard by kv-head — both fall back to the
        preemption-by-recompute path on a fleet drain."""
        return not isinstance(self._ck, tuple) \
            and self._mgr._mesh is None

    def export_slot(self, i: int) -> dict:
        """Export decode slot ``i``'s live state for page-granular
        migration to a peer engine: the request, its sequence
        position, and the slot's KV pages gathered out of the pool
        (one contiguous blob per K/V, layer-major — see
        ``BlockKVCacheManager.phys_rows``). Pages are NOT freed here;
        the caller releases the slot only after the import lands, so
        a failed migration leaves this engine untouched."""
        if not self.can_migrate():
            raise NotImplementedError(
                "KV-page migration needs a plain pool (no int8 "
                "cache-KV, no TP kv-head sharding) — use the "
                "recompute resume path instead")
        req = self._slots[i]
        if req is None:
            raise KeyError(f"slot {i} is not decoding")
        pages = list(self._mgr._owned[("slot", i)])
        rows_np = self._mgr.phys_rows(pages)
        nr = len(rows_np)
        rows = jnp.asarray(self._pad_pow2(rows_np))
        return {"req": req, "len": int(self._lens[i]),
                "last_tok": int(self._last_tok[i]),
                "n_pages": len(pages),
                "k": np.asarray(gather_rows(self._ck, rows))[:nr],
                "v": np.asarray(gather_rows(self._cv, rows))[:nr]}

    def import_slot(self, i: int, blob: dict) -> bool:
        """Adopt an exported decode slot into free slot ``i``: allocate
        exactly ``n_pages`` fresh pages, scatter the K/V blob into
        them, and re-home the request mid-decode — its next token
        comes out byte-identical because the cached KV (and the
        replicated weights) are byte-identical. False when the slot is
        occupied or the pool can't cover the pages (the caller falls
        back to recompute)."""
        if not self.can_migrate():
            raise NotImplementedError(
                "KV-page migration needs a plain pool (no int8 "
                "cache-KV, no TP kv-head sharding)")
        n = int(blob["n_pages"])
        if not self._slot_free(i) or n > self._mgr.free_pages \
                or n > self._pages_per_seq:
            return False

        pages = self._mgr.allocate(("slot", i), n * self.page_size)
        rows = jnp.asarray(self._pad_pow2(self._mgr.phys_rows(pages)))
        self._ck = restore_scatter_jit(
            self._ck, rows, jnp.asarray(self._pad_pow2(blob["k"])))
        self._cv = restore_scatter_jit(
            self._cv, rows, jnp.asarray(self._pad_pow2(blob["v"])))
        self._slots[i] = blob["req"]
        self._lens[i] = int(blob["len"])
        self._last_tok[i] = int(blob["last_tok"])
        return True

    # ------- async page streaming (decode-concurrent migration) -------
    #
    # Decode appends only: a page whose positions all sit below the
    # slot's current length never mutates again, so COMPLETE pages can
    # stream to the destination in batches with NO lock on the source
    # (reads snapshot the functional pool arrays) and only a short
    # per-batch critical section on the destination (the scatter swaps
    # its pool arrays). The join copies the mutable tail + metadata
    # under both step locks — byte-identical tokens preserved because
    # every streamed page is byte-identical by construction.

    def safe_page_count(self, i: int) -> int:
        """Pages of slot ``i`` that are complete (every position below
        the current length) and therefore immutable under further
        decode steps — the lock-free streamable prefix."""
        return min(int(self._lens[i]) // self.page_size,
                   len(self._mgr._owned.get(("slot", i), ())))

    def export_pages(self, i: int, lo: int, hi: int) -> dict:
        """Gather logical pages ``[lo, hi)`` of decoding slot ``i`` to
        host memory. Lock-free for complete pages: the pool arrays are
        functional (decode steps REPLACE them), so a snapshot reference
        carries byte-identical rows for any already-complete page."""
        if not self.can_migrate():
            raise NotImplementedError(
                "KV-page migration needs a plain pool (no int8 "
                "cache-KV, no TP kv-head sharding)")
        pages = list(self._mgr._owned[("slot", i)])[lo:hi]
        ck, cv = self._ck, self._cv
        rows_np = self._mgr.phys_rows(pages)
        nr = len(rows_np)
        rows = jnp.asarray(self._pad_pow2(rows_np))
        return {"lo": lo, "hi": hi,
                "k": np.asarray(gather_rows(ck, rows))[:nr],
                "v": np.asarray(gather_rows(cv, rows))[:nr]}

    def import_begin(self, n_pages: int):
        """Reserve ``n_pages`` for an in-flight migration WITHOUT
        claiming a decode slot (admission keeps running; the slot is
        picked at ``import_finish``). Returns an opaque ticket, or
        None when the pool can't cover the reservation. Call under
        this engine's step lock."""
        if not self.can_migrate():
            raise NotImplementedError(
                "KV-page migration needs a plain pool (no int8 "
                "cache-KV, no TP kv-head sharding)")
        if n_pages > self._mgr.free_pages or n_pages > self._pages_per_seq:
            return None
        self._mig_seq = getattr(self, "_mig_seq", 0) + 1
        key = ("migrate", self._mig_seq)
        self._mgr.allocate(key, n_pages * self.page_size)
        return {"key": key, "n_pages": n_pages}

    def import_pages(self, ticket, batch: dict):
        """Scatter one streamed page batch (an ``export_pages`` blob)
        into the ticket's reserved pages. Call under this engine's
        step lock — the scatter swaps the pool arrays and must not
        race a decode step's own swap."""

        pages = list(self._mgr._owned[ticket["key"]])
        rows = jnp.asarray(self._pad_pow2(self._mgr.phys_rows(
            pages[batch["lo"]:batch["hi"]])))
        self._ck = restore_scatter_jit(
            self._ck, rows, jnp.asarray(self._pad_pow2(batch["k"])))
        self._cv = restore_scatter_jit(
            self._cv, rows, jnp.asarray(self._pad_pow2(batch["v"])))

    def export_slot_tail(self, i: int, lo: int) -> dict:
        """The source's closing export for an async migration: slot
        metadata plus ONLY the pages from ``lo`` on (the mutable tail
        the background stream could not safely copy). Call under the
        source's step lock so ``len``/``last_tok`` and the tail bytes
        are one consistent snapshot."""
        req = self._slots[i]
        if req is None:
            raise KeyError(f"slot {i} is not decoding")
        n = len(self._mgr._owned[("slot", i)])
        tail = self.export_pages(i, lo, n) if lo < n else None
        return {"req": req, "len": int(self._lens[i]),
                "last_tok": int(self._last_tok[i]),
                "n_pages": n, "tail": tail}

    def import_finish(self, ticket, i: int, blob: dict) -> bool:
        """Join: adopt the reserved pages as free slot ``i`` and
        re-home the request with its final metadata (``blob`` from
        ``export_slot_tail`` — page range covers only the
        not-yet-streamed tail). The reservation grows to cover pages
        allocated on the source AFTER it was taken (decode kept
        running there). False when the slot was taken or the pool
        can't cover the growth — the caller aborts and falls back."""
        n = int(blob["n_pages"])
        if not self._slot_free(i):
            return False
        have = len(self._mgr._owned[ticket["key"]])
        if n > have and (n - have) > self._mgr.free_pages:
            return False
        if n > have:
            self._mgr.grow(ticket["key"], n - have)
        self._mgr.rekey(ticket["key"], ("slot", i))
        if blob.get("tail") is not None:
            self.import_pages({"key": ("slot", i)}, blob["tail"])
        self._slots[i] = blob["req"]
        self._lens[i] = int(blob["len"])
        self._last_tok[i] = int(blob["last_tok"])
        return True

    def import_abort(self, ticket):
        """Release an unfinished migration reservation."""
        self._mgr.free(ticket["key"])

    # -------- host-tier page spill/restore (tiered KV, ISSUE 20) --------
    #
    # Unlike slot migration, spill/restore moves IMMUTABLE pages only
    # (full prefix-cache pages, a preempted slot's complete pages), so
    # the int8 cache-KV mode is supported: a page's quantized rows spill
    # together with their f32 scale-plane columns and the pair restores
    # byte-identically — spilled traffic roughly halves vs bf16.

    def can_spill(self) -> bool:
        """Host-DRAM spill/restore supports plain AND int8 pools; only
        TP kv-head-sharded pools fall back (a one-shard blob could not
        restore into a differently-sharded peer pool)."""
        return self._mgr._mesh is None

    def _scale_cols(self, rows_np: np.ndarray) -> np.ndarray:
        """Scale-plane columns of the given pool rows: row r position t
        lives at plane column r * page_size + t (kv_cache.fresh_cache
        lane-major layout)."""
        ps = self.page_size
        return (rows_np[:, None] * ps
                + np.arange(ps, dtype=np.int64)[None, :]).reshape(-1)

    def export_kv_pages(self, pages) -> dict:
        """Gather arbitrary (immutable) pool pages to host memory —
        layer-major page-inner layout per ``phys_rows``, so the blob
        scatters back via ``import_kv_pages`` on any engine with the
        same geometry. int8 pools add the per-token scale columns."""
        if not self.can_spill():
            raise NotImplementedError(
                "host-tier KV spill needs an unsharded pool — TP "
                "kv-head shards fall back to evict/recompute")
        rows_np = self._mgr.phys_rows(list(pages))
        nr = len(rows_np)
        rows_pad = self._pad_pow2(rows_np)
        rows = jnp.asarray(rows_pad)
        if isinstance(self._ck, tuple):
            nc = nr * self.page_size
            cols = jnp.asarray(self._scale_cols(rows_pad))
            return {"n_pages": len(pages), "int8": True,
                    "k": np.asarray(self._ck[0][rows])[:nr],
                    "v": np.asarray(self._cv[0][rows])[:nr],
                    "k_scale": np.asarray(self._ck[1][:, cols])[:, :nc],
                    "v_scale": np.asarray(self._cv[1][:, cols])[:, :nc]}
        return {"n_pages": len(pages), "int8": False,
                "k": np.asarray(gather_rows(self._ck, rows))[:nr],
                "v": np.asarray(gather_rows(self._cv, rows))[:nr]}

    def import_kv_pages(self, pages, blob: dict) -> None:
        """Scatter a spilled host blob into freshly allocated pool
        pages (the restore half — ``kv_cache.restore_scatter``, the
        donated ``serve.kv_restore`` program). Swaps the functional
        pool arrays; call from the step thread / under the step lock."""

        rows_np = self._mgr.phys_rows(list(pages))
        nr = len(rows_np)
        rows_pad = self._pad_pow2(rows_np)
        rows = jnp.asarray(rows_pad)
        if blob.get("int8"):
            ps = self.page_size
            reps = len(rows_pad) - nr

            def _pad_sc(x):
                # the duplicated last row's scale columns, tiled to
                # match the padded cols (identical duplicate writes)
                x = np.asarray(x)
                if reps:
                    x = np.concatenate(
                        [x, np.tile(x[:, -ps:], (1, reps))], axis=1)
                return x

            cols = jnp.asarray(self._scale_cols(rows_pad))
            ck, cks = self._ck
            cv, cvs = self._cv
            self._ck = (restore_scatter_jit(
                            ck, rows,
                            jnp.asarray(self._pad_pow2(blob["k"]))),
                        cks.at[:, cols].set(jnp.asarray(
                            _pad_sc(blob["k_scale"]), cks.dtype)))
            self._cv = (restore_scatter_jit(
                            cv, rows,
                            jnp.asarray(self._pad_pow2(blob["v"]))),
                        cvs.at[:, cols].set(jnp.asarray(
                            _pad_sc(blob["v_scale"]), cvs.dtype)))
        else:
            self._ck = restore_scatter_jit(
                self._ck, rows, jnp.asarray(self._pad_pow2(blob["k"])))
            self._cv = restore_scatter_jit(
                self._cv, rows, jnp.asarray(self._pad_pow2(blob["v"])))

    # ---------------- internals ----------------

    def _release(self, i: int):
        self._mgr.free(("slot", i))
        self._slots[i] = None
        self._lens[i] = 0
        self._last_tok[i] = 0
        if self._spec is not None:
            # slot reuse: the next occupant's drafter state re-drafts
            # from its own recorded history (resume semantics)
            self._spec.reset_slot(i)

    def _postprocess_tokens(self, toks_np, active):
        """Hook over the decode chunk's fetched token matrix, called
        before the per-slot append loop. Base engine: identity. The
        serving frontend overrides it with fault-injection corruption
        + token-range validation (serving/scheduler.py)."""
        return toks_np

    def _adapter_operands(self, active):
        """Multi-LoRA decode operands hook: ``(slot_map, banks)``
        when any active slot decodes through a LoRA adapter, else
        ``(None, None)`` — the base engine has no adapter bank; the
        serving frontend overrides this against its AdapterBank
        (serving/scheduler.py)."""
        return None, None

    def _finish_hook(self, req, slot: int):
        """Called once per finished request, BEFORE its pages release.
        Base engine: journal a finish event when a flight recorder is
        installed. The serving frontend overrides this with SLO
        verdicts + lifecycle stamps (serving/scheduler.py)."""
        j = self._journal
        if j is not None:
            j.record("finish", req.id, slot,
                     {"n_tokens": len(req.generated)})

    def _grow_decode_slot(self, i: int, n_pages: int) -> bool:
        """Extend slot ``i``'s pages before a decode chunk; False means
        the slot was vacated instead of grown. The base engine's pool
        is sized for max_batch full-length sequences, so exhaustion
        here is a configuration error and raises; the serving frontend
        overrides this with prefix-cache eviction and, as a last
        resort, preemption-by-recompute."""
        self._mgr.grow(("slot", i), n_pages)
        return True

    def _slot_free(self, i: int) -> bool:
        """Is slot i available for admission? (The serving scheduler
        also parks chunk-prefilling requests on slots.)"""
        return self._slots[i] is None

    def _can_admit(self, req) -> bool:
        """Do the pool's free pages cover this request's prompt (+1
        decode token)? Overridden by the serving frontend to account
        for prefix-cache hits and to evict cold cached prefixes."""
        return self._mgr.pages_needed(len(req.prompt) + 1) \
            <= self._mgr.free_pages

    def _pick_waiting(self):
        """Next admissible waiting request, with BOUNDED SKIP-AHEAD:
        when the head's pages don't fit, up to ``admit_window`` later
        requests are tried (small requests flow past a parked big one
        instead of head-of-line blocking behind it). Each pass-over
        bumps the skipped requests' ``_admit_skips`` and the
        ``serving.admission_skips`` counter; once the head has been
        skipped ``starvation_bound`` times the window collapses to the
        head alone, so it admits next no matter what fits behind it."""
        if not self.waiting:
            return None
        head = self.waiting[0]
        window = 1 if head._admit_skips >= self.starvation_bound \
            else min(len(self.waiting), self.admit_window)
        for j in range(window):
            req = self.waiting[j]
            if self._can_admit(req):
                if j > 0:
                    for skipped in self.waiting[:j]:
                        skipped._admit_skips += 1
                    _stats.inc("serving.admission_skips", j)
                return self.waiting.pop(j)
        return None

    def _admit(self):
        """Move admissible waiting requests into free slots (skip-ahead
        selection via ``_pick_waiting``); prefill each prompt into the
        shared page pool (bucketed lengths bound recompiles)."""
        for i in range(self.max_batch):
            if not self.waiting or not self._slot_free(i):
                continue
            req = self._pick_waiting()
            if req is None:
                break  # nothing in the window fits — retry next step
            self._admit_into(req, i)

    def _admit_into(self, req, i: int):
        """Prefill ``req``'s whole prompt and start it decoding in slot
        ``i``. (The serving frontend overrides this with chunked
        prefill: the prompt fills in fixed-size chunks interleaved with
        decode steps instead of one monolithic program.)"""
        self._slots[i] = req
        _stats.inc("serving.admitted")
        self._gen._count_a8w8(1)
        L = len(req.prompt)
        self._mgr.allocate(("slot", i), L)
        tables = self._mgr.block_tables([("slot", i)],
                                        self._pages_per_seq)
        # bucket the padded prompt length to bound compile count
        bs = self.prompt_bucket
        s_pad = -(-L // bs) * bs
        ids = np.zeros((1, s_pad), np.int32)
        ids[0, :L] = req.prompt
        lnf_s, lnf_b = self._gen._lnf()
        logits, self._ck, self._cv = self._gen._prefill(
            self._gen._weights(), self._gen._embed(),
            self._gen._head_t, lnf_s, lnf_b, jnp.asarray(ids),
            jnp.asarray([L], jnp.int32), self._ck, self._cv, tables)
        t = int(np.asarray(jnp.argmax(logits, axis=-1))[0])
        req.generated.append(t)
        cb = getattr(req, "on_token", None)
        if cb is not None:
            cb(req, t)
        if (req.eos_token_id is not None and t == req.eos_token_id) \
                or req.max_new_tokens <= 1:
            req.done = True
            self._finish_hook(req, i)
            self._release(i)
            self.finished.append(req)
            return
        self._lens[i] = L + 1
        self._last_tok[i] = t
