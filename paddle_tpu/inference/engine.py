"""Generation engine: compiled prefill + paged-KV decode loop.

TPU-native equivalent of the reference's fused-decode serving spine
(reference: paddle/fluid/operators/fused/fused_multi_transformer_op.cu
driving AnalysisPredictor-run programs, with paged KV via
block_multi_head_attention_kernel.cu). Here both phases are single XLA
programs: prefill(x[b,s]) and decode_step(token[b]) are jit-compiled
once per shape with the cache donated, so steady-state decode is one
device program per token with zero host round-trips in the stack.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..incubate.nn.fused_transformer import (
    FusedMultiTransformer, PagedKV, rope_table)
from ..nn.layer_base import Layer
from .kv_cache import BlockKVCacheManager

__all__ = ["FusedCausalLM", "GenerationEngine"]


class FusedCausalLM(Layer):
    """Minimal GPT-style causal LM over FusedMultiTransformer:
    token embedding (tied lm head) + stack + final LN."""

    def __init__(self, vocab_size, embed_dim, num_heads, dim_feedforward,
                 num_layers, num_kv_heads=None, max_position=32768,
                 rope_theta=10000.0):
        super().__init__()
        from ..core.tensor import Parameter

        from ..core.generator import default_generator

        self.vocab_size = vocab_size
        self.embed = Parameter(
            jax.random.normal(default_generator().next_key(),
                              (vocab_size, embed_dim), jnp.float32) * 0.02)
        self.stack = FusedMultiTransformer(
            embed_dim, num_heads, dim_feedforward, num_layers,
            num_kv_heads=num_kv_heads, max_position=max_position,
            rope_theta=rope_theta)
        self.lnf_scale = Parameter(jnp.ones((embed_dim,), jnp.float32))
        self.lnf_bias = Parameter(jnp.zeros((embed_dim,), jnp.float32))

    def _final(self, h):
        h = FusedMultiTransformer._ln(
            h, self.lnf_scale._data, self.lnf_bias._data,
            self.stack.epsilon)
        return h @ self.embed._data.T

    def forward(self, ids):
        """Plain full-sequence forward (training/eval parity path):
        logits [b, s, vocab]. No cache involved."""
        ids_d = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
        x = self.embed._data[ids_d]
        cos_t, sin_t = rope_table(self.stack.max_position,
                                  self.stack.head_dim,
                                  self.stack.rope_theta)
        h, _ = self.stack.prefill_raw(
            self.stack._stack(), x, None, None, cos_t, sin_t)
        return Tensor(self._final(h))


class GenerationEngine:
    """Continuous single-batch generation over a FusedCausalLM.

    generate(): prefill the prompt (one compiled program), then a
    compiled decode step per token. The decode program takes and returns
    the paged cache with donated buffers — the cache never leaves HBM.
    """

    def __init__(self, model: FusedCausalLM, page_size: int = 16,
                 max_length: int = 1024, num_pages: Optional[int] = None,
                 decode_chunk: int = 8):
        self.model = model
        st = model.stack
        self.max_length = max_length
        self.page_size = page_size
        self.decode_chunk = max(int(decode_chunk), 1)
        self._cos, self._sin = rope_table(st.max_position, st.head_dim,
                                          st.rope_theta)
        # one jitted prefill; decode programs are per-chunk-size (k=1
        # is the single-token step)
        self._prefill = jax.jit(self._prefill_fn, donate_argnums=(5, 6))
        self._decode_k_jit = {}
        self._num_pages = num_pages
        self._mgr = None

    def _get_decode_k(self, k: int):
        if k not in self._decode_k_jit:
            import functools

            self._decode_k_jit[k] = jax.jit(
                functools.partial(self._decode_k_fn, k=k),
                donate_argnums=(6, 7))
        return self._decode_k_jit[k]

    # ---------- pure programs ----------

    def _prefill_fn(self, weights, embed, lnf_s, lnf_b, ids, cache_k,
                    cache_v, tables):
        st = self.model.stack
        x = embed[ids]
        h, cache = st.prefill_raw(
            weights, x, PagedKV(cache_k, cache_v), tables,
            self._cos, self._sin)
        hl = h[:, -1]
        logits = FusedMultiTransformer._ln(
            hl, lnf_s, lnf_b, st.epsilon) @ embed.T
        return logits, cache.k, cache.v

    def _decode_k_fn(self, weights, embed, lnf_s, lnf_b, tok, seq_lens,
                     cache_k, cache_v, tables, *, k):
        """K greedy steps as ONE XLA program: the argmax feeds back into
        the next step inside lax.scan, so the host syncs once per chunk
        instead of once per token (the per-token dispatch round-trip is
        what bounds serving latency on a remote/tunneled chip)."""
        st = self.model.stack

        def step(carry, _):
            tok, lens, ck, cv = carry
            x = embed[tok]
            h, cache = st.decode_raw(
                weights, x, PagedKV(ck, cv), tables, lens,
                self._cos, self._sin)
            logits = FusedMultiTransformer._ln(
                h, lnf_s, lnf_b, st.epsilon) @ embed.T
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, lens + 1, cache.k, cache.v), nxt

        (tok, seq_lens, ck, cv), toks = jax.lax.scan(
            step, (tok, seq_lens, cache_k, cache_v), None, length=k)
        return jnp.swapaxes(toks, 0, 1), ck, cv  # [b, k]

    # ---------- serving API ----------

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None):
        """Greedy decode. input_ids: [b, s] (numpy/Tensor). Returns
        np.ndarray [b, s + max_new_tokens] (post-EOS positions hold EOS)."""
        ids = np.asarray(input_ids._data if isinstance(input_ids, Tensor)
                         else input_ids)
        b, s = ids.shape
        if max_new_tokens <= 0:
            return ids.copy()
        st = self.model.stack
        if s + max_new_tokens > self.max_length:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"engine max_length ({self.max_length}); raise max_length "
                "(positions past the page table would silently clamp)")
        # pages always cover max_length: block-table shapes are constant
        # across requests, so prefill/decode never recompile per length
        pages_per_seq = -(-self.max_length // self.page_size)
        self._mgr = BlockKVCacheManager(
            st.num_layers, st.num_kv_heads, st.head_dim, self.page_size,
            num_pages=self._num_pages or b * pages_per_seq)
        for i in range(b):
            self._mgr.allocate(i, self.max_length)
        tables = self._mgr.block_tables(range(b), pages_per_seq)
        cache = self._mgr.fresh_cache()

        weights = self.model.stack._stack()
        embed = self.model.embed._data
        lnf_s, lnf_b = (self.model.lnf_scale._data,
                        self.model.lnf_bias._data)

        logits, ck, cv = self._prefill(
            weights, embed, lnf_s, lnf_b, jnp.asarray(ids), cache.k,
            cache.v, tables)

        out = np.concatenate(
            [ids, np.zeros((b, max_new_tokens), ids.dtype)], axis=1)
        finished = np.zeros((b,), bool)

        # first generated token comes from prefill's last-position logits
        tok_np = np.asarray(jnp.argmax(logits, axis=-1)).astype(ids.dtype)
        if eos_token_id is not None:
            finished |= tok_np == eos_token_id
        out[:, s] = tok_np
        emitted = 1

        # remaining tokens in scan-chunks: one device program + ONE host
        # sync per chunk instead of per token (tunnel-latency bound)
        while emitted < max_new_tokens and not (
                eos_token_id is not None and finished.all()):
            k = min(self.decode_chunk, max_new_tokens - emitted)
            last_pos = s + emitted - 1  # position of the token we feed
            toks, ck, cv = self._get_decode_k(k)(
                weights, embed, lnf_s, lnf_b,
                jnp.asarray(out[:, last_pos].astype(np.int32)),
                jnp.full((b,), last_pos, jnp.int32), ck, cv, tables)
            toks_np = np.asarray(toks)
            for j in range(k):
                col = toks_np[:, j].astype(ids.dtype)
                if eos_token_id is not None:
                    col = np.where(finished, eos_token_id, col)
                    finished |= col == eos_token_id
                out[:, s + emitted] = col
                emitted += 1
        if eos_token_id is not None and finished.all():
            out[:, s + emitted:] = eos_token_id
        for i in range(b):
            self._mgr.free(i)
        return out
