"""Paged KV-cache block manager for continuous-batching serving.

TPU-native equivalent of the block-table machinery behind the reference's
block_multi_head_attention serving kernel (reference:
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu — its
``block_tables`` input; allocation policy lives in serving frontends).
Pages are rows of a preallocated PAGE-MAJOR pool
[num_layers * num_pages, n_kv_heads, page_size, head_dim] (each page one
contiguous head-major block — see nn/functional/paged_attention.py
layout notes);
the manager hands out LOGICAL page ids from a free list so sequences of
different lengths share one pool with no copies.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..incubate.nn.fused_transformer import PagedKV

__all__ = ["BlockKVCacheManager", "restore_scatter",
           "restore_scatter_jit", "gather_rows"]


def restore_scatter(pool, rows, vals):
    """The host→HBM KV restore as one program: scatter a spilled page
    blob (``vals``, layer-major rows — see ``phys_rows``) back into the
    pool. The pool argument is DONATED at the jit boundary
    (``restore_scatter_jit``) so a restore never holds two copies of
    the pool in HBM; registered as the ``serve.kv_restore`` program
    site for the lint passes."""
    return pool.at[rows].set(vals.astype(pool.dtype))


#: the jitted restore — what the serving restore/import paths call.
#: One executable per (pool, rows, vals) shape bucket (row vectors are
#: power-of-two padded, see ``ContinuousBatchingEngine._pad_pow2``);
#: the eager op-by-op form costs several ms of dispatch overhead PER
#: CALL, which a prefill replica's stepping thread pays mid-drive.
restore_scatter_jit = jax.jit(restore_scatter, donate_argnums=(0,))


@jax.jit
def gather_rows(pool, rows):
    """The export half (spill/migration): pool rows to one contiguous
    blob as a single compiled gather — same bucketed-shape contract
    (and the same dispatch-overhead rationale) as the restore."""
    return pool[rows]


class BlockKVCacheManager:
    """Owns the page pool + free list; builds per-batch block tables.

    Pages are REFCOUNTED: ``allocate``/``grow`` hand out pages at
    refcount 1, ``share`` maps existing pages into another sequence at
    +1 (the prefix/KV-reuse path — requests sharing a system prompt map
    the prefix's pages instead of re-prefilling them), and ``free``
    only returns a page to the free list once its last reference drops.
    Shared pages are copy-on-write in the page-table sense: only FULL,
    immutable prefix pages are ever shared (serving/prefix_cache.py),
    and a sharer's decode writes land in its privately owned tail
    pages, so no data copy is ever needed.
    """

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int,
                 page_size: int = 16, num_pages: int = 512,
                 dtype=jnp.float32, reserve_scratch: bool = False,
                 mp_degree: int = 1, mesh=None, mp_axis: str = "mp"):
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.page_size = page_size
        self.num_pages = num_pages
        # dtype: pool element type ("bfloat16"/"float32" strings are
        # normalized; "int8"/jnp.int8 selects the QUANTIZED cache-KV
        # mode below). Orthogonal to the engines' weight quantization —
        # quant="int8"/"a8w8" changes the matmul path, not the pool, so
        # any (quant, kv_dtype) pair composes (the bench's best rung is
        # int8 weights + int8 KV at b64).
        if isinstance(dtype, str) and dtype != "int8":
            dtype = jnp.dtype(dtype)
        self.dtype = dtype
        # tensor parallelism (mp_degree > 1): the pool's kv-head axis
        # shards over the mesh's mp axis — each shard stores only
        # num_kv_heads // mp heads (or ONE replicated head per shard in
        # the GQA small-kv fallback, mp % num_kv_heads == 0; any other
        # combination raises here with the exact divisibility
        # constraint instead of shape-crashing in the pool scatter).
        # Page tables are host-side ints and stay replicated, so every
        # page-level mechanism (prefix sharing, refcounts, preemption)
        # is TP-oblivious.
        self.mp_degree = max(int(mp_degree or 1), 1)
        self.mp_axis = mp_axis
        self._mesh = mesh
        if self.mp_degree > 1:
            from ..distributed.tp import split_kv_heads

            self.kv_heads_per_shard, self.kv_replication = \
                split_kv_heads(num_kv_heads, self.mp_degree)
        else:
            self.kv_heads_per_shard = num_kv_heads
            self.kv_replication = 1
        self._pool_heads = self.kv_heads_per_shard * self.mp_degree
        if self._mesh is not None and \
                (self.dtype == "int8" or self.dtype == jnp.int8):
            raise NotImplementedError(
                "int8 cache-KV is not supported under tensor "
                "parallelism yet — serve TP with a bf16/f32 pool")
        # reserve_scratch: page 0 is never handed out, so block-table
        # padding entries (0) and idle continuous-batching slots can
        # write/read it without clobbering a live sequence
        self._free: List[int] = list(
            range(1 if reserve_scratch else 0, num_pages))
        self._owned: dict = {}
        self._refs: Dict[int, int] = {}
        # fault-injection registry (serving/faults.py) or None — the
        # ``kv.alloc`` / ``kv.grow`` sites fire BEFORE any free-list
        # mutation, so an injected raise leaves the pool consistent
        # and a retry is clean (one attribute test when disabled)
        self._faults = None

    def fresh_cache(self) -> PagedKV:
        # layer-FOLDED page-major pool (see PagedKV): layer l's logical
        # page p is physical page l * num_pages + p — decode updates it
        # in place; each page is one contiguous DMA block.
        # dtype "int8" = quantized cache-KV mode: int8 token rows plus
        # per-token-per-head f32 scale PLANES [n_kv, pages*page_size]
        # (lane-major so the decode kernel applies them as logits-column
        # multiplies; see paged_decode_attention_inplace_q)
        shape = (self.num_layers * self.num_pages, self._pool_heads,
                 self.page_size, self.head_dim)
        if self.dtype == "int8" or self.dtype == jnp.int8:
            plane = (self._pool_heads,
                     self.num_layers * self.num_pages * self.page_size)
            return PagedKV(
                (jnp.zeros(shape, jnp.int8),
                 jnp.zeros(plane, jnp.float32)),
                (jnp.zeros(shape, jnp.int8),
                 jnp.zeros(plane, jnp.float32)))
        if self._mesh is not None:
            # kv-head-sharded pool: allocated directly under its
            # NamedSharding so no chip ever holds the full pool. On an
            # ep-only mesh (mp_degree == 1, expert parallelism — ISSUE
            # 15) the pool is REPLICATED over the mesh instead: EP
            # shards the expert bank, and the pool must still be
            # mesh-committed so the shard_mapped decode programs never
            # mix single-device arrays with mesh-sharded weights.
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            spec = P(None, self.mp_axis, None, None) \
                if self.mp_degree > 1 else P()
            sh = NamedSharding(self._mesh, spec)
            zero = jax.jit(lambda: jnp.zeros(shape, self.dtype),
                           out_shardings=sh)
            return PagedKV(zero(), zero())
        return PagedKV(jnp.zeros(shape, self.dtype),
                       jnp.zeros(shape, self.dtype))

    def pages_needed(self, length: int) -> int:
        return -(-length // self.page_size)

    def page_hbm_bytes(self) -> int:
        """Bytes ONE logical page occupies in HBM across both K and V
        pools (all layers, all kv heads) — the unit of host-tier
        capacity accounting and of the router directory's restore-vs-
        re-prefill cost model. int8 cache-KV counts the quantized rows
        plus their f32 scale-plane columns, so a spilled int8 page
        moves roughly half the bytes of its bf16 equivalent."""
        elems = (self.num_layers * self._pool_heads
                 * self.page_size * self.head_dim)
        if self.dtype == "int8" or self.dtype == jnp.int8:
            scale = (self._pool_heads * self.num_layers
                     * self.page_size * 4)
            return 2 * (elems + scale)
        return 2 * elems * jnp.dtype(self.dtype).itemsize

    def phys_rows(self, pages: Sequence[int]) -> np.ndarray:
        """Physical pool-row indices of logical ``pages`` across the
        layer-folded pool — layer l's copy of page p is row
        ``l * num_pages + p``. LAYER-MAJOR ``[num_layers * len(pages)]``
        so a KV blob gathered with one manager's rows scatters into
        another manager's rows even when their ``num_pages`` differ
        (the fleet page-migration path, serving/router.py)."""
        pages = np.asarray(list(pages), np.int64)
        layers = np.arange(self.num_layers,
                           dtype=np.int64) * self.num_pages
        return (layers[:, None] + pages[None, :]).reshape(-1)

    def allocate(self, seq_id, max_length: int) -> List[int]:
        """Reserve pages covering max_length tokens for one sequence."""
        n = self.pages_needed(max_length)
        f = self._faults
        if f is not None:
            f.fire("kv.alloc")
        if n > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: need {n} pages, "
                f"{len(self._free)} free (of {self.num_pages})")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        self._owned.setdefault(seq_id, []).extend(pages)
        return pages

    def grow(self, seq_id, n_pages: int) -> List[int]:
        """On-demand paging: extend an existing sequence by n_pages
        (the continuous-batching growth path — the reference's serving
        frontends grow block tables the same way between steps)."""
        f = self._faults
        if f is not None:
            f.fire("kv.grow")
        if n_pages > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted growing seq {seq_id}: need "
                f"{n_pages} pages, {len(self._free)} free")
        pages = [self._free.pop() for _ in range(n_pages)]
        for p in pages:
            self._refs[p] = 1
        self._owned.setdefault(seq_id, []).extend(pages)
        return pages

    def free(self, seq_id) -> None:
        self.release_pages(self._owned.pop(seq_id, []))

    def truncate(self, seq_id, new_len: int) -> List[int]:
        """Page-granular ROLLBACK: shrink ``seq_id``'s page list to
        exactly ``pages_needed(new_len)`` leading pages, releasing the
        tail (the speculative-decoding rejection path — KV written in
        the rejected window is masked-dead, so only the page TABLE
        rolls back; no data moves). Releasing is refcount-aware: a
        tail page also held by the prefix cache or another sequence
        just drops this sequence's reference and stays live — shared
        prefix pages are NEVER freed by a rejection. Returns the pages
        released (possibly still live under other references)."""
        keep = self.pages_needed(max(int(new_len), 0))
        owned = self._owned.get(seq_id)
        if owned is None or keep >= len(owned):
            return []
        tail = owned[keep:]
        del owned[keep:]
        self.release_pages(tail)
        return tail

    # ---------- refcounting (prefix/KV reuse) ----------

    def retain(self, pages: Sequence[int]) -> None:
        """+1 on live pages (prefix-cache registration keeps prompt
        pages alive past their original request's free)."""
        for p in pages:
            if p not in self._refs:
                raise KeyError(f"retain of non-live page {p}")
            self._refs[p] += 1

    def release_pages(self, pages: Sequence[int]) -> None:
        """-1 each; a page returns to the free list when its LAST
        reference drops (shared prefix pages survive a sharer's free)."""
        for p in pages:
            rc = self._refs.get(p, 0)
            if rc <= 0:
                raise KeyError(f"release of non-live page {p}")
            if rc == 1:
                del self._refs[p]
                self._free.append(p)
            else:
                self._refs[p] = rc - 1

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def share(self, seq_id, pages: Sequence[int]) -> None:
        """Map already-live pages into ``seq_id``'s page list at +1 ref
        — the prefix-reuse admission path. Call BEFORE allocating the
        sequence's own tail pages: block tables are ordered, and the
        shared pages cover the leading positions."""
        self.retain(pages)
        self._owned.setdefault(seq_id, []).extend(pages)

    def rekey(self, old_seq_id, new_seq_id) -> None:
        """Move a sequence's page list to a new key (the serving
        scheduler parks chunk-prefilling sequences under a side key so
        the decode batch's slot tables never see half-filled pages)."""
        if new_seq_id in self._owned:
            raise KeyError(f"rekey target {new_seq_id!r} already owned")
        if old_seq_id in self._owned:
            self._owned[new_seq_id] = self._owned.pop(old_seq_id)

    def block_tables(self, seq_ids, pages_per_seq: int = None,
                     allow_missing: bool = False):
        """[batch, pages_per_seq] int32 table (padded with page 0 — padded
        entries are masked out by seq_lens in the attention).
        ``allow_missing`` maps unknown seq_ids to all-zero (scratch) rows
        — for continuous-batching idle slots; otherwise a stale/freed
        seq_id is a caller bug and raises KeyError."""
        if allow_missing:
            rows = [self._owned.get(s, []) for s in seq_ids]
        else:
            rows = [self._owned[s] for s in seq_ids]
        width = pages_per_seq or max(len(r) for r in rows)
        table = np.zeros((len(rows), width), np.int32)
        for i, r in enumerate(rows):
            table[i, : len(r)] = r
        return jnp.asarray(table)

    @property
    def free_pages(self) -> int:
        return len(self._free)
