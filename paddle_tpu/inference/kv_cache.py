"""Paged KV-cache block manager for continuous-batching serving.

TPU-native equivalent of the block-table machinery behind the reference's
block_multi_head_attention serving kernel (reference:
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu — its
``block_tables`` input; allocation policy lives in serving frontends).
Pages are rows of a preallocated PAGE-MAJOR pool
[num_layers * num_pages, n_kv_heads, page_size, head_dim] (each page one
contiguous head-major block — see nn/functional/paged_attention.py
layout notes);
the manager hands out LOGICAL page ids from a free list so sequences of
different lengths share one pool with no copies.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from ..incubate.nn.fused_transformer import PagedKV

__all__ = ["BlockKVCacheManager"]


class BlockKVCacheManager:
    """Owns the page pool + free list; builds per-batch block tables."""

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int,
                 page_size: int = 16, num_pages: int = 512,
                 dtype=jnp.float32, reserve_scratch: bool = False):
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.page_size = page_size
        self.num_pages = num_pages
        # dtype: pool element type ("bfloat16"/"float32" strings are
        # normalized; "int8"/jnp.int8 selects the QUANTIZED cache-KV
        # mode below). Orthogonal to the engines' weight quantization —
        # quant="int8"/"a8w8" changes the matmul path, not the pool, so
        # any (quant, kv_dtype) pair composes (the bench's best rung is
        # int8 weights + int8 KV at b64).
        if isinstance(dtype, str) and dtype != "int8":
            dtype = jnp.dtype(dtype)
        self.dtype = dtype
        # reserve_scratch: page 0 is never handed out, so block-table
        # padding entries (0) and idle continuous-batching slots can
        # write/read it without clobbering a live sequence
        self._free: List[int] = list(
            range(1 if reserve_scratch else 0, num_pages))
        self._owned: dict = {}

    def fresh_cache(self) -> PagedKV:
        # layer-FOLDED page-major pool (see PagedKV): layer l's logical
        # page p is physical page l * num_pages + p — decode updates it
        # in place; each page is one contiguous DMA block.
        # dtype "int8" = quantized cache-KV mode: int8 token rows plus
        # per-token-per-head f32 scale PLANES [n_kv, pages*page_size]
        # (lane-major so the decode kernel applies them as logits-column
        # multiplies; see paged_decode_attention_inplace_q)
        shape = (self.num_layers * self.num_pages, self.num_kv_heads,
                 self.page_size, self.head_dim)
        if self.dtype == "int8" or self.dtype == jnp.int8:
            plane = (self.num_kv_heads,
                     self.num_layers * self.num_pages * self.page_size)
            return PagedKV(
                (jnp.zeros(shape, jnp.int8),
                 jnp.zeros(plane, jnp.float32)),
                (jnp.zeros(shape, jnp.int8),
                 jnp.zeros(plane, jnp.float32)))
        return PagedKV(jnp.zeros(shape, self.dtype),
                       jnp.zeros(shape, self.dtype))

    def pages_needed(self, length: int) -> int:
        return -(-length // self.page_size)

    def allocate(self, seq_id, max_length: int) -> List[int]:
        """Reserve pages covering max_length tokens for one sequence."""
        n = self.pages_needed(max_length)
        if n > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: need {n} pages, "
                f"{len(self._free)} free (of {self.num_pages})")
        pages = [self._free.pop() for _ in range(n)]
        self._owned.setdefault(seq_id, []).extend(pages)
        return pages

    def grow(self, seq_id, n_pages: int) -> List[int]:
        """On-demand paging: extend an existing sequence by n_pages
        (the continuous-batching growth path — the reference's serving
        frontends grow block tables the same way between steps)."""
        if n_pages > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted growing seq {seq_id}: need "
                f"{n_pages} pages, {len(self._free)} free")
        pages = [self._free.pop() for _ in range(n_pages)]
        self._owned.setdefault(seq_id, []).extend(pages)
        return pages

    def free(self, seq_id) -> None:
        self._free.extend(self._owned.pop(seq_id, []))

    def block_tables(self, seq_ids, pages_per_seq: int = None,
                     allow_missing: bool = False):
        """[batch, pages_per_seq] int32 table (padded with page 0 — padded
        entries are masked out by seq_lens in the attention).
        ``allow_missing`` maps unknown seq_ids to all-zero (scratch) rows
        — for continuous-batching idle slots; otherwise a stale/freed
        seq_id is a caller bug and raises KeyError."""
        if allow_missing:
            rows = [self._owned.get(s, []) for s in seq_ids]
        else:
            rows = [self._owned[s] for s in seq_ids]
        width = pages_per_seq or max(len(r) for r in rows)
        table = np.zeros((len(rows), width), np.int32)
        for i, r in enumerate(rows):
            table[i, : len(r)] = r
        return jnp.asarray(table)

    @property
    def free_pages(self) -> int:
        return len(self._free)
