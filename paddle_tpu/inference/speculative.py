"""Speculative decoding: self-drafting + draft-model speculation with a
batched paged verify pass.

Decode is weight-bandwidth-bound: the grouped stream kernels (PR 5)
move the ENTIRE weight stack through VMEM per generated token, and TP
(PR 10) only shrank the per-chip slice, not the per-token cost. This
module amortizes that bandwidth k-fold: a cheap DRAFTER proposes k
tokens per active slot, and ONE streamed verify pass — reusing
``FusedMultiTransformer.prefill_chunk_raw`` against the paged pool —
scores the whole (k+1)-token window, so the weight stack is read once
per accepted window instead of once per token (ROADMAP item 1; the
verify-tail fusion follows "LLM Inference Acceleration via Efficient
Operation Fusion", PAPERS.md).

Greedy-token parity is BY CONSTRUCTION: the verify pass computes the
target model's own greedy picks ``cand[j]`` at every window position;
draft token ``d_j`` is accepted iff it EQUALS ``cand[j-1]``, and the
round emits the accepted prefix plus the bonus token ``cand[a]`` — all
of which are exactly the tokens non-speculative greedy decode would
have produced, whatever the drafter proposed. A bad drafter costs
throughput, never output.

The fused verify tail (logits → accept-prefix → bonus selection) runs
INSIDE the compiled program — the host fetches one ``[b, k+1]`` token
matrix and one ``[b]`` accept length per round, never a per-token
round-trip — and a rejection costs a page-table truncation
(``BlockKVCacheManager.truncate``): rejected positions' KV stays as
masked-dead garbage that the next round's window overwrites, while the
over-grown tail pages return to the pool (refcount-aware — shared
prefix pages only drop a reference).

Drafters (one ``Drafter`` interface, engine-agnostic):

- :class:`DraftModelDrafter` — a small :class:`FusedCausalLM` draft
  model with its own TINY, NON-PAGED KV state (one contiguous
  max_length region per slot; rollback = a length counter, no page
  ops). Draft weights are never sharded — under TP they stay
  replicated while the verify pass runs shard_mapped.
- :class:`SelfDraftHeads` — Medusa-style self-drafting heads,
  training-free: head ``h`` drafts greedy top-1 from the TARGET
  model's last verified hidden state through a fixed seeded residual
  projection and the target's own lm head. Zero extra weights to
  stream; acceptance depends on workload regularity.
- :class:`ScheduledDrafter` — proposes from a per-request token
  script. The forced accept/reject schedules of the parity tests and
  the acceptance-ceiling bench rung (``bench.py --decode-spec``
  replays a recorded greedy stream → accept rate 1.0, isolating pure
  verify amortization).

Scheduler integration: ``ContinuousBatchingEngine(speculative=...)``
(and thus ``ServingEngine``) replaces the decode-chunk step with one
speculative round — speculation takes the decode slot of the
SLO-weighted interleave cycle and composes with chunked prefill,
preemption-by-recompute (a resumed request's drafter state resets and
re-drafts), deadlines and the progress watchdog (accepted tokens move
``len(req.generated)``, the watchdog's mark).

Telemetry: ``serving.spec_{drafted,accepted,rejected}_tokens`` +
``serving.spec_rounds`` counters, the ``serve.accept_len`` histogram,
``spec.{propose_ms,verify_ms}`` timing histograms and the ``spec.k``
gauge; each round journals a ``spec_verify[k,accepted]`` lifecycle
event (rendered as a span in the chrome trace and as the accept-rate
row in ``tools/serve_top.py``). The verify program reports under the
``serve.verify[k=*,mp=N]`` roofline rung and is registered as the
``serve.verify`` program site for the tpu_lint whole-program passes.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..incubate.nn.fused_transformer import (
    FusedMultiTransformer, PagedKV, rope_table)
from ..profiler import roofline as _roofline
from ..profiler import stats as _stats

__all__ = ["Drafter", "DraftModelDrafter", "SelfDraftHeads",
           "ScheduledDrafter", "SpeculativeDecoder",
           "build_speculative_decoder"]


class Drafter:
    """Draft-token source for speculative decoding.

    One instance serves every slot of one engine; ``bind`` is called
    once by the :class:`SpeculativeDecoder` before the first round.
    ``propose`` must be IDEMPOTENT for unchanged engine state: a
    crash-isolated retry re-runs the whole round, and re-proposing
    must rewrite the same drafter state with identical values (commit
    is the only place per-slot progress advances).
    """

    k = 0

    def bind(self, engine, k: int) -> None:
        self.k = int(k)

    def reset(self, slot: int) -> None:
        """Slot reuse/preemption: drop slot state; the next round
        re-drafts from the request's recorded history."""

    def propose(self, engine, active) -> np.ndarray:
        """[max_batch, k] int32 draft tokens continuing each active
        slot's stream past its ``_last_tok`` (rows of inactive slots
        are ignored)."""
        raise NotImplementedError

    def commit(self, slot: int, accepted: int) -> None:
        """A verify round accepted ``accepted`` of this slot's drafts
        (and emitted the bonus token): advance per-slot state."""

    def observe_hidden(self, hidden, active) -> None:
        """Target-model hidden state at each slot's accept boundary
        (``[max_batch, d]``), from the verify pass — the self-drafting
        heads' input. Called once per round for surviving slots."""


class DraftModelDrafter(Drafter):
    """A small FusedCausalLM draft model with its own tiny, NON-PAGED
    KV state.

    Each slot owns one contiguous ``max_length``-token KV region (a
    degenerate one-page-per-sequence layout: ``page_size ==
    max_length``), so rollback after a rejection is a per-slot length
    counter — no page-table surgery, no data movement. The drafter
    maintains the invariant ``_lens[slot] <= engine._lens[slot] - 1``
    (tokens of the request's history present in the draft cache); a
    lag (resume after preemption, the fully-accepted round's last
    draft) is closed by bucketed catch-up chunks through the draft
    stack's ``prefill_chunk_raw`` before the next propose.

    Draft weights never shard: under TP the propose/catch-up programs
    run plain (replicated) jit while the target's verify pass runs
    shard_mapped.
    """

    def __init__(self, model, prompt_bucket: int = 16):
        self.model = model
        self.prompt_bucket = max(int(prompt_bucket), 1)

    def bind(self, engine, k: int) -> None:
        from .kv_cache import BlockKVCacheManager

        self.k = int(k)
        st = self.model.stack
        if self.model.vocab_size != engine.model.vocab_size:
            raise ValueError(
                f"draft model vocab ({self.model.vocab_size}) != target "
                f"vocab ({engine.model.vocab_size})")
        if st.max_position < engine.max_length:
            raise ValueError(
                f"draft model max_position ({st.max_position}) < engine "
                f"max_length ({engine.max_length})")
        self._B = engine.max_batch
        self._max_len = int(engine.max_length)
        wd = st.qkv_weight._data.dtype
        self._cdtype = jnp.bfloat16 if wd == jnp.int8 else wd
        self._cos, self._sin = rope_table(st.max_position, st.head_dim,
                                          st.rope_theta)
        self._head_t = jnp.array(self.model.embed._data.T) \
            .astype(self._cdtype)
        # tiny non-paged KV: one max_length page per slot (+ scratch)
        self._mgr = BlockKVCacheManager(
            st.num_layers, st.num_kv_heads, st.head_dim,
            page_size=self._max_len, num_pages=self._B + 1,
            dtype=(jnp.bfloat16 if self._cdtype == jnp.int8
                   else self._cdtype),
            reserve_scratch=True)
        for i in range(self._B):
            self._mgr.allocate(i, 1)
        self._tables = self._mgr.block_tables(range(self._B), 1)
        cache = self._mgr.fresh_cache()
        self._ck, self._cv = cache.k, cache.v
        self._lens = np.zeros((self._B,), np.int64)
        self._propose_jit = None
        self._catchup_jit: dict = {}
        _stats.set_gauge(
            "spec.draft_params",
            sum(int(np.prod(p.shape))
                for p in self.model.parameters()))

    def reset(self, slot: int) -> None:
        self._lens[slot] = 0

    def commit(self, slot: int, accepted: int) -> None:
        # propose wrote k tokens ([last_tok, d_1..d_{k-1}]); they are
        # correct through the fed last_tok plus the accepted prefix
        self._lens[slot] += min(accepted + 1, self.k)

    # ---------- compiled draft programs ----------

    def _catchup_fn(self, weights, embed, ids, start, chunk_lens,
                    ck, cv, tables):
        st = self.model.stack
        x = embed[ids].astype(self._cdtype)
        _h, cache = st.prefill_chunk_raw(
            weights, x, PagedKV(ck, cv), tables, start, chunk_lens,
            self._cos, self._sin)
        return cache.k, cache.v

    def _get_catchup(self, c: int):
        if c not in self._catchup_jit:
            self._catchup_jit[c] = _roofline.AotProgram(
                f"spec.draft_catchup[c={c}]",
                jax.jit(self._catchup_fn, donate_argnums=(5, 6)))
        return self._catchup_jit[c]

    def _propose_fn(self, weights, embed, head_t, lnf_s, lnf_b, tok,
                    lens, ck, cv, tables, *, k):
        """k greedy draft steps as ONE scan program: the picked token
        feeds back inside the loop (the target engine's _decode_k_fn
        shape), writing the fed tokens' KV into the per-slot regions."""
        st = self.model.stack
        from .engine import GenerationEngine

        def step(carry, _):
            tok, lens, ck, cv = carry
            x = embed[tok].astype(self._cdtype)
            h, cache = st.decode_raw(
                weights, x, PagedKV(ck, cv), tables, lens,
                self._cos, self._sin)
            hl = FusedMultiTransformer._ln(
                h, lnf_s, lnf_b, st.epsilon).astype(head_t.dtype)
            logits = jax.lax.dot_general(
                hl, head_t, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            nxt = GenerationEngine._argmax(logits)
            return (nxt, lens + 1, cache.k, cache.v), nxt

        (_t, _l, ck, cv), toks = jax.lax.scan(
            step, (tok, lens, ck, cv), None, length=k)
        return jnp.swapaxes(toks, 0, 1), ck, cv          # [B, k]

    def _get_propose(self):
        if self._propose_jit is None:
            import functools

            self._propose_jit = _roofline.AotProgram(
                f"spec.draft_propose[k={self.k}]",
                jax.jit(functools.partial(self._propose_fn, k=self.k),
                        donate_argnums=(7, 8)))
        return self._propose_jit

    # ---------- Drafter API ----------

    def _ensure(self, engine, i: int) -> None:
        """Close any history lag (admission, resume-after-preempt, the
        fully-accepted round's unfed last draft) with bucketed catch-up
        chunks. No-op when the slot is already synced — so a retried
        round re-enters idempotently."""
        need = int(engine._lens[i]) - 1
        have = int(self._lens[i])
        if have >= need:
            return
        req = engine._slots[i]
        hist = np.concatenate(
            [req.prompt, np.asarray(req.generated[:-1], np.int32)]) \
            if req.generated else np.asarray(req.prompt, np.int32)
        w = self.model.stack._stack()
        embed = self.model.embed._data
        bs = self.prompt_bucket
        while have < need:
            n = min(need - have, 4 * bs)
            c = -(-n // bs) * bs
            ids = np.zeros((1, c), np.int32)
            ids[0, :n] = hist[have: have + n]
            self._ck, self._cv = self._get_catchup(c)(
                w, embed, jnp.asarray(ids),
                jnp.asarray([have], jnp.int32),
                jnp.asarray([n], jnp.int32),
                self._ck, self._cv, self._tables[i: i + 1])
            have += n
        self._lens[i] = have

    def propose(self, engine, active) -> np.ndarray:
        for i in active:
            self._ensure(engine, i)
        tok = np.zeros((self._B,), np.int32)
        lens = np.zeros((self._B,), np.int32)
        for i in active:
            tok[i] = engine._last_tok[i]
            lens[i] = self._lens[i]
        toks, self._ck, self._cv = self._get_propose()(
            self.model.stack._stack(), self.model.embed._data,
            self._head_t, self.model.lnf_scale._data,
            self.model.lnf_bias._data, jnp.asarray(tok),
            jnp.asarray(lens), self._ck, self._cv, self._tables)
        return np.asarray(toks)


class SelfDraftHeads(Drafter):
    """Medusa-style self-drafting heads, training-free.

    Head ``h`` drafts position ``+h+1`` as the greedy top-1 of the
    TARGET model's lm head over a fixed seeded residual projection of
    the last verified hidden state (``hidden + hidden @ W_h``,
    ``W_h ~ scale * N(0, 1)`` — no training in-repo; near-zero scale
    degenerates every head to the model's own next-token belief, which
    accepts on locally repetitive streams). Costs no extra weight
    streaming — the heads ride the already-resident lm head — so even
    low acceptance rarely loses; acceptance never changes output.
    """

    def __init__(self, scale: float = 0.02, seed: int = 0):
        self.scale = float(scale)
        self.seed = int(seed)

    def bind(self, engine, k: int) -> None:
        self.k = int(k)
        self._engine = engine
        d = engine.model.stack.embed_dim
        self._w = jax.random.normal(
            jax.random.PRNGKey(self.seed), (self.k, d, d),
            jnp.float32) * self.scale
        self._hidden = np.zeros((engine.max_batch, d), np.float32)
        self._jit = None

    def reset(self, slot: int) -> None:
        self._hidden[slot] = 0.0

    def observe_hidden(self, hidden, active) -> None:
        h = np.asarray(hidden, np.float32)
        for i in active:
            self._hidden[i] = h[i]

    def _heads_fn(self, head_t, lnf_s, lnf_b, ws, hidden):
        g = self._engine._gen
        from .engine import GenerationEngine

        def one(w):
            hh = hidden + hidden @ w
            logits = g._logits(hh.astype(g._cdtype), head_t,
                               lnf_s, lnf_b)
            return GenerationEngine._argmax(logits)

        toks = jax.lax.map(one, ws)                      # [k, B]
        return jnp.swapaxes(toks, 0, 1)

    def propose(self, engine, active) -> np.ndarray:
        if self._jit is None:
            self._jit = _roofline.AotProgram(
                f"spec.heads_propose[k={self.k}]",
                jax.jit(self._heads_fn))
        lnf_s, lnf_b = engine._gen._lnf()
        toks = self._jit(engine._gen._head_t, lnf_s, lnf_b, self._w,
                         jnp.asarray(self._hidden))
        return np.asarray(toks)


class ScheduledDrafter(Drafter):
    """Drafts from a per-request token script: ``lookup(req)`` returns
    the request's full expected generated stream; each round proposes
    its next k tokens. The parity tests' forced accept/reject
    schedules and the bench's acceptance-ceiling oracle (replay a
    recorded greedy stream → accept rate 1.0) both use this."""

    def __init__(self, lookup):
        self._lookup = lookup

    def bind(self, engine, k: int) -> None:
        self.k = int(k)
        self._B = engine.max_batch

    def propose(self, engine, active) -> np.ndarray:
        out = np.zeros((self._B, self.k), np.int32)
        for i in active:
            req = engine._slots[i]
            fut = np.asarray(self._lookup(req),
                             np.int32)[len(req.generated):]
            n = min(len(fut), self.k)
            out[i, :n] = fut[:n]
        return out


class SpeculativeDecoder:
    """Per-engine speculative-round driver: drafter + the batched
    verify program + accept/rollback bookkeeping. Owned by
    ``ContinuousBatchingEngine`` (``self._spec``); ``run_round`` is the
    decode-slot payload of the scheduler's interleave cycle."""

    def __init__(self, engine, drafter: Drafter, k: int):
        if k < 1:
            raise ValueError(f"spec_k must be >= 1, got {k}")
        self.engine = engine
        self.drafter = drafter
        self.k = int(k)
        drafter.bind(engine, self.k)
        self._rid = [None] * engine.max_batch
        self._verify_jit = None
        _stats.set_gauge("spec.k", self.k)

    def _rung(self) -> str:
        tp = self.engine._gen._tp
        mp = f",mp={tp.mp}" if tp is not None else ""
        return f"serve.verify[k={self.k}{mp}]"

    def reset_slot(self, i: int) -> None:
        self._rid[i] = None
        self.drafter.reset(i)

    # ---------- the compiled verify program ----------

    def _verify_fn(self, weights, embed, head_t, lnf_s, lnf_b, ids,
                   start, chunk_lens, draft, ck, cv, tables, *, k):
        """ONE streamed pass scores the whole (k+1)-token window
        ``ids[b] = [last_tok, d_1..d_k]`` at positions ``start[b]..``
        against the paged pool (``prefill_chunk_raw`` — cached pages +
        the in-window causal triangle), then fuses the verify tail:
        greedy picks at every window position, the accept-prefix
        length, and the accept-boundary hidden state (the self-draft
        heads' input) — so the host consumes one token matrix per
        round, never a per-token sync. Rows with ``chunk_lens == 0``
        (idle slots) write scratch and are ignored."""
        g = self.engine._gen
        st = self.engine.model.stack
        from .engine import GenerationEngine

        x = embed[ids].astype(g._cdtype)
        h, cache = st.prefill_chunk_raw(
            weights, x, PagedKV(ck, cv), tables, start, chunk_lens,
            g._cos, g._sin, a8w8=g._a8w8, tp=g._tp)
        b, c, d = h.shape                                # c = k + 1
        logits = g._logits(h.reshape(b * c, d), head_t, lnf_s, lnf_b)
        cand = GenerationEngine._argmax(logits).reshape(b, c)
        # fused accept-prefix: draft j (window index j+1) is accepted
        # iff it equals the model's own greedy pick at index j AND its
        # window index is inside the (clamped) valid window
        valid = (jnp.arange(k, dtype=jnp.int32)[None, :] + 2) \
            <= chunk_lens[:, None]
        match = jnp.logical_and(draft == cand[:, :-1], valid)
        acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                      axis=1).astype(jnp.int32)
        h_acc = h[jnp.arange(b), acc]                    # [b, d]
        return cand, acc, h_acc, cache.k, cache.v

    def _get_verify(self):
        if self._verify_jit is None:
            import functools

            self._verify_jit = _roofline.AotProgram(
                self._rung(),
                jax.jit(functools.partial(self._verify_fn, k=self.k),
                        donate_argnums=(9, 10)))
        return self._verify_jit

    # ---------- one speculative round ----------

    def run_round(self, eng, active, win):
        """Draft + verify + consume for the active decode batch.

        ``win[i]`` is slot i's clamped window length (<= k+1). NO host
        state mutates before the fetched round validates
        (``_postprocess_tokens``), so a crash-isolated retry re-runs
        the round cleanly — propose/catch-up rewrite identical values
        at identical positions. Returns requests finished this round.
        """
        import time as _time

        g = eng._gen
        B, k = eng.max_batch, self.k
        mgr = eng._mgr
        # (re)bind drafter slots whose request changed — admission or
        # resume-after-preemption ("resume re-drafts")
        for i in active:
            req = eng._slots[i]
            if self._rid[i] != req.id:
                self.drafter.reset(i)
                self._rid[i] = req.id
        t0 = _time.perf_counter()
        draft_np = np.asarray(self.drafter.propose(eng, active),
                              np.int32)
        _stats.observe("spec.propose_ms",
                       (_time.perf_counter() - t0) * 1e3)
        ids = np.zeros((B, k + 1), np.int32)
        start = np.zeros((B,), np.int32)
        clens = np.zeros((B,), np.int32)
        for i in active:
            ids[i, 0] = eng._last_tok[i]
            ids[i, 1:] = draft_np[i]
            start[i] = int(eng._lens[i]) - 1
            clens[i] = int(win[i])
        tables = mgr.block_tables(
            [("slot", i) for i in range(B)], eng._pages_per_seq,
            allow_missing=True)
        _stats.set_gauge("serving.kv_pages_in_use",
                         mgr.num_pages - mgr.free_pages)
        _stats.set_gauge("serving.active_slots", len(active))
        # re-stamped per round: benches reset the registry after
        # warmup, and the window size must survive into telemetry
        _stats.set_gauge("spec.k", k)
        if g._tp is not None:
            _stats.set_gauge("dist.mp_degree", g._tp.mp)
        g._count_a8w8(1)
        lnf_s, lnf_b = g._lnf()
        t0 = _time.perf_counter()
        cand, acc, h_acc, eng._ck, eng._cv = self._get_verify()(
            g._weights(), g._embed(), g._head_t, lnf_s, lnf_b,
            jnp.asarray(ids), jnp.asarray(start), jnp.asarray(clens),
            jnp.asarray(draft_np), eng._ck, eng._cv, tables)
        cand_np, acc_np = np.asarray(cand), np.asarray(acc)
        # the fetch above synced the round — honest verify roofline
        dt = _time.perf_counter() - t0
        _roofline.analyze(self._rung(), dt)
        _stats.observe("spec.verify_ms", dt * 1e3)
        # validation BEFORE any request mutates (serving override:
        # corruption detection) — a raise leaves the round retryable
        cand_np = eng._postprocess_tokens(cand_np, active)

        _stats.inc("serving.spec_rounds")
        jr = eng._journal
        u = eng._usage
        done_now = []
        alive = []
        for i in active:
            req = eng._slots[i]
            a = int(acc_np[i])
            _stats.inc("serving.spec_drafted_tokens", k)
            _stats.inc("serving.spec_accepted_tokens", a)
            _stats.inc("serving.spec_rejected_tokens", k - a)
            _stats.observe("serve.accept_len", a)
            if jr is not None:
                jr.record("spec_verify", req.id, i,
                          {"k": k, "accepted": a,
                           "dur_ms": round(dt * 1e3, 3)})
            cb = getattr(req, "on_token", None)
            consumed = 0
            for j in range(a + 1):
                if req.done:
                    break
                t = int(cand_np[i, j])
                req.generated.append(t)
                consumed += 1
                if cb is not None:
                    cb(req, t)
                if (req.eos_token_id is not None
                        and t == req.eos_token_id) or \
                        len(req.generated) >= req.max_new_tokens:
                    req.done = True
            # window tokens decoded past req.done are executed-but-
            # discarded device work, same meaning as the decode-chunk
            # counter (here bounded by the accept length)
            _stats.inc("serving.wasted_decode_tokens",
                       a + 1 - consumed)
            if u is not None:
                u.add_tokens(req, spec_accepted=a,
                             wasted=a + 1 - consumed)
            if req.done:
                eng._finish_hook(req, i)
                eng._release(i)          # also resets the drafter slot
                done_now.append(req)
            else:
                eng._lens[i] += consumed          # consumed == a + 1
                eng._last_tok[i] = int(cand_np[i, consumed - 1])
                # rejection rollback = page-table truncation: pages
                # grown for the rejected window tail return to the
                # pool (refcount-aware — shared prefix pages only
                # drop a reference, never free under a live sharer)
                mgr.truncate(("slot", i), int(eng._lens[i]) - 1)
                if u is not None:
                    u.set_pages(req, len(
                        mgr._owned.get(("slot", i), ())))
                self.drafter.commit(i, a)
                alive.append(i)
        if alive:
            self.drafter.observe_hidden(h_acc, alive)
        eng.finished.extend(done_now)
        return done_now


def build_speculative_decoder(engine, speculative,
                              spec_k: Optional[int] = None
                              ) -> SpeculativeDecoder:
    """Resolve the engines' ``speculative=`` argument: ``True`` reads
    ``FLAGS_spec_drafter``; ``"self"`` builds the self-drafting heads;
    a :class:`FusedCausalLM` wraps into a :class:`DraftModelDrafter`;
    a :class:`Drafter` instance is used as-is. ``spec_k`` defaults to
    ``FLAGS_spec_k``."""
    from ..core.flags import flag as _flag
    from .engine import FusedCausalLM

    k = int(spec_k) if spec_k is not None else int(_flag("spec_k"))
    if speculative is True:
        speculative = str(_flag("spec_drafter"))
    if isinstance(speculative, str):
        if speculative == "self":
            drafter = SelfDraftHeads()
        elif speculative == "draft":
            raise ValueError(
                "speculative='draft' needs a draft model — pass "
                "speculative=DraftModelDrafter(draft_model) (or the "
                "FusedCausalLM itself)")
        else:
            raise ValueError(
                f"speculative={speculative!r}: expected 'self', a "
                "Drafter instance, or a FusedCausalLM draft model")
    elif isinstance(speculative, FusedCausalLM):
        drafter = DraftModelDrafter(speculative)
    elif isinstance(speculative, Drafter):
        drafter = speculative
    else:
        raise ValueError(
            f"speculative={speculative!r}: expected True, 'self', a "
            "Drafter instance, or a FusedCausalLM draft model")
    return SpeculativeDecoder(engine, drafter, k)
