"""paddle_tpu.io — mirrors python/paddle/io."""
from .dataloader import DataLoader, default_collate_fn, get_worker_info  # noqa: F401
from .dataset import (  # noqa: F401
    ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset,
    Subset, TensorDataset, random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler, SubsetRandomSampler, WeightedRandomSampler,
)

__all__ = [
    "DataLoader", "Dataset", "IterableDataset", "TensorDataset",
    "ComposeDataset", "ChainDataset", "ConcatDataset", "Subset",
    "random_split", "Sampler", "SequenceSampler", "RandomSampler",
    "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler",
    "SubsetRandomSampler", "default_collate_fn", "get_worker_info",
]
