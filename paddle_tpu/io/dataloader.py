"""DataLoader.

TPU-native equivalent of the reference's DataLoader (reference:
python/paddle/io/dataloader/dataloader_iter.py — multiprocess workers +
blocking queue feeding the device). Here: collation to numpy on worker
threads with a bounded prefetch queue (keeping the TPU fed is a host-side
pipeline problem; heavy decode work can still use multiprocessing via
``num_workers``), final device transfer happens lazily at first use.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, seed, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack samples into batched Tensors (reference: collate.py)."""
    from ..core.tensor import Tensor

    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s._data for s in batch]))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(t)) for t in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class _PrefetchIter:
    """Background-thread prefetcher with a bounded queue."""

    def __init__(self, gen_fn, prefetch: int):
        self._q: queue.Queue = queue.Queue(maxsize=max(prefetch, 1))
        self._done = object()
        self._exc = None

        def run():
            try:
                for item in gen_fn():
                    self._q.put(item)
            except BaseException as e:  # surfaced on the consumer side
                self._exc = e
            finally:
                self._q.put(self._done)

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item


def _worker_loop(dataset, index_q, data_q, worker_id, num_workers, seed,
                 init_fn):
    """Worker process body (reference: io/dataloader/worker.py
    _worker_loop): pull (batch_idx, indices), fetch samples, push raw
    results; collation happens in the parent so only plain numpy/python
    crosses the queue."""
    import traceback

    _worker_info.info = WorkerInfo(worker_id, num_workers, seed, dataset)
    if init_fn is not None:
        init_fn(worker_id)
    while True:
        item = index_q.get()
        if item is None:
            break
        bidx, indices = item
        try:
            samples = [dataset[i] for i in indices]
            data_q.put((bidx, samples, None))
        except Exception:
            data_q.put((bidx, None, traceback.format_exc()))


class _MultiprocessIter:
    """Multi-process fetch with ordered reassembly (reference:
    dataloader_iter.py _DataLoaderIterMultiProcess — per-worker index
    queues, shared data queue, out-of-order results reordered by batch
    index). Workers are forked: they only run dataset.__getitem__ (host
    numpy work), never jax."""

    def __init__(self, loader: "DataLoader"):
        import multiprocessing as mp

        self._loader = loader
        self._ctx = mp.get_context("fork")
        n = loader.num_workers
        self._index_queues = [self._ctx.Queue() for _ in range(n)]
        self._data_queue = self._ctx.Queue()
        self._workers = []
        # fresh base seed per epoch/iterator so per-worker augmentation
        # RNGs differ across epochs (reference: base_seed + worker_id)
        base_seed = int(np.random.randint(0, 2 ** 31 - 1))
        for wid in range(n):
            w = self._ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self._index_queues[wid],
                      self._data_queue, wid, n, base_seed + wid,
                      loader.worker_init_fn),
                daemon=True)
            w.start()
            self._workers.append(w)
        self._batches = enumerate(iter(loader.batch_sampler))
        self._prefetch = max(loader.prefetch_factor, 1) * n
        self._sent = 0
        self._next_yield = 0
        self._rcvd = {}
        self._exhausted = False
        self._shutdown_done = False
        for _ in range(self._prefetch):
            self._dispatch_one()

    def _dispatch_one(self):
        if self._exhausted:
            return
        try:
            bidx, indices = next(self._batches)
        except StopIteration:
            self._exhausted = True
            return
        self._index_queues[bidx % len(self._workers)].put((bidx, indices))
        self._sent += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self._next_yield >= self._sent and self._exhausted:
            self._shutdown()
            raise StopIteration
        while self._next_yield not in self._rcvd:
            try:
                bidx, samples, err = self._data_queue.get(timeout=5.0)
            except queue.Empty:
                # liveness check: a worker killed abnormally (OOM,
                # segfault) never posts its batch — hang-proof the wait
                # (reference dataloader_iter.py monitors worker death)
                dead = [w.pid for w in self._workers if not w.is_alive()]
                if dead:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} died "
                        "unexpectedly (killed?) — batch "
                        f"{self._next_yield} will never arrive")
                continue
            if err is not None:
                self._shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            self._rcvd[bidx] = samples
        samples = self._rcvd.pop(self._next_yield)
        self._next_yield += 1
        self._dispatch_one()
        return self._loader.collate_fn(samples)

    def _shutdown(self):
        if self._shutdown_done:
            return
        self._shutdown_done = True
        for q in self._index_queues:
            try:
                q.put(None)
            except Exception:
                pass
        for w in self._workers:
            w.join(timeout=5)
            if w.is_alive():
                w.terminate()

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


# incubate.autotune dataloader knobs (reference: incubate/autotune.py
# dataloader section — tune num_workers automatically)
AUTOTUNE_NUM_WORKERS = False
AUTOTUNE_STEPS = 500


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        if AUTOTUNE_NUM_WORKERS and num_workers == 0:
            import os

            # autotune heuristic: hide host preprocessing behind device
            # steps with a small worker pool bounded by core count
            num_workers = min(4, max((os.cpu_count() or 2) // 2, 1))
        self.num_workers = num_workers
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _gen(self):
        if self._iterable_mode:
            _worker_info.info = WorkerInfo(0, max(self.num_workers, 1), 0,
                                           self.dataset)
            batch = []
            for sample in self.dataset:
                if self.batch_size is None:
                    yield sample
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
            return
        for indices in self.batch_sampler:
            batch = [self.dataset[i] for i in indices]
            yield self.collate_fn(batch)

    def __iter__(self):
        if self.num_workers and self.num_workers > 0:
            # map-style + sampler → real multiprocess workers (the
            # reference's one-process-per-worker model); iterable-style
            # keeps the thread prefetcher (sample streams don't split
            # by index)
            if not self._iterable_mode and self.batch_sampler is not None:
                return _MultiprocessIter(self)
            return _PrefetchIter(self._gen,
                                 self.prefetch_factor * self.num_workers)
        return self._gen()

    def __call__(self):
        return self.__iter__()
