"""DataLoader.

TPU-native equivalent of the reference's DataLoader (reference:
python/paddle/io/dataloader/dataloader_iter.py — multiprocess workers +
blocking queue feeding the device). Here: collation to numpy on worker
threads with a bounded prefetch queue (keeping the TPU fed is a host-side
pipeline problem; heavy decode work can still use multiprocessing via
``num_workers``), final device transfer happens lazily at first use.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, seed, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack samples into batched Tensors (reference: collate.py)."""
    from ..core.tensor import Tensor

    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s._data for s in batch]))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(t)) for t in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class _PrefetchIter:
    """Background-thread prefetcher with a bounded queue."""

    def __init__(self, gen_fn, prefetch: int):
        self._q: queue.Queue = queue.Queue(maxsize=max(prefetch, 1))
        self._done = object()
        self._exc = None

        def run():
            try:
                for item in gen_fn():
                    self._q.put(item)
            except BaseException as e:  # surfaced on the consumer side
                self._exc = e
            finally:
                self._q.put(self._done)

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.num_workers = num_workers
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _gen(self):
        if self._iterable_mode:
            _worker_info.info = WorkerInfo(0, max(self.num_workers, 1), 0,
                                           self.dataset)
            batch = []
            for sample in self.dataset:
                if self.batch_size is None:
                    yield sample
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
            return
        for indices in self.batch_sampler:
            batch = [self.dataset[i] for i in indices]
            yield self.collate_fn(batch)

    def __iter__(self):
        if self.num_workers and self.num_workers > 0:
            return _PrefetchIter(self._gen,
                                 self.prefetch_factor * self.num_workers)
        return self._gen()

    def __call__(self):
        return self.__iter__()
