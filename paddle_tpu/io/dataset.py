"""Datasets (reference: python/paddle/io/dataset.py family)."""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__getitem__", self.__class__.__name__))

    def __len__(self):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__len__", self.__class__.__name__))


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__iter__", self.__class__.__name__))

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        lens = {t.shape[0] for t in tensors}
        assert len(lens) == 1, "tensors must share the batch dim"
        self.tensors = tensors

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: List[Dataset]):
        self.datasets = list(datasets)
        assert len({len(d) for d in self.datasets}) == 1

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            sample.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets: List[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets: Iterable[Dataset]):
        self.datasets = list(datasets)
        sizes = [len(d) for d in self.datasets]
        self.cumulative_sizes = np.cumsum(sizes).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(v, float) for v in lengths) and \
            abs(sum(lengths) - 1.0) < 1e-6:
        n = len(dataset)
        sizes = [int(np.floor(n * f)) for f in lengths]
        rem = n - sum(sizes)
        for i in range(rem):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    total = sum(lengths)
    assert total == len(dataset), "sum of lengths != dataset size"
    perm = np.random.permutation(total).tolist()
    out = []
    offset = 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n]))
        offset += n
    return out
