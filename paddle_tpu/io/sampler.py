"""Samplers (reference: python/paddle/io/dataloader/sampler.py,
batch_sampler.py — incl. DistributedBatchSampler for DP sharded loads)."""
from __future__ import annotations

import math

import numpy as np

__all__ = ["Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler",
           "SubsetRandomSampler"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None \
            else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            yield from np.random.randint(0, n, self.num_samples).tolist()
        else:
            yield from np.random.permutation(n)[: self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        super().__init__()
        self.indices = list(indices)

    def __iter__(self):
        perm = np.random.permutation(len(self.indices))
        return iter([self.indices[i] for i in perm])

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__()
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__()
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batches (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler).
    On TPU the same sampler feeds per-host loading for multi-host DP."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas or max(get_world_size(), 1)
            rank = rank if rank is not None else max(get_rank(), 0)
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n).tolist()
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        # pad to be divisible
        indices += indices[: (self.total_size - n)]
        # contiguous split per rank (reference behavior)
        indices = indices[self.local_rank * self.num_samples:
                          (self.local_rank + 1) * self.num_samples]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
