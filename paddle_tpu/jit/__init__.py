def to_static(function=None, **kwargs):
    """placeholder — replaced by full jit module."""
    def deco(fn):
        return fn
    return deco(function) if callable(function) else deco
