"""paddle_tpu.jit — mirrors python/paddle/jit (to_static, save, load) plus
the TPU-native whole-step compiler (TrainStep)."""
from .api import TranslatedLayer, load, save  # noqa: F401
from .static_function import (  # noqa: F401
    StaticFunction, not_to_static, to_static,
)
from .train_step import TrainStep  # noqa: F401

__all__ = ["to_static", "not_to_static", "save", "load", "StaticFunction",
           "TranslatedLayer", "TrainStep"]


def enable_to_static(flag: bool = True):
    StaticFunction._globally_enabled = bool(flag)


def ignore_module(modules):
    """SOT-compat no-op (we trace through everything)."""
    return None
